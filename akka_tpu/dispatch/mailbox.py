"""Mailboxes: per-actor message queue + scheduling status machine.

Reference parity: akka-actor/src/main/scala/akka/dispatch/Mailbox.scala —
status bitfield constants (:37-45), `run` (:227-237), the throughput-bounded
`processMailbox` loop (:260-277), `processAllSystemMessages` (:286-330), and
the pluggable mailbox types (:638-1036). The reference's Unsafe CAS on the
status word (dispatch/Mailbox.scala:115-138 via AbstractMailbox field offsets)
becomes an `AtomicInt` here; the optional C++ substrate (akka_tpu/native)
provides a lock-free MPSC queue for the user-message queue.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, NamedTuple, Optional, TYPE_CHECKING

from . import sysmsg
from ..actor.messages import DeadLetter, Dropped

if TYPE_CHECKING:  # pragma: no cover
    from .dispatcher import MessageDispatcher


class Envelope(NamedTuple):
    """A user message + its sender (reference: dispatch/AbstractDispatcher.scala:26-38)."""
    message: Any
    sender: Any


class AtomicInt:
    """CAS-able int. Stands in for sun.misc.Unsafe volatile/CAS field ops
    (reference: akka-actor/src/main/scala/akka/util/Unsafe.java:17-35)."""

    __slots__ = ("_value", "_lock")

    def __init__(self, value: int = 0):
        self._value = value
        self._lock = threading.Lock()

    def get(self) -> int:
        return self._value

    def set(self, value: int) -> None:
        with self._lock:
            self._value = value

    def compare_and_set(self, expect: int, update: int) -> bool:
        with self._lock:
            if self._value == expect:
                self._value = update
                return True
            return False

    def get_and_add(self, delta: int) -> int:
        with self._lock:
            v = self._value
            self._value = v + delta
            return v


# -- message queues --------------------------------------------------------

class MessageQueue:
    def enqueue(self, receiver: Any, handle: Envelope) -> None:
        raise NotImplementedError

    def dequeue(self) -> Optional[Envelope]:
        raise NotImplementedError

    @property
    def number_of_messages(self) -> int:
        raise NotImplementedError

    @property
    def has_messages(self) -> bool:
        return self.number_of_messages > 0

    def clean_up(self, owner: Any, dead_letters: "MessageQueue") -> None:
        while True:
            env = self.dequeue()
            if env is None:
                break
            dead_letters.enqueue(owner, env)


class UnboundedMessageQueue(MessageQueue):
    """MPSC unbounded FIFO (reference: UnboundedMailbox, dispatch/Mailbox.scala:647,
    backed by AbstractNodeQueue.java). collections.deque.append/popleft are
    atomic under the GIL, matching the lock-free reference queue's contract."""

    __slots__ = ("_q",)

    def __init__(self) -> None:
        self._q: deque = deque()

    def enqueue(self, receiver: Any, handle: Envelope) -> None:
        self._q.append(handle)

    def dequeue(self) -> Optional[Envelope]:
        try:
            return self._q.popleft()
        except IndexError:
            return None

    @property
    def number_of_messages(self) -> int:
        return len(self._q)


class BoundedMessageQueue(MessageQueue):
    """Blocking bounded queue; on push-timeout the envelope goes to dead
    letters (reference: BoundedMailbox, dispatch/Mailbox.scala:699-726)."""

    __slots__ = ("_q", "capacity", "push_timeout", "_not_full", "_owner_system")

    def __init__(self, capacity: int, push_timeout: float) -> None:
        self._q: deque = deque()
        self.capacity = capacity
        self.push_timeout = push_timeout
        self._not_full = threading.Condition()

    def enqueue(self, receiver: Any, handle: Envelope) -> None:
        with self._not_full:
            if len(self._q) >= self.capacity:
                ok = self._not_full.wait_for(
                    lambda: len(self._q) < self.capacity,
                    timeout=self.push_timeout if self.push_timeout != float("inf") else None)
                if not ok:
                    system = getattr(receiver, "_system", None) or getattr(getattr(receiver, "provider", None), "system", None)
                    if system is not None:
                        system.dead_letters.tell(
                            DeadLetter(handle.message, handle.sender, receiver), handle.sender)
                    return
            self._q.append(handle)

    def dequeue(self) -> Optional[Envelope]:
        with self._not_full:
            if not self._q:
                return None
            env = self._q.popleft()
            self._not_full.notify()
            return env

    @property
    def number_of_messages(self) -> int:
        return len(self._q)


class NonBlockingBoundedMessageQueue(MessageQueue):
    """Drops to dead letters when full, never blocks the sender
    (reference: NonBlockingBoundedMailbox, dispatch/Mailbox.scala:684-697)."""

    __slots__ = ("_q", "capacity")

    def __init__(self, capacity: int) -> None:
        self._q: deque = deque()
        self.capacity = capacity

    def enqueue(self, receiver: Any, handle: Envelope) -> None:
        if len(self._q) >= self.capacity:
            system = getattr(receiver, "_system", None)
            if system is not None:
                system.dead_letters.tell(
                    DeadLetter(handle.message, handle.sender, receiver), handle.sender)
            return
        self._q.append(handle)

    def dequeue(self) -> Optional[Envelope]:
        try:
            return self._q.popleft()
        except IndexError:
            return None

    @property
    def number_of_messages(self) -> int:
        return len(self._q)


class PriorityMessageQueue(MessageQueue):
    """Unbounded priority queue; `stable` keeps FIFO order among equal
    priorities (reference: UnboundedPriorityMailbox :764 /
    UnboundedStablePriorityMailbox :795)."""

    __slots__ = ("_heap", "_counter", "_prio", "_lock")

    def __init__(self, priority: Callable[[Any], int], stable: bool = True) -> None:
        self._heap: list = []
        self._counter = itertools.count()
        self._prio = priority
        self._lock = threading.Lock()

    def enqueue(self, receiver: Any, handle: Envelope) -> None:
        with self._lock:
            heapq.heappush(self._heap, (self._prio(handle.message), next(self._counter), handle))

    def dequeue(self) -> Optional[Envelope]:
        with self._lock:
            if not self._heap:
                return None
            return heapq.heappop(self._heap)[2]

    @property
    def number_of_messages(self) -> int:
        return len(self._heap)


class ControlMessage:
    """Marker: jumps the queue in a ControlAwareMessageQueue
    (reference: ControlAwareMessageQueueSemantics, dispatch/Mailbox.scala:881-920)."""
    __slots__ = ()


class ControlAwareMessageQueue(MessageQueue):
    __slots__ = ("_control", "_ordinary")

    def __init__(self) -> None:
        self._control: deque = deque()
        self._ordinary: deque = deque()

    def enqueue(self, receiver: Any, handle: Envelope) -> None:
        if isinstance(handle.message, ControlMessage):
            self._control.append(handle)
        else:
            self._ordinary.append(handle)

    def dequeue(self) -> Optional[Envelope]:
        try:
            return self._control.popleft()
        except IndexError:
            try:
                return self._ordinary.popleft()
            except IndexError:
                return None

    @property
    def number_of_messages(self) -> int:
        return len(self._control) + len(self._ordinary)


class DequeBasedMessageQueue(UnboundedMessageQueue):
    """Supports enqueue_first for Stash unstashing
    (reference: UnboundedDequeBasedMailbox, dispatch/Mailbox.scala:838)."""

    def enqueue_first(self, receiver: Any, handle: Envelope) -> None:
        self._q.appendleft(handle)


# -- requirement markers (reference: RequiresMessageQueue, Mailbox.scala:1036) --

class RequiresMessageQueue:
    """Actor classes may set `mailbox_requirement` to a MessageQueue marker
    class; Mailboxes.lookup honors it."""
    mailbox_requirement: Optional[type] = None


# -- the mailbox itself ----------------------------------------------------

# Status bitfield (reference: dispatch/Mailbox.scala:37-45)
OPEN = 0
CLOSED = 1
SCHEDULED = 2
SHOULD_SCHEDULE_MASK = 3
SHOULD_NOT_PROCESS_MASK = ~2 & 0xFFFFFFFF
SUSPEND_MASK = ~3 & 0xFFFFFFFF
SUSPEND_UNIT = 4


class Mailbox:
    """Binds an actor cell to a message queue, runs as a task on the
    dispatcher's executor. One `run` processes all system messages then up to
    `throughput` user messages (reference: dispatch/Mailbox.scala:227-277)."""

    __slots__ = ("message_queue", "actor", "dispatcher", "_status", "_sysq", "_sysq_lock")

    def __init__(self, message_queue: MessageQueue):
        self.message_queue = message_queue
        self.actor = None          # ActorCell, set by Dispatch.init
        self.dispatcher: Optional["MessageDispatcher"] = None
        self._status = AtomicInt(OPEN)
        self._sysq: deque = deque()
        self._sysq_lock = threading.Lock()

    # -- status machine (reference: Mailbox.scala:96-225) -------------------
    @property
    def status(self) -> int:
        return self._status.get()

    def should_process_message(self) -> bool:
        return (self.status & SHOULD_NOT_PROCESS_MASK) == 0

    def suspend_count(self) -> int:
        return self.status // SUSPEND_UNIT

    def is_suspended(self) -> bool:
        return (self.status & SUSPEND_MASK) != 0

    def is_closed(self) -> bool:
        return self.status == CLOSED

    def is_scheduled(self) -> bool:
        return (self.status & SCHEDULED) != 0

    def suspend(self) -> bool:
        """Increment suspend count; True if transitioned from not-suspended."""
        while True:
            s = self.status
            if s == CLOSED:
                return False
            if self._status.compare_and_set(s, s + SUSPEND_UNIT):
                return s < SUSPEND_UNIT

    def resume(self) -> bool:
        """Decrement suspend count; True if now fully resumed."""
        while True:
            s = self.status
            if s == CLOSED:
                return False
            next_s = s if s < SUSPEND_UNIT else s - SUSPEND_UNIT
            if self._status.compare_and_set(s, next_s):
                return next_s < SUSPEND_UNIT

    def become_closed(self) -> bool:
        while True:
            s = self.status
            if s == CLOSED:
                return False
            if self._status.compare_and_set(s, CLOSED):
                return True

    def set_as_scheduled(self) -> bool:
        while True:
            s = self.status
            if (s & SHOULD_SCHEDULE_MASK) != OPEN:
                return False
            if self._status.compare_and_set(s, s | SCHEDULED):
                return True

    def set_as_idle(self) -> bool:
        while True:
            s = self.status
            if self._status.compare_and_set(s, s & ~SCHEDULED if s != CLOSED else CLOSED):
                return True

    def can_be_scheduled_for_execution(self, has_message_hint: bool, has_system_message_hint: bool) -> bool:
        s = self.status
        if s in (OPEN, SCHEDULED):
            return has_message_hint or has_system_message_hint or self.has_system_messages or self.has_messages
        if s == CLOSED:
            return False
        return has_system_message_hint or self.has_system_messages

    # -- queues ------------------------------------------------------------
    def enqueue(self, receiver: Any, envelope: Envelope) -> None:
        self.message_queue.enqueue(receiver, envelope)

    def dequeue(self) -> Optional[Envelope]:
        return self.message_queue.dequeue()

    @property
    def has_messages(self) -> bool:
        return self.message_queue.has_messages

    @property
    def number_of_messages(self) -> int:
        return self.message_queue.number_of_messages

    def system_enqueue(self, receiver: Any, message: sysmsg.SystemMessage) -> None:
        """MPSC system queue (reference: Mailbox.scala:467-497)."""
        with self._sysq_lock:
            if self.is_closed():
                closed = True
            else:
                self._sysq.append(message)
                closed = False
        if closed:
            system = getattr(receiver, "_system", None)
            if system is not None:
                system.dead_letters.tell(DeadLetter(message, receiver, receiver), receiver)

    def system_drain(self) -> list:
        with self._sysq_lock:
            msgs = list(self._sysq)
            self._sysq.clear()
            return msgs

    @property
    def has_system_messages(self) -> bool:
        return len(self._sysq) > 0

    # -- execution (reference: Mailbox.scala:227-330) -----------------------
    def run(self) -> None:
        try:
            if not self.is_closed():
                self.process_all_system_messages()
                self.process_mailbox()
        finally:
            self.set_as_idle()
            if self.dispatcher is not None:
                self.dispatcher.register_for_execution(self, False, False)

    def process_all_system_messages(self) -> None:
        while self.has_system_messages and not self.is_closed():
            for msg in self.system_drain():
                self.actor.system_invoke(msg)

    def process_mailbox(self) -> None:
        left = self.dispatcher.throughput if self.dispatcher else 1
        deadline = (time.monotonic() + self.dispatcher.throughput_deadline
                    if self.dispatcher and self.dispatcher.throughput_deadline > 0 else 0.0)
        while left > 0 and self.should_process_message():
            env = self.dequeue()
            if env is None:
                return
            self.actor.invoke(env)
            if self.has_system_messages:
                self.process_all_system_messages()
            left -= 1
            if deadline and time.monotonic() >= deadline:
                return

    def clean_up(self) -> None:
        """Move remaining messages to dead letters after close, then let the
        queue release its backing resources via the MessageQueue.clean_up SPI
        (reference: Mailbox.scala:332-360 delegating to
        messageQueue.cleanUp(actor.self, deadLetterMailbox.messageQueue))."""
        if self.actor is None:
            return
        system = self.actor.system
        dl = system.dead_letters
        for msg in self.system_drain():
            dl.tell(msg, self.actor.self_ref)
        self.message_queue.clean_up(
            self.actor.self_ref, _DeadLetterSink(dl, self.actor.self_ref))


class _DeadLetterSink(MessageQueue):
    """Adapter presenting the dead-letters ActorRef as the MessageQueue that
    MessageQueue.clean_up drains into (the deadLetterMailbox.messageQueue
    role in the reference)."""

    __slots__ = ("_dl", "_owner")

    def __init__(self, dead_letters_ref: Any, owner_ref: Any) -> None:
        self._dl = dead_letters_ref
        self._owner = owner_ref

    def enqueue(self, receiver: Any, handle: Envelope) -> None:
        self._dl.tell(DeadLetter(handle.message, handle.sender, self._owner),
                      handle.sender)

    def dequeue(self) -> Optional[Envelope]:
        return None

    @property
    def number_of_messages(self) -> int:
        return 0


# -- mailbox type registry (reference: dispatch/Mailboxes.scala:91) ---------

class MailboxType:
    """Factory for message queues."""

    def create(self, owner: Any, system: Any) -> MessageQueue:
        raise NotImplementedError


class UnboundedMailbox(MailboxType):
    def create(self, owner, system) -> MessageQueue:
        return UnboundedMessageQueue()


class BoundedMailbox(MailboxType):
    def __init__(self, capacity: int, push_timeout: float = 10.0):
        self.capacity = capacity
        self.push_timeout = push_timeout

    def create(self, owner, system) -> MessageQueue:
        return BoundedMessageQueue(self.capacity, self.push_timeout)


class NonBlockingBoundedMailbox(MailboxType):
    def __init__(self, capacity: int):
        self.capacity = capacity

    def create(self, owner, system) -> MessageQueue:
        return NonBlockingBoundedMessageQueue(self.capacity)


class UnboundedPriorityMailbox(MailboxType):
    def __init__(self, priority: Callable[[Any], int], stable: bool = True):
        self.priority = priority
        self.stable = stable

    def create(self, owner, system) -> MessageQueue:
        return PriorityMessageQueue(self.priority, self.stable)


class UnboundedControlAwareMailbox(MailboxType):
    def create(self, owner, system) -> MessageQueue:
        return ControlAwareMessageQueue()


class UnboundedDequeBasedMailbox(MailboxType):
    def create(self, owner, system) -> MessageQueue:
        return DequeBasedMessageQueue()


class Mailboxes:
    """Mailbox-type lookup from config path or requirement
    (reference: dispatch/Mailboxes.scala)."""

    def __init__(self, settings, event_stream):
        self.settings = settings
        self.event_stream = event_stream
        self._types: dict[str, MailboxType] = {
            "unbounded": UnboundedMailbox(),
            "unbounded-deque-based": UnboundedDequeBasedMailbox(),
            "unbounded-control-aware": UnboundedControlAwareMailbox(),
        }

    def register(self, name: str, mailbox_type: MailboxType) -> None:
        self._types[name] = mailbox_type

    def lookup(self, name: str) -> MailboxType:
        if name in self._types:
            return self._types[name]
        cfg = self.settings.config.get_config(name) if self.settings.config.has_path(name) else None
        if cfg is not None and cfg.has_path("mailbox-type"):
            return self.from_config(cfg)
        raise KeyError(f"unknown mailbox type: {name}")

    def from_config(self, cfg) -> MailboxType:
        mt = cfg.get_string("mailbox-type", "unbounded")
        if mt in self._types:
            return self._types[mt]
        if mt == "bounded":
            return BoundedMailbox(cfg.get_int("mailbox-capacity", 1000),
                                  cfg.get_duration("mailbox-push-timeout-time", "10s"))
        raise KeyError(f"unknown mailbox-type: {mt}")

    def default_mailbox(self) -> MailboxType:
        return self._types["unbounded"]

    def for_props(self, props) -> MailboxType:
        if props.mailbox is not None:
            if isinstance(props.mailbox, MailboxType):
                return props.mailbox
            return self.lookup(props.mailbox)
        req = getattr(props.actor_class(), "mailbox_requirement", None) if props.actor_class() else None
        if req is DequeBasedMessageQueue:
            return self._types["unbounded-deque-based"]
        if req is ControlAwareMessageQueue:
            return self._types["unbounded-control-aware"]
        return self.default_mailbox()
