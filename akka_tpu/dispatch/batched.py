"""The `tpu-batched` dispatcher type — the BASELINE north-star seam.

Reference parity: the MessageDispatcherConfigurator / Dispatchers extension
point (dispatch/Dispatchers.scala:235-259, registerConfigurator :184-185)
gates the backend, so `akka.actor.default-dispatcher.type = tpu-batched` (or a
dedicated `akka.actor.tpu-dispatcher` id) selects this dispatcher.

Semantics: ordinary Python actors attached to this dispatcher still execute on
a host thread pool (they are the control plane / IO edge), but the dispatcher
owns a device-resident BatchedSystem; actors whose Props carry a
BatchedBehavior are laid out as rows in the SoA slabs and stepped on-device.
`BatchedRuntimeHandle.tell` bridges host refs into the device inbox (the
slow-lane equivalent of Artery's large-message lane)."""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from .dispatcher import Dispatcher, DispatcherConfigurator


class TpuBatchedDispatcher(Dispatcher):
    """Host-facing dispatcher + owner of the device BatchedSystem."""

    def __init__(self, dispatchers, id: str, config):
        super().__init__(dispatchers, id,
                         throughput=config.get_int("throughput", 64),
                         shutdown_timeout=config.get_duration("shutdown-timeout", "1s"))
        self._config = config
        self._runtime = None
        self._runtime_lock = threading.Lock()

    def runtime(self, behaviors=None, **overrides):
        """Get (or lazily build) the BatchedSystem for this dispatcher.
        First caller supplies the behavior list; later callers share it."""
        with self._runtime_lock:
            if self._runtime is None:
                if behaviors is None:
                    raise ValueError(
                        "tpu-batched runtime not initialized: first call must "
                        "pass behaviors=[BatchedBehavior, ...]")
                from ..batched.core import BatchedSystem
                c = self._config
                self._runtime = BatchedSystem(
                    capacity=overrides.get("capacity", c.get_int("capacity", 1 << 20)),
                    behaviors=behaviors,
                    payload_width=overrides.get("payload_width", c.get_int("payload-width", 8)),
                    out_degree=overrides.get("out_degree", c.get_int("out-degree", 1)),
                    host_inbox=overrides.get("host_inbox", c.get_int("host-inbox", 1024)),
                )
            return self._runtime

    @property
    def has_runtime(self) -> bool:
        return self._runtime is not None


class TpuBatchedDispatcherConfigurator(DispatcherConfigurator):
    def __init__(self, config, dispatchers, id: str):
        super().__init__(config, dispatchers)
        self.id = id
        self._instance: Optional[TpuBatchedDispatcher] = None
        self._lock = threading.Lock()

    def dispatcher(self) -> TpuBatchedDispatcher:
        with self._lock:
            if self._instance is None:
                self._instance = TpuBatchedDispatcher(self.dispatchers, self.id, self.config)
            return self._instance


def register_tpu_dispatcher_type(dispatchers) -> None:
    """Called from ActorSystem bootstrap (actor/system.py)."""
    dispatchers.register_type("tpu-batched", TpuBatchedDispatcherConfigurator)
