"""The `tpu-batched` dispatcher type — the BASELINE north-star seam.

Reference parity: the MessageDispatcherConfigurator / Dispatchers extension
point (dispatch/Dispatchers.scala:235-259, registerConfigurator :184-185)
gates the backend, so `akka.actor.default-dispatcher.type = tpu-batched` (or
the dedicated `akka.actor.tpu-dispatcher` id) selects this dispatcher.

Semantics: ordinary Python actors attached to this dispatcher still execute on
a host thread pool (they are the control plane / IO edge), but the dispatcher
owns a BatchedRuntimeHandle (akka_tpu/batched/bridge.py); actors whose Props
carry a DeviceSpec are laid out as rows in the SoA slabs and stepped
on-device, with `ref.tell` staged through the native stager and `ask`
completed via promise rows — the full ActorRef.! → receive stack of
SURVEY.md §3.2 replaced by one jitted step."""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from .dispatcher import Dispatcher, DispatcherConfigurator


class TpuBatchedDispatcher(Dispatcher):
    """Host-facing dispatcher + owner of the device runtime handle."""

    def __init__(self, dispatchers, id: str, config):
        super().__init__(dispatchers, id,
                         throughput=config.get_int("throughput", 64),
                         shutdown_timeout=config.get_duration("shutdown-timeout", "1s"))
        self._config = config
        self._handle = None
        self._runtime_lock = threading.Lock()

    def handle(self, system=None, **overrides):
        """Get (or lazily build) the BatchedRuntimeHandle."""
        with self._runtime_lock:
            if self._handle is None:
                from ..batched.bridge import BatchedRuntimeHandle
                c = self._config
                self._handle = BatchedRuntimeHandle(
                    capacity=overrides.get("capacity", c.get_int("capacity", 1 << 20)),
                    payload_width=overrides.get(
                        "payload_width", c.get_int("payload-width", 8)),
                    out_degree=overrides.get(
                        "out_degree", c.get_int("out-degree", 1)),
                    host_inbox=overrides.get(
                        "host_inbox", c.get_int("host-inbox", 4096)),
                    mailbox_slots=overrides.get(
                        "mailbox_slots", c.get_int("mailbox-slots", 0)),
                    promise_rows=overrides.get(
                        "promise_rows", c.get_int("promise-rows", 256)),
                    auto_step_interval=c.get_duration(
                        "auto-step-interval", "1ms"),
                    event_stream=getattr(system, "event_stream", None),
                    flight_recorder=getattr(system, "flight_recorder", None),
                    failure_policy=c.get_string("failure-policy", "restart"),
                    pipeline_depth=overrides.get(
                        "pipeline_depth", c.get_int("pipeline-depth", 2)),
                    checkpoint_interval_steps=overrides.get(
                        "checkpoint_interval_steps",
                        c.get_int("checkpoint-interval-steps", 0)),
                    checkpoint_dir=overrides.get(
                        "checkpoint_dir",
                        c.get_string("checkpoint-dir", "") or None),
                    checkpoint_keep=overrides.get(
                        "checkpoint_keep", c.get_int("checkpoint-keep", 3)),
                    # WAL group commit: the system-wide
                    # akka.persistence.tell-journal.fsync-every-n key (or a
                    # per-dispatcher wal-fsync-every-n / override) batches
                    # journal fsyncs; 1 = per-record, bit-identical
                    wal_fsync_every_n=overrides.get(
                        "wal_fsync_every_n",
                        c.get_int(
                            "wal-fsync-every-n",
                            getattr(system, "settings", None) and
                            system.settings.config.get_int(
                                "akka.persistence.tell-journal."
                                "fsync-every-n", 1) or 1)),
                    sentinel_threshold=overrides.get(
                        "sentinel_threshold",
                        c.get_float("sentinel-threshold", 8.0)),
                    sentinel_heartbeat_interval=overrides.get(
                        "sentinel_heartbeat_interval",
                        c.get_duration("sentinel-heartbeat-interval",
                                       "100ms")),
                    sentinel_acceptable_pause=overrides.get(
                        "sentinel_acceptable_pause",
                        c.get_duration("sentinel-acceptable-pause", "3s")),
                    sentinel_max_failovers=overrides.get(
                        "sentinel_max_failovers",
                        c.get_int("sentinel-max-failovers", 3)),
                    sentinel_depth_recovery_rounds=overrides.get(
                        "sentinel_depth_recovery_rounds",
                        c.get_int("sentinel-depth-recovery-rounds", 64)),
                    # telemetry plane: the system-level akka.metrics.enabled
                    # switch (or an explicit override) compiles the device
                    # metric slab in; the system-owned registry is shared
                    # so every dispatcher's collectors land in one plane
                    metrics_enabled=overrides.get(
                        "metrics_enabled",
                        c.get_bool("metrics-enabled", False) or
                        getattr(system, "metrics_registry", None)
                        is not None),
                    metrics_registry=overrides.get(
                        "metrics_registry",
                        getattr(system, "metrics_registry", None)),
                )
            return self._handle

    def runtime(self, behaviors=None, **overrides):
        """Back-compat: the raw BatchedSystem (builds the handle; registers
        any passed behaviors)."""
        h = self.handle(**overrides)
        for b in behaviors or ():
            h._behavior_index(b)
        return h.runtime

    @property
    def has_runtime(self) -> bool:
        return self._handle is not None and self._handle._runtime is not None

    def shutdown(self) -> None:
        if self._handle is not None:
            self._handle.shutdown()
        super().shutdown()


class TpuBatchedDispatcherConfigurator(DispatcherConfigurator):
    def __init__(self, config, dispatchers, id: str):
        super().__init__(config, dispatchers)
        self.id = id
        self._instance: Optional[TpuBatchedDispatcher] = None
        self._lock = threading.Lock()

    def dispatcher(self) -> TpuBatchedDispatcher:
        with self._lock:
            if self._instance is None:
                self._instance = TpuBatchedDispatcher(self.dispatchers, self.id, self.config)
            return self._instance


def register_tpu_dispatcher_type(dispatchers) -> None:
    """Called from ActorSystem bootstrap (actor/system.py)."""
    dispatchers.register_type("tpu-batched", TpuBatchedDispatcherConfigurator)
