"""RoutedActorRef / RoutedActorCell: messages bypass the router's mailbox.

Reference parity: routing/RoutedActorCell.scala:137-141 (sendMessage routes
directly on the caller's thread), RouterActor (manages routees + resizer),
RouterPoolActor supervision of pool routees.
"""

from __future__ import annotations

from typing import Any, Optional

from ..actor.actor import Actor
from ..actor.cell import ActorCell
from ..actor.messages import PoisonPill, Terminated
from ..actor.props import Props
from ..actor.ref import ActorRef, LocalActorRef
from ..actor.supervision import default_strategy
from ..dispatch.mailbox import Envelope
from .router import (ActorRefRoutee, AddRoutee, AdjustPoolSize, Broadcast,
                     GetRoutees, RemoveRoutee, Routees, Router,
                     RouterManagementMessage)


class RouterActor(Actor):
    """The actor living at the router ref: handles management messages and
    watches routees (reference: routing/RouterActor.scala)."""

    def __init__(self, router_config):
        super().__init__()
        self.router_config = router_config
        self._message_counter = 0

    @property
    def supervisor_strategy(self):
        return self.router_config.supervisor_strategy or default_strategy()

    @property
    def _rcell(self) -> "RoutedActorCell":
        return self.context  # type: ignore[return-value]

    def pre_start(self) -> None:
        # routees are created synchronously by RoutedActorCell.init (the
        # reference does this in RoutedActorCell's constructor so no message
        # can arrive before the routees exist); watch them here
        for r in self._rcell.router.routees:
            ref = getattr(r, "ref", None)
            if ref is not None:
                self.context.watch(ref)

    def _spawn_routee(self) -> None:
        cell = self._rcell
        child = cell.actor_of(cell.routee_props)
        cell.watch(child)
        cell.router.add_routee(ActorRefRoutee(child))

    def receive(self, message: Any):
        cell = self._rcell
        if isinstance(message, GetRoutees):
            self.sender.tell(Routees(tuple(cell.router.routees)), self.self_ref)
        elif isinstance(message, AddRoutee):
            cell.router.add_routee(message.routee)
        elif isinstance(message, RemoveRoutee):
            cell.router.remove_routee(message.routee)
            ref = getattr(message.routee, "ref", None)
            if ref is not None:
                self.context.unwatch(ref)
                ref.tell(PoisonPill)
        elif isinstance(message, AdjustPoolSize):
            if message.change > 0:
                for _ in range(message.change):
                    self._spawn_routee()
            else:
                for _ in range(-message.change):
                    if cell.router.routees:
                        r = cell.router.routees[-1]
                        cell.router.remove_routee(r)
                        ref = getattr(r, "ref", None)
                        if ref is not None:
                            ref.tell(PoisonPill)
        elif isinstance(message, Terminated):
            cell.router.routees = [
                r for r in cell.router.routees
                if getattr(r, "ref", None) != message.actor]
            if not self.router_config.is_group and not cell.is_terminating:
                # pool keeps its size (reference: RouterPoolActor supervision)
                if len(cell.router.routees) < self.router_config.nr_of_instances:
                    self._spawn_routee()
        else:
            return NotImplemented
        return None

    def maybe_resize(self) -> None:
        resizer = self.router_config.resizer
        if resizer is None:
            return
        self._message_counter += 1
        if resizer.is_time_for_resize(self._message_counter):
            change = resizer.resize(self._rcell.router.routees)
            if change:
                self.self_ref.tell(AdjustPoolSize(change))


class RoutedActorCell(ActorCell):
    def __init__(self, system, self_ref, props: Props, dispatcher_id, parent):
        # the cell's own actor is the RouterActor; routees use the user props
        from dataclasses import replace
        router_config = props.router_config
        self.routee_props = replace(props, router_config=None, deploy=None,
                                    device=None)
        # cluster-aware configs supply their own router actor (cluster/
        # routing.py; reference: ClusterRouterActor in cluster/routing/)
        actor_cls = getattr(router_config, "router_actor_class", RouterActor)
        router_actor_props = Props.create(actor_cls, router_config)
        super().__init__(system, self_ref, router_actor_props, dispatcher_id, parent)
        self.router: Router = router_config.create_router(system)
        self.router_config = router_config

    def init(self, send_supervise: bool, mailbox_type) -> None:
        super().init(send_supervise, mailbox_type)
        # populate routees synchronously before any message can be routed
        cfg = self.router_config
        if cfg.is_group:
            from .router import ActorSelectionRoutee
            for path in cfg.paths:
                self.router.add_routee(ActorSelectionRoutee(path, self.system))
        else:
            for _ in range(max(cfg.nr_of_instances, 0)):
                child = self.actor_of(self.routee_props)
                self.router.add_routee(ActorRefRoutee(child))

    def send_message(self, envelope: Envelope) -> None:
        """Route on the caller's thread, bypassing our mailbox
        (reference: RoutedActorCell.sendMessage :137-141)."""
        msg = envelope.message
        from ..actor.messages import AutoReceivedMessage
        if isinstance(msg, (RouterManagementMessage, AutoReceivedMessage)):
            super().send_message(envelope)
            return
        if isinstance(self.actor, RouterActor):
            self.actor.maybe_resize()
        self.router.route(msg, envelope.sender)


class RoutedActorRef(LocalActorRef):
    def __init__(self, system, props, dispatcher_id, parent, path):
        from ..actor.ref import InternalActorRef  # noqa: F401
        self.path = path
        self._system = system
        self.cell = RoutedActorCell(system, self, props, dispatcher_id, parent)
