"""Routing logics as index maps — the batched/device tier.

SURVEY.md §2.11: RoundRobin = iota mod n; Random = hashed counter;
ConsistentHash = hash tensor mod n. These produce destination-id tensors
consumed by BatchedBehavior emissions, so a 100k-routee RoundRobinPool routes
entirely on device (BASELINE config 4).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def round_robin_dst(n_messages: int, routee_base: int, n_routees: int,
                    offset=0) -> jax.Array:
    """Destination ids for n_messages round-robin over routees
    [routee_base, routee_base + n_routees)."""
    return routee_base + (jnp.arange(n_messages, dtype=jnp.int32) + offset) % n_routees


def random_dst(key: jax.Array, n_messages: int, routee_base: int,
               n_routees: int) -> jax.Array:
    return routee_base + jax.random.randint(key, (n_messages,), 0, n_routees, jnp.int32)


def _fnv1a(x: jax.Array) -> jax.Array:
    """Vectorized 32-bit FNV-1a-style mix of int32 keys (device-side stand-in
    for the reference's MurmurHash, routing/MurmurHash.scala)."""
    x = x.astype(jnp.uint32)
    h = jnp.uint32(2166136261)
    for shift in (0, 8, 16, 24):
        byte = (x >> shift) & jnp.uint32(0xFF)
        h = (h ^ byte) * jnp.uint32(16777619)
    return h


def consistent_hash_dst(keys: jax.Array, routee_base: int, n_routees: int) -> jax.Array:
    """Map int32 hash keys to stable routee destinations."""
    return routee_base + (_fnv1a(keys) % jnp.uint32(n_routees)).astype(jnp.int32)


def broadcast_dst(n_routees: int, routee_base: int) -> jax.Array:
    """All routees (use with out_degree = n_routees emissions)."""
    return routee_base + jnp.arange(n_routees, dtype=jnp.int32)


class BatchedRouter:
    """Router-as-index-map: the device-tier `Router.route` seam
    (routing/Router.scala:116 — fan-out WITHOUT going through a router
    mailbox, here without leaving the vmapped step at all).

    `route(key, step)` is scalar JAX, so behaviors call it under vmap to
    compute one message's routee row; the logic string mirrors the pool
    types of the reference (RoundRobinPool / RandomPool /
    ConsistentHashingPool, routing/RoundRobinRoutingLogic et al.).
    RoundRobin keys on (sender, step) so each producer's successive
    messages walk successive routees, exactly the classic pool contract
    per sender.
    """

    LOGICS = ("round-robin", "random", "consistent-hash")

    def __init__(self, logic: str, routee_base: int, n_routees: int):
        if logic not in self.LOGICS:
            raise ValueError(f"unknown routing logic {logic!r}; "
                             f"one of {self.LOGICS}")
        if n_routees <= 0:
            raise ValueError("n_routees must be > 0")
        self.logic = logic
        self.routee_base = routee_base
        self.n_routees = n_routees

    def route(self, key, step=0) -> jax.Array:
        """Routee row for one message. `key` identifies the sender (or the
        hash key for consistent-hash); `step` advances round-robin state."""
        key = jnp.asarray(key, jnp.int32)
        step = jnp.asarray(step, jnp.int32)
        if self.logic == "round-robin":
            idx = (key + step) % self.n_routees
        elif self.logic == "random":
            # Knuth multiplicative constant exceeds int32: mix in uint32
            mixed = (key.astype(jnp.uint32) * jnp.uint32(2654435761)
                     + step.astype(jnp.uint32))
            idx = (_fnv1a(mixed.astype(jnp.int32))
                   % jnp.uint32(self.n_routees)).astype(jnp.int32)
        else:  # consistent-hash: stable in `key`, step-independent
            idx = (_fnv1a(key) % jnp.uint32(self.n_routees)).astype(jnp.int32)
        return self.routee_base + idx
