"""Routing: fan-out without the router's mailbox on the hot path.

Reference parity: akka-actor/src/main/scala/akka/routing/ —
`RoutedActorCell.sendMessage` routes directly (routing/RoutedActorCell.scala:137-141),
`Router.route` (routing/Router.scala:116), logics RoundRobin/Random/Broadcast/
SmallestMailbox/ConsistentHashing (murmur hash, routing/MurmurHash.scala)/
ScatterGatherFirstCompleted/TailChopping, Pool vs Group, Resizer, and the
management messages (GetRoutees/AddRoutee/RemoveRoutee/AdjustPoolSize).

The batched analogue — routing logics as index-permutation tensors — lives in
akka_tpu/routing/batched.py (SURVEY.md §2.11).
"""

from __future__ import annotations

import hashlib
import itertools
import random as _random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

from ..actor.messages import PoisonPill
from ..actor.props import Props
from ..actor.ref import ActorRef, Nobody
from ..dispatch.mailbox import Envelope


# -- routees ----------------------------------------------------------------

class Routee:
    def send(self, message: Any, sender: Optional[ActorRef]) -> None:
        raise NotImplementedError


@dataclass(frozen=True)
class ActorRefRoutee(Routee):
    ref: ActorRef

    def send(self, message, sender) -> None:
        self.ref.tell(message, sender)


@dataclass(frozen=True)
class ActorSelectionRoutee(Routee):
    path: str
    system: Any = None

    def send(self, message, sender) -> None:
        self.system.actor_selection(self.path).tell(message, sender)


class NoRoutee(Routee):
    def send(self, message, sender) -> None:
        pass


@dataclass(frozen=True)
class SeveralRoutees(Routee):
    routees: tuple

    def send(self, message, sender) -> None:
        for r in self.routees:
            r.send(message, sender)


# -- management messages (reference: routing/RouterManagementMesssage.scala) --

class RouterManagementMessage:
    __slots__ = ()


class GetRoutees(RouterManagementMessage):
    pass


@dataclass(frozen=True)
class Routees:
    routees: tuple


@dataclass(frozen=True)
class AddRoutee(RouterManagementMessage):
    routee: Routee


@dataclass(frozen=True)
class RemoveRoutee(RouterManagementMessage):
    routee: Routee


@dataclass(frozen=True)
class AdjustPoolSize(RouterManagementMessage):
    change: int


@dataclass(frozen=True)
class Broadcast:
    """Envelope: send the inner message to ALL routees (reference: routing/Broadcast)."""
    message: Any


# -- routing logics ----------------------------------------------------------

class RoutingLogic:
    def select(self, message: Any, routees: Sequence[Routee]) -> Routee:
        raise NotImplementedError


class RoundRobinRoutingLogic(RoutingLogic):
    def __init__(self):
        self._next = itertools.count()

    def select(self, message, routees):
        if not routees:
            return NoRoutee()
        return routees[next(self._next) % len(routees)]


class RandomRoutingLogic(RoutingLogic):
    def select(self, message, routees):
        if not routees:
            return NoRoutee()
        return routees[_random.randrange(len(routees))]


class BroadcastRoutingLogic(RoutingLogic):
    def select(self, message, routees):
        return SeveralRoutees(tuple(routees))


class SmallestMailboxRoutingLogic(RoutingLogic):
    """(reference: routing/SmallestMailbox.scala — prefers idle/empty mailboxes)"""

    def select(self, message, routees):
        if not routees:
            return NoRoutee()
        best, best_size = None, None
        for r in routees:
            size = 0
            ref = getattr(r, "ref", None)
            cell = getattr(ref, "cell", None)
            if cell is not None and cell.mailbox is not None:
                size = cell.mailbox.number_of_messages
            if best is None or size < best_size:
                best, best_size = r, size
        return best


def _hash_key(key: Any) -> int:
    h = hashlib.md5(repr(key).encode()).digest()
    return int.from_bytes(h[:8], "little")


class ConsistentHashingRoutingLogic(RoutingLogic):
    """Consistent-hash ring with virtual nodes (reference:
    routing/ConsistentHashingRouter.scala + ConsistentHash.scala)."""

    def __init__(self, hash_mapping: Optional[Callable[[Any], Any]] = None,
                 virtual_nodes_factor: int = 17):
        self.hash_mapping = hash_mapping
        self.vnodes = virtual_nodes_factor
        self._ring_cache: tuple = ()

    def _ring(self, routees):
        key = tuple(id(r) for r in routees)
        if self._ring_cache and self._ring_cache[0] == key:
            return self._ring_cache[1]
        ring = sorted((_hash_key((i, v)), r)
                      for i, r in enumerate(routees) for v in range(self.vnodes))
        self._ring_cache = (key, ring)
        return ring

    def select(self, message, routees):
        if not routees:
            return NoRoutee()
        key = message
        if self.hash_mapping is not None:
            key = self.hash_mapping(message)
        elif isinstance(message, ConsistentHashableEnvelope):
            key = message.hash_key
            message = message.message
        h = _hash_key(key)
        ring = self._ring(routees)
        # first node clockwise from h
        lo, hi = 0, len(ring)
        while lo < hi:
            mid = (lo + hi) // 2
            if ring[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        return ring[lo % len(ring)][1]


@dataclass(frozen=True)
class ConsistentHashableEnvelope:
    message: Any
    hash_key: Any


class ScatterGatherFirstCompletedRoutingLogic(RoutingLogic):
    """Send to all; first reply wins (reference: routing/ScatterGatherFirstCompleted...)"""

    def __init__(self, within: float = 5.0):
        self.within = within

    def select(self, message, routees):
        return SeveralRoutees(tuple(routees))


class TailChoppingRoutingLogic(RoutingLogic):
    """Send to one random routee, then another after `interval` until a reply
    (reference: routing/TailChopping.scala). Approximated: scatter to a random
    ordering with host-side delay handled by the router actor."""

    def __init__(self, scheduler=None, within: float = 5.0, interval: float = 0.5):
        self.scheduler = scheduler
        self.within = within
        self.interval = interval

    def select(self, message, routees):
        if not routees:
            return NoRoutee()
        shuffled = list(routees)
        _random.shuffle(shuffled)
        if self.scheduler is None or len(shuffled) == 1:
            return shuffled[0]
        first, rest = shuffled[0], shuffled[1:]

        class _Chopper(Routee):
            def send(_self, message, sender):
                first.send(message, sender)
                for i, r in enumerate(rest):
                    self.scheduler.schedule_once(
                        self.interval * (i + 1), lambda r=r: r.send(message, sender))
        return _Chopper()


class Router:
    """(reference: routing/Router.scala:116)"""

    def __init__(self, logic: RoutingLogic, routees: Sequence[Routee] = ()):
        self.logic = logic
        self.routees: List[Routee] = list(routees)

    def route(self, message: Any, sender: Optional[ActorRef]) -> None:
        if isinstance(message, Broadcast):
            SeveralRoutees(tuple(self.routees)).send(message.message, sender)
        else:
            self.logic.select(message, self.routees).send(message, sender)

    def with_routees(self, routees: Sequence[Routee]) -> "Router":
        return Router(self.logic, list(routees))

    def add_routee(self, routee: Routee) -> None:
        self.routees.append(routee)

    def remove_routee(self, routee: Routee) -> None:
        try:
            self.routees.remove(routee)
        except ValueError:
            pass


# -- resizer (reference: routing/Resizer.scala DefaultResizer) ---------------

@dataclass
class DefaultResizer:
    lower_bound: int = 1
    upper_bound: int = 10
    pressure_threshold: int = 1
    rampup_rate: float = 0.2
    backoff_threshold: float = 0.3
    backoff_rate: float = 0.1
    messages_per_resize: int = 10

    def is_time_for_resize(self, message_counter: int) -> bool:
        return message_counter % self.messages_per_resize == 0

    def resize(self, routees: Sequence[Routee]) -> int:
        """Returns the change in capacity (+/-)."""
        pressure = 0
        for r in routees:
            cell = getattr(getattr(r, "ref", None), "cell", None)
            if cell is not None and cell.mailbox is not None:
                if cell.mailbox.number_of_messages >= self.pressure_threshold:
                    pressure += 1
        cap = len(routees)
        if pressure >= cap:
            change = max(1, int(cap * self.rampup_rate))
        elif cap > 0 and pressure / cap < self.backoff_threshold:
            change = -max(1, int(cap * self.backoff_rate))
        else:
            change = 0
        new_cap = min(max(cap + change, self.lower_bound), self.upper_bound)
        return new_cap - cap


@dataclass
class OptimalSizeExploringResizer:
    """Explore-and-exploit pool sizing (reference:
    routing/OptimalSizeExploringResizer.scala): most resize checks EXPLOIT
    the best-throughput size seen so far; with `explore_step_size`
    probability-driven jitter the pool EXPLORES nearby sizes, recording
    messages-processed-per-size so the optimum tracks changing workloads.
    Same `resize(routees) -> delta` seam as DefaultResizer."""

    lower_bound: int = 1
    upper_bound: int = 10
    chance_of_exploration: float = 0.4
    explore_step_size: float = 0.1
    messages_per_resize: int = 10
    # decayed throughput record: size -> (ewma msgs processed per check)
    _perf: dict = field(default_factory=dict)
    _last_queued: int = 0

    def is_time_for_resize(self, message_counter: int) -> bool:
        return message_counter % self.messages_per_resize == 0

    def _record(self, routees: Sequence[Routee]) -> int:
        """Messages PROCESSED since the last check: exactly
        messages_per_resize were routed between checks, so processed =
        routed - backlog growth. Backlog is tracked as a delta (not an
        absolute clamp) so sizes stay distinguishable under sustained
        saturation — a size that drains faster records more throughput
        even while a queue persists."""
        queued = 0
        for r in routees:
            cell = getattr(getattr(r, "ref", None), "cell", None)
            if cell is not None and cell.mailbox is not None:
                queued += cell.mailbox.number_of_messages
        processed = max(
            0, self.messages_per_resize - (queued - self._last_queued))
        self._last_queued = queued
        size = len(routees)
        prev = self._perf.get(size)
        self._perf[size] = (processed if prev is None
                            else 0.5 * prev + 0.5 * processed)
        return queued

    def resize(self, routees: Sequence[Routee]) -> int:
        size = len(routees)
        queued = self._record(routees)
        if _random.random() < self.chance_of_exploration:
            # explore: jitter around the current size
            step = max(1, int(size * self.explore_step_size))
            target = size + _random.choice((-step, step))
        else:
            # exploit: the best recorded size; bias upward under pressure
            if self._perf:
                target = max(self._perf.items(), key=lambda kv: kv[1])[0]
            else:
                target = size
            if queued > size:
                target = max(target, size + 1)
        target = min(max(target, self.lower_bound), self.upper_bound)
        return target - size


# -- router configs ----------------------------------------------------------

@dataclass(frozen=True)
class RouterConfig:
    nr_of_instances: int = 0
    logic_factory: Callable[[], RoutingLogic] = RoundRobinRoutingLogic
    paths: tuple = ()
    resizer: Optional[DefaultResizer] = None
    supervisor_strategy: Any = None

    def create_router(self, system) -> Router:
        return Router(self.logic_factory())

    @property
    def is_group(self) -> bool:
        return bool(self.paths)


def RoundRobinPool(n: int, resizer: Optional[DefaultResizer] = None,
                   supervisor_strategy=None) -> RouterConfig:
    return RouterConfig(nr_of_instances=n, logic_factory=RoundRobinRoutingLogic,
                        resizer=resizer, supervisor_strategy=supervisor_strategy)


def RandomPool(n: int, **kw) -> RouterConfig:
    return RouterConfig(nr_of_instances=n, logic_factory=RandomRoutingLogic, **kw)


def BroadcastPool(n: int, **kw) -> RouterConfig:
    return RouterConfig(nr_of_instances=n, logic_factory=BroadcastRoutingLogic, **kw)


def SmallestMailboxPool(n: int, **kw) -> RouterConfig:
    return RouterConfig(nr_of_instances=n, logic_factory=SmallestMailboxRoutingLogic, **kw)


def ConsistentHashingPool(n: int, hash_mapping=None, virtual_nodes_factor: int = 17,
                          **kw) -> RouterConfig:
    return RouterConfig(
        nr_of_instances=n,
        logic_factory=lambda: ConsistentHashingRoutingLogic(hash_mapping, virtual_nodes_factor),
        **kw)


def ScatterGatherFirstCompletedPool(n: int, within: float = 5.0, **kw) -> RouterConfig:
    return RouterConfig(nr_of_instances=n,
                        logic_factory=lambda: ScatterGatherFirstCompletedRoutingLogic(within),
                        **kw)


def TailChoppingPool(n: int, within: float = 5.0, interval: float = 0.5, **kw) -> RouterConfig:
    return RouterConfig(nr_of_instances=n,
                        logic_factory=lambda: TailChoppingRoutingLogic(None, within, interval),
                        **kw)


def RoundRobinGroup(paths: Sequence[str]) -> RouterConfig:
    return RouterConfig(logic_factory=RoundRobinRoutingLogic, paths=tuple(paths))


def RandomGroup(paths: Sequence[str]) -> RouterConfig:
    return RouterConfig(logic_factory=RandomRoutingLogic, paths=tuple(paths))


def BroadcastGroup(paths: Sequence[str]) -> RouterConfig:
    return RouterConfig(logic_factory=BroadcastRoutingLogic, paths=tuple(paths))


def ConsistentHashingGroup(paths: Sequence[str], hash_mapping=None) -> RouterConfig:
    return RouterConfig(logic_factory=lambda: ConsistentHashingRoutingLogic(hash_mapping),
                        paths=tuple(paths))
