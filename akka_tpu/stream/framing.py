"""Framing: delimiter- and length-field-based byte-stream framing.

Reference parity: akka-stream scaladsl/Framing.scala — `delimiter`
(split on a byte marker, enforce max frame length), `lengthField`
(binary length-prefixed frames), and `simpleFramingProtocol` (the
encoder/decoder pair for symmetric length-prefixed wire protocols, as
used over TCP). Stages operate on bytes CHUNKS with arbitrary
boundaries — reassembly is the whole point.
"""

from __future__ import annotations

import struct
from typing import List

from .ops import _LinearStage, make_in_handler, make_out_handler


class FramingException(RuntimeError):
    pass


class DelimiterFraming(_LinearStage):
    def __init__(self, delimiter: bytes, maximum_frame_length: int = 1 << 20,
                 allow_truncation: bool = False):
        super().__init__("DelimiterFraming")
        if not delimiter:
            raise ValueError("empty delimiter")
        self.delimiter = bytes(delimiter)
        self.max_len = maximum_frame_length
        self.allow_truncation = allow_truncation

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        stage = self
        buf = bytearray()
        pending: List[bytes] = []

        def split() -> None:
            while True:
                i = buf.find(stage.delimiter)
                if i < 0:
                    if len(buf) > stage.max_len:
                        raise FramingException(
                            f"frame exceeds {stage.max_len} bytes without "
                            f"delimiter")
                    return
                if i > stage.max_len:
                    raise FramingException(
                        f"frame of {i} bytes exceeds {stage.max_len}")
                pending.append(bytes(buf[:i]))
                del buf[:i + len(stage.delimiter)]

        def on_push():
            buf.extend(logic.grab(in_))
            try:
                split()
            except FramingException as e:
                logic.fail_stage(e)
                return
            if pending:
                logic.push(out, pending.pop(0))
            else:
                logic.pull(in_)

        def on_finish():
            if buf:
                if not stage.allow_truncation:
                    logic.fail_stage(FramingException(
                        "stream finished with truncated frame"))
                    return
                pending.append(bytes(buf))
                buf.clear()
            if pending:
                logic.emit_multiple(out, list(pending))
                pending.clear()
            logic.complete_stage()

        def on_pull():
            if pending:
                logic.push(out, pending.pop(0))
            else:
                logic.pull(in_)

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class LengthFieldFraming(_LinearStage):
    """Frames = [length field][payload]; emits payload-only frames unless
    include_header. Big-endian unsigned length of field_length bytes."""

    def __init__(self, field_length: int, maximum_frame_length: int = 1 << 20,
                 field_offset: int = 0, include_header: bool = False):
        super().__init__("LengthFieldFraming")
        if field_length not in (1, 2, 4, 8):
            raise ValueError("field_length must be 1, 2, 4 or 8")
        self.field_length = field_length
        self.field_offset = field_offset
        self.max_len = maximum_frame_length
        self.include_header = include_header

    def _decode_len(self, data: bytes) -> int:
        return int.from_bytes(data, "big")

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        stage = self
        buf = bytearray()
        pending: List[bytes] = []
        head = stage.field_offset + stage.field_length

        def split() -> None:
            while len(buf) >= head:
                n = stage._decode_len(
                    bytes(buf[stage.field_offset:head]))
                if n > stage.max_len:
                    raise FramingException(
                        f"frame of {n} bytes exceeds {stage.max_len}")
                total = head + n
                if len(buf) < total:
                    return
                frame = bytes(buf[:total]) if stage.include_header \
                    else bytes(buf[head:total])
                pending.append(frame)
                del buf[:total]

        def on_push():
            buf.extend(logic.grab(in_))
            try:
                split()
            except FramingException as e:
                logic.fail_stage(e)
                return
            if pending:
                logic.push(out, pending.pop(0))
            else:
                logic.pull(in_)

        def on_finish():
            if buf:
                logic.fail_stage(FramingException(
                    "stream finished with truncated frame"))
                return
            if pending:
                logic.emit_multiple(out, list(pending))
                pending.clear()
            logic.complete_stage()

        def on_pull():
            if pending:
                logic.push(out, pending.pop(0))
            else:
                logic.pull(in_)

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class JsonObjectFraming(_LinearStage):
    """Bracket-counting JSON object scanner (reference: scaladsl/
    JsonFraming.scala:17 objectScanner + impl/JsonObjectParser.scala):
    emits one complete top-level `{...}` object per element from a chunked
    byte stream, skipping whitespace, commas and the enclosing brackets of
    an outer array, so both newline/comma-separated object streams and
    `[{...},{...}]` documents frame identically. String literals (with
    escapes) are opaque to the brace counter."""

    _SKIP = frozenset(b" \t\r\n,[]")

    def __init__(self, maximum_object_length: int = 1 << 20):
        super().__init__("JsonObjectFraming")
        self.max_len = maximum_object_length

    def create_logic(self):  # noqa: C901
        logic, in_, out = self._logic(), self.in_, self.out
        stage = self
        buf = bytearray()
        pending: List[bytes] = []
        # scan state survives chunk boundaries: pos = next unscanned byte,
        # start = object start (-1 outside an object)
        st = {"pos": 0, "start": -1, "depth": 0, "in_str": False,
              "esc": False}

        def scan() -> None:
            while st["pos"] < len(buf):
                b = buf[st["pos"]]
                if st["depth"] == 0:
                    if b == 0x7B:  # {
                        st["start"] = st["pos"]
                        st["depth"] = 1
                    elif b not in stage._SKIP:
                        raise FramingException(
                            f"invalid JSON input: unexpected byte "
                            f"0x{b:02x} outside an object")
                elif st["esc"]:
                    st["esc"] = False
                elif st["in_str"]:
                    if b == 0x5C:  # backslash
                        st["esc"] = True
                    elif b == 0x22:  # "
                        st["in_str"] = False
                elif b == 0x22:
                    st["in_str"] = True
                elif b == 0x7B:
                    st["depth"] += 1
                elif b == 0x7D:  # }
                    st["depth"] -= 1
                    if st["depth"] == 0:
                        if st["pos"] - st["start"] + 1 > stage.max_len:
                            raise FramingException(
                                f"JSON object exceeds {stage.max_len} bytes")
                        pending.append(bytes(buf[st["start"]:st["pos"] + 1]))
                        del buf[:st["pos"] + 1]
                        st["pos"] = -1
                        st["start"] = -1
                # in-progress length check: pos - start + 1 bytes consumed
                # by the open object so far (same formula as at emit, so an
                # exactly-max_len object passes and max_len+1 fails)
                if st["depth"] > 0 and \
                        st["pos"] - st["start"] + 1 > stage.max_len:
                    raise FramingException(
                        f"JSON object exceeds {stage.max_len} bytes")
                st["pos"] += 1
            # trim consumed bytes so memory stays bounded by max_len even
            # when the input is mostly separators/whitespace (outside an
            # object everything scanned is droppable; inside, everything
            # before the object start is)
            if st["start"] < 0:
                del buf[:st["pos"]]
                st["pos"] = 0
            elif st["start"] > 0:
                del buf[:st["start"]]
                st["pos"] -= st["start"]
                st["start"] = 0

        def on_push():
            buf.extend(logic.grab(in_))
            try:
                scan()
            except FramingException as e:
                logic.fail_stage(e)
                return
            if pending:
                logic.push(out, pending.pop(0))
            else:
                logic.pull(in_)

        def on_finish():
            if st["depth"] > 0:
                logic.fail_stage(FramingException(
                    "stream finished with truncated JSON object"))
                return
            if pending:
                logic.emit_multiple(out, list(pending))
                pending.clear()
            logic.complete_stage()

        def on_pull():
            if pending:
                logic.push(out, pending.pop(0))
            else:
                logic.pull(in_)

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class JsonFraming:
    """Factory namespace (scaladsl/JsonFraming.scala)."""

    @staticmethod
    def object_scanner(maximum_object_length: int = 1 << 20):
        from .dsl import Flow
        return Flow().via_stage(lambda: JsonObjectFraming(
            maximum_object_length))


class Framing:
    """Factory namespace (scaladsl/Framing.scala)."""

    @staticmethod
    def delimiter(delimiter: bytes, maximum_frame_length: int = 1 << 20,
                  allow_truncation: bool = False):
        from .dsl import Flow
        return Flow().via_stage(lambda: DelimiterFraming(
            delimiter, maximum_frame_length, allow_truncation))

    @staticmethod
    def length_field(field_length: int, maximum_frame_length: int = 1 << 20,
                     field_offset: int = 0, include_header: bool = False):
        from .dsl import Flow
        return Flow().via_stage(lambda: LengthFieldFraming(
            field_length, maximum_frame_length, field_offset, include_header))

    @staticmethod
    def simple_framing_protocol_encoder(maximum_frame_length: int = 1 << 20):
        """bytes frame -> [u32 length][frame] (the symmetric encoder of
        simpleFramingProtocol)."""
        from .dsl import Flow

        def encode(frame: bytes) -> bytes:
            if len(frame) > maximum_frame_length:
                raise FramingException(
                    f"frame of {len(frame)} exceeds {maximum_frame_length}")
            return struct.pack(">I", len(frame)) + frame

        return Flow().map(encode)

    @staticmethod
    def simple_framing_protocol_decoder(maximum_frame_length: int = 1 << 20):
        return Framing.length_field(4, maximum_frame_length)
