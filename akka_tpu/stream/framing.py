"""Framing: delimiter- and length-field-based byte-stream framing.

Reference parity: akka-stream scaladsl/Framing.scala — `delimiter`
(split on a byte marker, enforce max frame length), `lengthField`
(binary length-prefixed frames), and `simpleFramingProtocol` (the
encoder/decoder pair for symmetric length-prefixed wire protocols, as
used over TCP). Stages operate on bytes CHUNKS with arbitrary
boundaries — reassembly is the whole point.
"""

from __future__ import annotations

import struct
from typing import List

from .ops import _LinearStage, make_in_handler, make_out_handler


class FramingException(RuntimeError):
    pass


class DelimiterFraming(_LinearStage):
    def __init__(self, delimiter: bytes, maximum_frame_length: int = 1 << 20,
                 allow_truncation: bool = False):
        super().__init__("DelimiterFraming")
        if not delimiter:
            raise ValueError("empty delimiter")
        self.delimiter = bytes(delimiter)
        self.max_len = maximum_frame_length
        self.allow_truncation = allow_truncation

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        stage = self
        buf = bytearray()
        pending: List[bytes] = []

        def split() -> None:
            while True:
                i = buf.find(stage.delimiter)
                if i < 0:
                    if len(buf) > stage.max_len:
                        raise FramingException(
                            f"frame exceeds {stage.max_len} bytes without "
                            f"delimiter")
                    return
                if i > stage.max_len:
                    raise FramingException(
                        f"frame of {i} bytes exceeds {stage.max_len}")
                pending.append(bytes(buf[:i]))
                del buf[:i + len(stage.delimiter)]

        def on_push():
            buf.extend(logic.grab(in_))
            try:
                split()
            except FramingException as e:
                logic.fail_stage(e)
                return
            if pending:
                logic.push(out, pending.pop(0))
            else:
                logic.pull(in_)

        def on_finish():
            if buf:
                if not stage.allow_truncation:
                    logic.fail_stage(FramingException(
                        "stream finished with truncated frame"))
                    return
                pending.append(bytes(buf))
                buf.clear()
            if pending:
                logic.emit_multiple(out, list(pending))
                pending.clear()
            logic.complete_stage()

        def on_pull():
            if pending:
                logic.push(out, pending.pop(0))
            else:
                logic.pull(in_)

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class LengthFieldFraming(_LinearStage):
    """Frames = [length field][payload]; emits payload-only frames unless
    include_header. Big-endian unsigned length of field_length bytes."""

    def __init__(self, field_length: int, maximum_frame_length: int = 1 << 20,
                 field_offset: int = 0, include_header: bool = False):
        super().__init__("LengthFieldFraming")
        if field_length not in (1, 2, 4, 8):
            raise ValueError("field_length must be 1, 2, 4 or 8")
        self.field_length = field_length
        self.field_offset = field_offset
        self.max_len = maximum_frame_length
        self.include_header = include_header

    def _decode_len(self, data: bytes) -> int:
        return int.from_bytes(data, "big")

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        stage = self
        buf = bytearray()
        pending: List[bytes] = []
        head = stage.field_offset + stage.field_length

        def split() -> None:
            while len(buf) >= head:
                n = stage._decode_len(
                    bytes(buf[stage.field_offset:head]))
                if n > stage.max_len:
                    raise FramingException(
                        f"frame of {n} bytes exceeds {stage.max_len}")
                total = head + n
                if len(buf) < total:
                    return
                frame = bytes(buf[:total]) if stage.include_header \
                    else bytes(buf[head:total])
                pending.append(frame)
                del buf[:total]

        def on_push():
            buf.extend(logic.grab(in_))
            try:
                split()
            except FramingException as e:
                logic.fail_stage(e)
                return
            if pending:
                logic.push(out, pending.pop(0))
            else:
                logic.pull(in_)

        def on_finish():
            if buf:
                logic.fail_stage(FramingException(
                    "stream finished with truncated frame"))
                return
            if pending:
                logic.emit_multiple(out, list(pending))
                pending.clear()
            logic.complete_stage()

        def on_pull():
            if pending:
                logic.push(out, pending.pop(0))
            else:
                logic.pull(in_)

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class Framing:
    """Factory namespace (scaladsl/Framing.scala)."""

    @staticmethod
    def delimiter(delimiter: bytes, maximum_frame_length: int = 1 << 20,
                  allow_truncation: bool = False):
        from .dsl import Flow
        return Flow().via_stage(lambda: DelimiterFraming(
            delimiter, maximum_frame_length, allow_truncation))

    @staticmethod
    def length_field(field_length: int, maximum_frame_length: int = 1 << 20,
                     field_offset: int = 0, include_header: bool = False):
        from .dsl import Flow
        return Flow().via_stage(lambda: LengthFieldFraming(
            field_length, maximum_frame_length, field_offset, include_header))

    @staticmethod
    def simple_framing_protocol_encoder(maximum_frame_length: int = 1 << 20):
        """bytes frame -> [u32 length][frame] (the symmetric encoder of
        simpleFramingProtocol)."""
        from .dsl import Flow

        def encode(frame: bytes) -> bytes:
            if len(frame) > maximum_frame_length:
                raise FramingException(
                    f"frame of {len(frame)} exceeds {maximum_frame_length}")
            return struct.pack(">I", len(frame)) + frame

        return Flow().map(encode)

    @staticmethod
    def simple_framing_protocol_decoder(maximum_frame_length: int = 1 << 20):
        return Framing.length_field(4, maximum_frame_length)
