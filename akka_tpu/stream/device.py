"""Device pipelines: tensor-shaped streams fused into single XLA programs.

SURVEY.md §7 step 10: "on-device fused pipelines for tensor-shaped streams".
Where the reference fuses operator islands into one actor (impl/
PhasedFusingActorMaterializer.scala), the TPU-native analogue fuses a chain
of per-chunk tensor ops into ONE jitted function — XLA then fuses the
elementwise chain into a single kernel, so a 10-op pipeline costs one HBM
round trip instead of ten. Chunks ride `lax.scan` when stacked on device
(zero host round trips between chunks) or a host loop when streamed in.

Filter semantics are mask-based: tensor streams keep static shapes (no
data-dependent shapes under jit — SURVEY.md XLA semantics), so `filter`
zeroes failing lanes and threads a validity mask; `compact()` at the end
drops invalid lanes on the host.

Integration: `.as_flow()` turns the compiled pipeline into a host-stream
Flow operator so device pipelines compose with the backpressured DSL.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class DevicePipeline:
    """Chain of per-chunk tensor ops compiled to one jitted step.

    ops:
    - map(fn):        chunk -> chunk (elementwise or any shape-preserving op)
    - filter(pred):   pred(chunk) -> bool mask over leading axis; failing
                      lanes are zeroed and masked out
    - scan(fn, init): stateful across chunks: fn(carry, chunk) -> (carry, out)
    """

    def __init__(self):
        self._ops: List[Tuple] = []
        self._scan_init = None
        self._has_scan = False
        self._compiled = None

    # -- builders (return self for chaining) ---------------------------------
    def map(self, fn: Callable) -> "DevicePipeline":
        self._ops.append(("map", fn))
        self._compiled = None
        return self

    def filter(self, pred: Callable) -> "DevicePipeline":
        self._ops.append(("filter", pred))
        self._compiled = None
        return self

    def scan(self, fn: Callable, init: Any) -> "DevicePipeline":
        if self._has_scan:
            raise ValueError("one scan per pipeline")
        self._ops.append(("scan", fn))
        self._scan_init = init
        self._has_scan = True
        self._compiled = None
        return self

    # -- compile --------------------------------------------------------------
    def _build_step(self):
        ops = list(self._ops)

        def step(carry, chunk):
            mask = jnp.ones((chunk.shape[0],), dtype=jnp.bool_)
            x = chunk
            for kind, fn in ops:
                if kind == "map":
                    x = fn(x)
                elif kind == "filter":
                    keep = fn(x)
                    mask = jnp.logical_and(mask, keep)
                    # zero failing lanes so later ops see neutral values
                    zero_shape = (x.shape[0],) + (1,) * (x.ndim - 1)
                    x = jnp.where(keep.reshape(zero_shape), x,
                                  jnp.zeros_like(x))
                else:  # scan
                    carry, x = fn(carry, x)
            return carry, (x, mask)
        return step

    def compile(self):
        """One fused jitted step(carry, chunk) -> (carry, (out, mask))."""
        if self._compiled is None:
            self._compiled = jax.jit(self._build_step())
        return self._compiled

    # -- run ------------------------------------------------------------------
    def run(self, chunks) -> Tuple[Any, Any, Any]:
        """Run over chunks. If `chunks` is a stacked array [n_chunks, ...],
        the whole pipeline is ONE lax.scan on device; otherwise a host loop
        feeds the jitted step chunk by chunk.

        Returns (outputs, masks, final_carry) with outputs/masks stacked.
        """
        step = self.compile()
        carry0 = self._scan_init if self._scan_init is not None else 0
        if isinstance(chunks, (jnp.ndarray, np.ndarray)) and \
                getattr(chunks, "ndim", 0) >= 2:
            final_carry, (outs, masks) = jax.lax.scan(
                step, carry0, jnp.asarray(chunks))
            return outs, masks, final_carry
        outs, masks = [], []
        carry = carry0
        for chunk in chunks:
            carry, (out, mask) = step(carry, jnp.asarray(chunk))
            outs.append(out)
            masks.append(mask)
        return jnp.stack(outs), jnp.stack(masks), carry

    @staticmethod
    def compact(outs, masks) -> np.ndarray:
        """Host-side: drop masked-out lanes and flatten chunk structure."""
        o = np.asarray(outs)
        m = np.asarray(masks).astype(bool)
        flat_o = o.reshape((-1,) + o.shape[2:])
        return flat_o[m.reshape(-1)]

    # -- host-stream integration ---------------------------------------------
    def as_flow(self):
        """A Flow operator running this pipeline per stream element (each
        element is one chunk); emits (out_chunk, mask) pairs. The carry is
        threaded across elements — a stateful fused stage."""
        from .dsl import Flow
        step = self.compile()
        state = {"carry": self._scan_init if self._scan_init is not None
                 else 0}

        def apply(chunk):
            state["carry"], (out, mask) = step(state["carry"],
                                               jnp.asarray(chunk))
            return out, mask
        return Flow().map(apply)
