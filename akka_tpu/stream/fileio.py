"""File + compression stages.

Reference parity: akka-stream impl/io/FileSource/FileSink
(scaladsl/FileIO.scala — chunked file reads, appending/overwriting byte
sinks with an IOResult count) and scaladsl/Compression.scala
(gzip/gunzip/deflate/inflate flows). Host-side IO is the slow path here as
in the reference; the stages run inside the stream's interpreter actor."""

from __future__ import annotations

import os
import zlib
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Optional

from .ops import _LinearStage, _SinkStage, _SourceStage, make_in_handler, \
    make_out_handler
from .stage import GraphStageLogic


@dataclass
class IOResult:
    """(reference: stream/IOResult.scala)"""

    count: int
    error: Optional[BaseException] = None

    @property
    def was_successful(self) -> bool:
        return self.error is None


class FileSource(_SourceStage):
    def __init__(self, path: str, chunk_size: int = 8192):
        super().__init__("FileSource")
        self.path = path
        self.chunk_size = chunk_size

    def create_logic_and_mat(self):
        stage = self
        mat: Future = Future()
        logic = GraphStageLogic(self._shape)
        state = {"fh": None, "count": 0}

        def on_pull():
            if state["fh"] is None:
                try:
                    state["fh"] = open(stage.path, "rb")
                except OSError as e:
                    mat.set_result(IOResult(0, e))
                    logic.fail_stage(e)
                    return
            chunk = state["fh"].read(stage.chunk_size)
            if chunk:
                state["count"] += len(chunk)
                logic.push(stage.out, chunk)
            else:
                state["fh"].close()
                mat.set_result(IOResult(state["count"]))
                logic.complete(stage.out)

        def on_downstream_finish(cause=None):
            # cancellation mid-file still closes the handle and resolves
            # the IOResult with what was read (no fd leak, no hung mat)
            if state["fh"] is not None:
                try:
                    state["fh"].close()
                except OSError:
                    pass
            if not mat.done():
                mat.set_result(IOResult(state["count"]))
            logic.cancel_stage(cause)

        logic.set_handler(stage.out, make_out_handler(on_pull,
                                                      on_downstream_finish))
        return logic, mat


class FileSink(_SinkStage):
    def __init__(self, path: str, append: bool = False):
        super().__init__("FileSink")
        self.path = path
        self.append = append

    def create_logic_and_mat(self):
        from .ops import _sink_logic
        stage = self
        fut: Future = Future()
        state = {"fh": None, "count": 0}

        def write(data) -> None:
            if state["fh"] is None:
                state["fh"] = open(stage.path,
                                   "ab" if stage.append else "wb")
            state["fh"].write(data)
            state["count"] += len(data)

        def result() -> IOResult:
            if state["fh"] is None:  # empty stream still creates the file
                write(b"")
            state["fh"].close()
            return IOResult(state["count"])

        def cleanup() -> None:
            # upstream failed / write raised: flush + close what we have so
            # the fd never leaks and the tail bytes reach disk
            if state["fh"] is not None:
                state["fh"].close()
                state["fh"] = None

        return _sink_logic(stage, write, fut, result_fn=result,
                           cleanup_fn=cleanup), fut


class FileIO:
    """Factory namespace (scaladsl/FileIO.scala)."""

    @staticmethod
    def from_path(path: str, chunk_size: int = 8192):
        from .dsl import Source
        return Source.from_graph(lambda: FileSource(path, chunk_size))

    @staticmethod
    def to_path(path: str, append: bool = False):
        from .dsl import Sink
        return Sink.from_graph(lambda: FileSink(path, append))


class _Deflate(_LinearStage):
    def __init__(self, gzip: bool, level: int = 6):
        super().__init__("Gzip" if gzip else "Deflate")
        self.gzip = gzip
        self.level = level

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        wbits = 16 + zlib.MAX_WBITS if self.gzip else zlib.MAX_WBITS
        comp = zlib.compressobj(self.level, zlib.DEFLATED, wbits)

        def on_push():
            data = comp.compress(logic.grab(in_))
            if data:
                logic.push(out, data)
            else:
                logic.pull(in_)

        def on_finish():
            tail = comp.flush()
            if tail:
                logic.emit(out, tail)
            logic.complete_stage()

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class _Inflate(_LinearStage):
    def __init__(self, gzip: bool):
        super().__init__("Gunzip" if gzip else "Inflate")
        self.gzip = gzip

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        wbits = 16 + zlib.MAX_WBITS if self.gzip else zlib.MAX_WBITS
        decomp = zlib.decompressobj(wbits)

        def on_push():
            try:
                data = decomp.decompress(logic.grab(in_))
            except zlib.error as e:
                logic.fail_stage(e)
                return
            if data:
                logic.push(out, data)
            else:
                logic.pull(in_)

        def on_finish():
            try:
                tail = decomp.flush()
            except zlib.error as e:
                logic.fail_stage(e)
                return
            if tail:
                logic.emit(out, tail)
            logic.complete_stage()

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class Compression:
    """(reference: scaladsl/Compression.scala)"""

    @staticmethod
    def gzip(level: int = 6):
        from .dsl import Flow
        return Flow().via_stage(lambda: _Deflate(True, level))

    @staticmethod
    def gunzip():
        from .dsl import Flow
        return Flow().via_stage(lambda: _Inflate(True))

    @staticmethod
    def deflate(level: int = 6):
        from .dsl import Flow
        return Flow().via_stage(lambda: _Deflate(False, level))

    @staticmethod
    def inflate():
        from .dsl import Flow
        return Flow().via_stage(lambda: _Inflate(False))
