"""RetryFlow: retry-with-backoff around a request/response flow.

Reference parity: akka-stream/src/main/scala/akka/stream/scaladsl/
RetryFlow.scala:12 (withBackoff / withBackoffAndContext) and impl/
RetryFlowCoordinator.scala: the wrapped flow is materialized ONCE and kept
running; at most ONE element is in flight at a time (the coordinator's
contract — it makes retry bookkeeping unambiguous); for every response the
user's `decide_retry(last_sent_in, out) -> Optional[new_in]` chooses
whether to re-inject a (possibly modified) element after an exponential
backoff or emit the response downstream. After `max_retries` re-injections
the latest response is emitted regardless. The inner flow must be 1:1
(one response per request); early completion/cancellation of the inner
flow while unfinished business remains fails the stage, as the reference
coordinator does.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Optional

from .ops import _QUEUE_END
from .restart import _BridgeHandle, _BridgeSource
from .stage import (FlowShape, GraphStage, GraphStageLogic, Inlet, Outlet,
                    make_in_handler, make_out_handler)


class _RetryFlowStage(GraphStage):
    def __init__(self, min_backoff: float, max_backoff: float,
                 random_factor: float, max_retries: int, flow: Any,
                 decide_retry: Callable[[Any, Any], Optional[Any]]):
        self.name = "RetryFlow"
        self.min_backoff = float(min_backoff)
        self.max_backoff = float(max_backoff)
        self.random_factor = float(random_factor)
        self.max_retries = int(max_retries)
        self.flow = flow
        self.decide_retry = decide_retry
        self.in_ = Inlet("RetryFlow.in")
        self.out = Outlet("RetryFlow.out")
        self._shape = FlowShape(self.in_, self.out)

    @property
    def shape(self):
        return self._shape

    def delay_for(self, retry_no: int) -> float:
        base = min(self.max_backoff,
                   self.min_backoff * (2.0 ** max(retry_no - 1, 0)))
        return base * (1.0 + random.random() * self.random_factor)

    def create_logic(self):  # noqa: C901
        stage = self
        in_, out = self.in_, self.out
        NO_STASH = object()  # sentinel: None is a legal stream element
        # at most one element in progress: attempt_in is the input of the
        # in-flight attempt (what decide_retry sees as `in`), retries the
        # number of re-injections already performed for it
        st = {"handle": None, "queue": None, "demand": 0,
              "send_stash": NO_STASH, "attempt_in": None, "in_flight": False,
              "retries": 0, "pulling": False, "finishing": False,
              "stopped": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                from .dsl import Keep, Sink, Source
                handle = _BridgeHandle(
                    self.get_async_callback(self._on_bridge), 1)
                st["handle"] = handle
                st["queue"] = Source.from_graph(
                    lambda: _BridgeSource(handle)).via(stage.flow) \
                    .to_mat(Sink.queue(), Keep.right).run(self.materializer)

            # ---- feeding the inner flow ----
            def _send(self, elem):
                st["attempt_in"] = elem
                st["in_flight"] = True
                if st["demand"] > 0:
                    st["demand"] -= 1
                    st["handle"].to_inner(("elem", elem))
                else:
                    st["send_stash"] = elem
                self._request()

            def _on_bridge(self, pair):
                _gen, ev = pair
                if st["stopped"]:
                    return
                if ev[0] == "demand":
                    st["demand"] += 1
                    if st["send_stash"] is not NO_STASH:
                        elem, st["send_stash"] = st["send_stash"], NO_STASH
                        st["demand"] -= 1
                        st["handle"].to_inner(("elem", elem))
                elif ev[0] == "cancel":
                    # the inner flow cancelled its input: the terminal
                    # outcome (failure with the real error, or a clean
                    # completion = contract violation) arrives on the
                    # queue side — make sure we are reading it
                    self._request()

            # ---- reading the inner flow's responses ----
            def _request(self):
                if st["pulling"] or st["queue"] is None:
                    return
                st["pulling"] = True
                cb = self.get_async_callback(self._on_response)
                st["queue"].pull().add_done_callback(cb.invoke)

            def _on_response(self, f):
                if st["stopped"]:
                    return
                st["pulling"] = False
                ex = f.exception()
                if ex is not None:
                    st["stopped"] = True
                    self.fail_stage(ex)
                    return
                item = f.result()
                if item is _QUEUE_END:
                    if st["in_flight"]:
                        self._illegal("inner flow completed with an "
                                      "element in flight")
                    elif st["finishing"]:
                        st["stopped"] = True
                        self.complete_stage()
                    else:
                        self._illegal("inner flow completed while upstream "
                                      "is still running")
                    return
                if not st["in_flight"]:
                    self._illegal("inner flow emitted without a request")
                    return
                retry_with = None
                try:
                    retry_with = stage.decide_retry(st["attempt_in"], item)
                except Exception as e:  # noqa: BLE001 — user decision fn
                    st["stopped"] = True
                    self.fail_stage(e)
                    return
                if retry_with is None or st["retries"] >= stage.max_retries:
                    st["in_flight"] = False
                    st["attempt_in"] = None
                    st["retries"] = 0
                    self.push(out, item)
                    if st["finishing"]:
                        st["handle"].to_inner(("complete",))
                        self._request()  # drain to _QUEUE_END -> complete
                    return
                st["retries"] += 1
                st["retry_with"] = retry_with
                self.schedule_once("retry", stage.delay_for(st["retries"]))

            def on_timer(self, key):
                if st["stopped"] or key != "retry":
                    return
                self._send(st.pop("retry_with"))

            def _illegal(self, what: str):
                st["stopped"] = True
                self.fail_stage(RuntimeError(
                    f"RetryFlow inner flow violated its contract: {what}"))

            def post_stop(self):
                q = st["queue"]
                if q is not None:
                    q.cancel()

        logic = _L(self._shape)

        def on_push():
            logic._send(logic.grab(in_))

        def on_finish():
            st["finishing"] = True
            if not st["in_flight"] and st["handle"] is not None:
                st["handle"].to_inner(("complete",))
                logic._request()

        def on_failure(ex):
            st["stopped"] = True
            h = st["handle"]
            if h is not None:
                h.to_inner(("fail", ex))
            logic.fail_stage(ex)

        def on_pull():
            if not st["in_flight"] and not logic.has_been_pulled(in_) and \
                    not logic.is_closed(in_):
                logic.pull(in_)

        def on_cancel(cause=None):
            st["stopped"] = True
            q = st["queue"]
            if q is not None:
                q.cancel()
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        logic.set_handler(out, make_out_handler(on_pull, on_cancel))
        return logic


class RetryFlow:
    """(reference: scaladsl/RetryFlow.scala:12)"""

    @staticmethod
    def with_backoff(min_backoff: float, max_backoff: float,
                     random_factor: float, max_retries: int, flow: Any,
                     decide_retry: Callable[[Any, Any], Optional[Any]]):
        """Flow[In, Out] wrapping `flow`; `decide_retry(in, out)` returns
        None to emit `out`, or a new `in` to re-inject after backoff."""
        from .dsl import Flow
        return Flow.from_graph(lambda: _RetryFlowStage(
            min_backoff, max_backoff, random_factor, max_retries, flow,
            decide_retry))

    @staticmethod
    def with_backoff_and_context(min_backoff: float, max_backoff: float,
                                 random_factor: float, max_retries: int,
                                 flow_with_context: Any,
                                 decide_retry: Callable[[Any, Any],
                                                        Optional[Any]]):
        """FlowWithContext variant: the inner flow and decide_retry see
        (data, ctx) pairs (reference: RetryFlow.withBackoffAndContext)."""
        from .context import FlowWithContext
        inner = flow_with_context.as_flow() \
            if isinstance(flow_with_context, FlowWithContext) \
            else flow_with_context
        return FlowWithContext.from_tuples(RetryFlow.with_backoff(
            min_backoff, max_backoff, random_factor, max_retries, inner,
            decide_retry))
