"""Stream Attributes + per-element supervision (reference parity:
akka-stream/src/main/scala/akka/stream/Attributes.scala — an immutable
heterogeneous list of attribute values attached to a graph section, with
`and` composition where the most specific (innermost/latest) wins; and
Supervision.scala — Decider: Throwable => Directive with resume/restart/
stop, honored per element by the interpreter rather than per-operator
try/catch as in Ops.scala, which is the same contract centralized).

Usage (scaladsl `withAttributes(supervisionStrategy(resumingDecider))`):

    flow.map(f).with_attributes(
        Attributes.supervision_strategy(Supervision.resuming_decider))

Attributes apply to every stage built by the wrapped section only —
operators appended AFTER with_attributes are outside it, exactly like the
reference's section scoping (Attributes.scala:662 supervisionStrategy).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple


class Supervision:
    """Directives + canned deciders (reference: stream/Supervision.scala).

    stop    — tear the stream down (default; fail downstream/cancel upstream)
    resume  — drop the failing element and keep the stream running
    restart — drop the element AND reset the failing stage's accumulated
              state (stages expose reset via GraphStageLogic.restart_state;
              stages without one resume — mirroring the reference where
              restart is meaningful only for stages that declare state)
    """

    stop = "stop"
    resume = "resume"
    restart = "restart"

    Decider = Callable[[BaseException], str]

    @staticmethod
    def stopping_decider(ex: BaseException) -> str:  # noqa: ARG004
        return Supervision.stop

    @staticmethod
    def resuming_decider(ex: BaseException) -> str:  # noqa: ARG004
        return Supervision.resume

    @staticmethod
    def restarting_decider(ex: BaseException) -> str:  # noqa: ARG004
        return Supervision.restart


class Attributes:
    """Immutable attribute bag. Keys are strings; `and_then` (the
    reference's `and`) layers another bag on top with the NEW values
    winning — the interpreter reads the effective (topmost) value."""

    def __init__(self, values: Optional[Dict[str, Any]] = None):
        self._values: Dict[str, Any] = dict(values or {})

    # -- composition ---------------------------------------------------------
    def and_then(self, other: "Attributes") -> "Attributes":
        merged = dict(self._values)
        merged.update(other._values)
        return Attributes(merged)

    def get(self, key: str, default: Any = None) -> Any:
        return self._values.get(key, default)

    def __repr__(self) -> str:
        return f"Attributes({self._values!r})"

    # -- well-known attributes (reference: Attributes object) ---------------
    @staticmethod
    def name(n: str) -> "Attributes":
        return Attributes({"name": n})

    @staticmethod
    def supervision_strategy(decider: "Supervision.Decider") -> "Attributes":
        """(reference: ActorAttributes.supervisionStrategy /
        Attributes.scala:662)"""
        return Attributes({"supervision_decider": decider})

    @staticmethod
    def input_buffer(initial: int, max_: int) -> "Attributes":
        return Attributes({"input_buffer": (initial, max_)})

    @staticmethod
    def dispatcher(name: str) -> "Attributes":
        """(reference: ActorAttributes.dispatcher — which dispatcher the
        island's interpreter actor runs on)"""
        return Attributes({"dispatcher": name})

    @staticmethod
    def log_levels(on_element: str = "debug", on_finish: str = "debug",
                   on_failure: str = "error") -> "Attributes":
        return Attributes({"log_levels": (on_element, on_finish, on_failure)})

    # -- effective lookups ---------------------------------------------------
    def effective_decider(self) -> "Supervision.Decider":
        return self._values.get("supervision_decider",
                                Supervision.stopping_decider)

    def effective_input_buffer(self,
                               default: Tuple[int, int] = (16, 16)
                               ) -> Tuple[int, int]:
        return self._values.get("input_buffer", default)


def effective_decider_of(logic) -> "Supervision.Decider":
    """The decider the interpreter consults for a failing stage: the
    stage's stamped attributes, else stop (reference default)."""
    attrs = getattr(logic, "attributes", None)
    if attrs is None:
        return Supervision.stopping_decider
    return attrs.effective_decider()
