"""Reactive-streams-style compliance verification harness.

Reference parity: akka-stream-tests-tck/src/test/scala/akka/stream/tck/
AkkaPublisherVerification.scala:18 and AkkaIdentityProcessorVerification.scala
— a REUSABLE rule-by-rule battery any Source (publisher) or Flow (processor)
implementation runs against, instead of per-operator ad-hoc assertions. The
rules checked are the spirit of the reactive-streams spec mapped onto the
port-state interpreter's contract:

publisher rules (spec §1.x):
  1.01 no elements without demand
  1.02 no more elements than requested
  1.03 elements arrive in order
  1.05 completion after the final element
  1.08 cancel stops the stream (no further elements)
  1.09 error is terminal (no elements after onError)
  1.10 a blueprint supports multiple independent materializations

processor rules (identity processing, spec §2.x):
  2.01 demand propagates upstream
  2.02 elements pass through in order
  2.03 upstream completion propagates after in-flight elements
  2.04 upstream error propagates
  2.05 downstream cancel propagates upstream

Usage:
    verify_publisher(lambda n: Source.from_iterable(range(n)), system)
    verify_identity_processor(lambda: Flow().map(lambda x: x), system)
Each raises AssertionError naming the violated rule.
"""

from __future__ import annotations

from typing import Callable, List

from .dsl import Flow, Keep, Sink, Source
from .testkit import TestSink, TestSource


class TckViolation(AssertionError):
    def __init__(self, rule: str, detail: str):
        super().__init__(f"[{rule}] {detail}")
        self.rule = rule


def _probe(source: Source, system):
    return source.to_mat(TestSink.probe(), Keep.right).run(system)


def verify_publisher(source_factory: Callable[[int], Source], system,
                     n: int = 16) -> List[str]:
    """Run the publisher battery against `source_factory(k)` — which must
    build a Source emitting exactly k known elements 0..k-1 (or any fixed
    sequence; order/count is what is checked). Returns the rule ids that
    ran (all passed; violations raise TckViolation)."""
    ran: List[str] = []

    def rule(rid: str, cond: bool, detail: str = ""):
        ran.append(rid)
        if not cond:
            raise TckViolation(rid, detail)

    # 1.01: nothing before demand
    p = _probe(source_factory(n), system)
    try:
        p.expect_no_message(0.25)
        rule("1.01", True)
    except AssertionError as e:
        raise TckViolation("1.01", f"emitted without demand: {e}") from e

    # 1.02 + 1.03: at most the requested count, in order
    p.request(3)
    got = [p.expect_next() for _ in range(3)]
    p.expect_no_message(0.25)
    rule("1.02", True, "")
    expected_all = None
    try:
        expected_all = list(range(n))
        rule("1.03", got == expected_all[:3],
             f"out of order: {got} vs {expected_all[:3]}")
    except TckViolation:
        raise
    # drain + 1.05: completion after the final element
    p.request(n)  # over-request past the end
    rest = [p.expect_next() for _ in range(n - 3)]
    rule("1.03b", got + rest == expected_all,
         f"full sequence mismatch: {got + rest}")
    p.expect_complete()
    rule("1.05", True)

    # 1.08: cancel stops the stream
    p2 = _probe(source_factory(n), system)
    p2.request(1)
    p2.expect_next()
    p2.cancel()
    try:
        p2.expect_no_message(0.3)
        rule("1.08", True)
    except AssertionError as e:
        raise TckViolation("1.08", f"emitted after cancel: {e}") from e

    # 1.09: error is terminal
    boom = RuntimeError("tck-error")
    perr = _probe(
        source_factory(n).map(
            lambda x: (_ for _ in ()).throw(boom) if x == 1 else x),
        system)
    perr.request(n + 1)
    perr.expect_next()  # element 0
    err = perr.expect_error()
    rule("1.09", isinstance(err, RuntimeError), f"wrong error: {err!r}")
    perr.expect_no_message(0.2)

    # 1.10: blueprint reuse — two independent materializations.
    # Demand is n+1: the spec does not force completion-without-demand on
    # every operator (unfold-style stages discover the end on the next
    # pull), so the battery supplies the extra pull like the reference
    # TCK's requestNextElementOrEndOfStream
    src = source_factory(4)
    a = _probe(src, system)
    b = _probe(src, system)
    a.request(5)
    b.request(5)
    got_a = [a.expect_next() for _ in range(4)]
    got_b = [b.expect_next() for _ in range(4)]
    rule("1.10", got_a == got_b == list(range(4)),
         f"materializations diverge: {got_a} vs {got_b}")
    a.expect_complete()
    b.expect_complete()
    return ran


def verify_identity_processor(flow_factory: Callable[[], Flow], system,
                              n: int = 16) -> List[str]:
    """Run the processor battery against `flow_factory()` — a Flow that
    must pass elements through unchanged (identity) so ordering/count
    checks are exact (AkkaIdentityProcessorVerification analogue)."""
    ran: List[str] = []

    def rule(rid: str, cond: bool, detail: str = ""):
        ran.append(rid)
        if not cond:
            raise TckViolation(rid, detail)

    def harness():
        """TestSource -> flow -> TestSink with both probes."""
        return TestSource.probe().via_mat(flow_factory(), Keep.left) \
            .to_mat(TestSink.probe(), Keep.both).run(system)

    # 2.01: demand propagates upstream
    up, down = harness()
    down.request(2)
    req = up.expect_request()
    rule("2.01", req >= 1, f"no upstream demand, got {req}")

    # 2.02: elements pass through in order
    for i in range(3):
        up.send_next(i)
    down.request(8)
    first = [down.expect_next() for _ in range(3)]
    rule("2.02", first == [0, 1, 2], f"reordered: {first}")

    # 2.03: upstream completion propagates (after in-flight elements)
    up.send_next(99)
    up.send_complete()
    rule("2.03", down.expect_next() == 99, "in-flight element lost")
    down.expect_complete()

    # 2.04: upstream error propagates
    up2, down2 = harness()
    down2.request(4)
    up2.expect_request()
    up2.send_next(1)
    down2.expect_next()
    up2.send_error(ValueError("tck"))
    err = down2.expect_error()
    rule("2.04", isinstance(err, ValueError), f"wrong error: {err!r}")

    # 2.05: downstream cancel propagates upstream
    up3, down3 = harness()
    down3.request(1)
    up3.expect_request()
    down3.cancel()
    try:
        up3.expect_cancellation()
        rule("2.05", True)
    except AssertionError as e:
        raise TckViolation("2.05", f"cancel never reached upstream: {e}") \
            from e
    return ran
