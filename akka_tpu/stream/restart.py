"""RestartSource / RestartFlow / RestartSink: self-healing stream sections.

Reference parity: akka-stream/src/main/scala/akka/stream/scaladsl/
RestartSource.scala:20 (withBackoff / onFailuresWithBackoff), RestartFlow
.scala, RestartSink.scala and impl RestartWithBackoffLogic: the wrapped
blueprint is MATERIALIZED ANEW after failure (and, for withBackoff, after
completion), with exponential backoff between attempts; elements in flight
when the inner stream dies are lost (the reference documents the wrap as
at-most-once across restarts); the restart counter resets once the stream
has run longer than `max_restarts_within`.

Implementation: the outer stage sub-materializes the factory's blueprint on
the SAME materializer (exactly how flatMapConcat runs its inner sources)
and bridges elements/demand through async callbacks:
- RestartSource: inner runs `factory().to(Sink.queue())`; the outer pulls
  one element per downstream demand; a failed pull future triggers backoff.
- RestartSink:   inner runs `_BridgeSource().to(factory())`; the bridge
  signals per-element demand back to the outer, so backpressure crosses
  the restart boundary without a lossy buffer.
- RestartFlow:   both bridges around `factory()`.

Backoff timers ride the stream's TimerGraphStageLogic support, so Restart
stages need an actor-hosted materializer (the default).
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional

from .ops import _QUEUE_END, _SinkStage, _SourceStage
from .stage import (FlowShape, GraphStage, GraphStageLogic, Inlet, Outlet,
                    make_in_handler, make_out_handler)


class RestartSettings:
    """(reference: akka.stream.RestartSettings)"""

    def __init__(self, min_backoff: float = 1.0, max_backoff: float = 30.0,
                 random_factor: float = 0.2, max_restarts: int = -1,
                 max_restarts_within: Optional[float] = None):
        self.min_backoff = float(min_backoff)
        self.max_backoff = float(max_backoff)
        self.random_factor = float(random_factor)
        self.max_restarts = int(max_restarts)
        # the reference defaults the counting window to min_backoff
        self.max_restarts_within = (float(max_restarts_within)
                                    if max_restarts_within is not None
                                    else self.min_backoff)

    def delay_for(self, restart_count: int) -> float:
        base = min(self.max_backoff,
                   self.min_backoff * (2.0 ** max(restart_count - 1, 0)))
        return base * (1.0 + random.random() * self.random_factor)


class _BackoffState:
    """Shared restart bookkeeping (RestartWithBackoffLogic counter/deadline)."""

    def __init__(self, settings: RestartSettings):
        self.settings = settings
        self.count = 0
        self.window_start: Optional[float] = None

    def next_delay(self) -> Optional[float]:
        """None = budget exhausted (propagate the failure)."""
        now = time.monotonic()
        if self.window_start is None or \
                now - self.window_start > self.settings.max_restarts_within:
            self.window_start = now
            self.count = 0
        self.count += 1
        if 0 <= self.settings.max_restarts < self.count:
            return None
        return self.settings.delay_for(self.count)


class _BridgeHandle:
    """Outer-side handle to an inner _BridgeSource: send elements/completion
    in; receive demand/cancel out (both directions through interpreter
    async callbacks, so each side runs in its own island actor safely)."""

    def __init__(self, outer_cb, gen: int):
        self._outer_cb = outer_cb      # AsyncCallback on the OUTER logic
        self.gen = gen
        self._inner_cb = None
        self._pending = []
        import threading
        self._lock = threading.Lock()

    # inner side
    def _bind(self, inner_cb) -> None:
        with self._lock:
            self._inner_cb = inner_cb
            pending, self._pending = self._pending, []
        for ev in pending:
            inner_cb.invoke(ev)

    def to_outer(self, ev) -> None:
        self._outer_cb.invoke((self.gen, ev))

    # outer side
    def to_inner(self, ev) -> None:
        with self._lock:
            if self._inner_cb is None:
                self._pending.append(ev)
                return
        self._inner_cb.invoke(ev)


class _BridgeSource(_SourceStage):
    """Head of an inner materialization: pulls become ("demand") events to
    the outer stage, elements/completion/failure arrive as events."""

    def __init__(self, handle: _BridgeHandle):
        super().__init__("RestartBridgeSource")
        self.handle = handle

    def create_logic(self):
        out, handle = self.out, self.handle
        logic = GraphStageLogic(self._shape)

        def on_ev(ev):
            kind = ev[0]
            if kind == "elem":
                logic.push(out, ev[1])
            elif kind == "complete":
                logic.complete(out)
            elif kind == "fail":
                logic.fail(out, ev[1])

        def on_pull():
            handle.to_outer(("demand",))

        def on_cancel(cause=None):
            handle.to_outer(("cancel",))

        orig_pre = logic.pre_start

        def pre_start():
            orig_pre()
            handle._bind(logic.get_async_callback(on_ev))
        logic.pre_start = pre_start
        logic.set_handler(out, make_out_handler(on_pull, on_cancel))
        return logic


class _RestartWithBackoffSource(_SourceStage):
    """RestartSource.withBackoff / onFailuresWithBackoff (RestartSource
    .scala:20). Inner = factory().to(Sink.queue()); one outstanding pull."""

    def __init__(self, factory: Callable[[], Any], settings: RestartSettings,
                 only_on_failures: bool):
        super().__init__("RestartWithBackoffSource")
        self.factory = factory
        self.settings = settings
        self.only_on_failures = only_on_failures

    def create_logic(self):
        stage = self
        out = self.out
        st = {"queue": None, "gen": 0, "pulling": False, "want": False,
              "stopped": False}
        backoff = _BackoffState(self.settings)

        class _L(GraphStageLogic):
            def pre_start(self):
                self._start_inner()

            def _start_inner(self):
                from .dsl import Keep, Sink
                st["gen"] += 1
                st["queue"] = stage.factory().to_mat(
                    Sink.queue(), Keep.right).run(self.materializer)
                if st["want"] and not st["pulling"]:
                    self._request()

            def _request(self):
                st["pulling"] = True
                gen = st["gen"]
                cb = self.get_async_callback(self._on_inner)
                st["queue"].pull().add_done_callback(
                    lambda f: cb.invoke((gen, f)))

            def _on_inner(self, pair):
                gen, f = pair
                if gen != st["gen"] or st["stopped"]:
                    return  # stale run
                st["pulling"] = False
                ex = f.exception()
                if ex is not None:
                    self._terminated(ex)
                    return
                item = f.result()
                if item is _QUEUE_END:
                    if stage.only_on_failures:
                        st["stopped"] = True
                        self.complete(out)
                    else:
                        self._terminated(None)
                    return
                st["want"] = False
                self.push(out, item)

            def _terminated(self, ex):
                st["queue"] = None
                delay = backoff.next_delay()
                if delay is None:  # restart budget exhausted: propagate
                    st["stopped"] = True
                    if ex is not None:
                        self.fail(out, ex)
                    else:
                        self.complete(out)
                    return
                self.schedule_once("restart", delay)

            def on_timer(self, key):
                if key == "restart" and not st["stopped"]:
                    self._start_inner()

            def post_stop(self):
                q = st["queue"]
                if q is not None:
                    q.cancel()

        logic = _L(self._shape)

        def on_pull():
            st["want"] = True
            if st["queue"] is not None and not st["pulling"]:
                logic._request()

        def on_cancel(cause=None):
            st["stopped"] = True
            q = st["queue"]
            if q is not None:
                q.cancel()
            logic.complete(out)
        logic.set_handler(out, make_out_handler(on_pull, on_cancel))
        return logic


class _RestartWithBackoffSink(_SinkStage):
    """RestartSink.withBackoff (RestartSink.scala): inner =
    _BridgeSource().to(factory()); inner cancellation (a sink failing
    cancels its upstream) triggers a backoff restart. The element in
    flight at the instant of failure may be lost (reference contract);
    an element waiting for demand is retained across restarts."""

    def __init__(self, factory: Callable[[], Any],
                 settings: RestartSettings):
        super().__init__("RestartWithBackoffSink")
        self.factory = factory
        self.settings = settings

    def create_logic(self):
        stage = self
        in_ = self.in_
        st = {"handle": None, "gen": 0, "demand": 0, "stash": None,
              "stopped": False, "finishing": False}
        backoff = _BackoffState(self.settings)

        class _L(GraphStageLogic):
            def pre_start(self):
                self.set_keep_going(True)  # survive upstream completion
                self._start_inner()

            def _start_inner(self):
                from .dsl import Keep, Sink, Source
                st["gen"] += 1
                st["demand"] = 0
                handle = _BridgeHandle(
                    self.get_async_callback(self._on_inner), st["gen"])
                st["handle"] = handle
                Source.from_graph(lambda: _BridgeSource(handle)).to_mat(
                    stage.factory(), Keep.none).run(self.materializer)

            def _on_inner(self, pair):
                gen, ev = pair
                if gen != st["gen"] or st["stopped"]:
                    return
                if ev[0] == "demand":
                    st["demand"] += 1
                    if st["stash"] is not None:
                        elem, st["stash"] = st["stash"], None
                        st["demand"] -= 1
                        st["handle"].to_inner(("elem", elem))
                        if st["finishing"]:
                            self._finish_inner()
                    elif st["finishing"]:
                        self._finish_inner()
                    elif not self.has_been_pulled(in_) and \
                            not self.is_closed(in_):
                        self.pull(in_)
                elif ev[0] == "cancel":
                    # inner sink failed/cancelled: restart with backoff
                    st["handle"] = None
                    delay = backoff.next_delay()
                    if delay is None:
                        st["stopped"] = True
                        self.set_keep_going(False)
                        self.complete_stage()
                        return
                    self.schedule_once("restart", delay)

            def _finish_inner(self):
                st["handle"].to_inner(("complete",))
                st["stopped"] = True
                self.set_keep_going(False)
                self.complete_stage()

            def on_timer(self, key):
                if key == "restart" and not st["stopped"]:
                    self._start_inner()

            def post_stop(self):
                h = st["handle"]
                if h is not None and not st["stopped"]:
                    h.to_inner(("complete",))

        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            if st["handle"] is not None and st["demand"] > 0:
                st["demand"] -= 1
                st["handle"].to_inner(("elem", elem))
            else:
                st["stash"] = elem  # retained across the restart
            if st["demand"] > 0 and not logic.is_closed(in_):
                logic.pull(in_)

        def on_finish():
            if st["stash"] is None and st["handle"] is not None:
                logic._finish_inner()
            else:
                st["finishing"] = True  # flush the stash first

        def on_failure(ex):
            h = st["handle"]
            st["stopped"] = True
            if h is not None:
                h.to_inner(("fail", ex))
            logic.set_keep_going(False)
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic


class _RestartWithBackoffFlow(GraphStage):
    """RestartFlow.withBackoff / onFailuresWithBackoff (RestartFlow.scala):
    inner = _BridgeSource().via(factory()).to(Sink.queue()); failure on
    EITHER side (flow failing downstream, or flow cancelling upstream)
    triggers the same backoff restart."""

    def __init__(self, factory: Callable[[], Any], settings: RestartSettings,
                 only_on_failures: bool):
        self.name = "RestartWithBackoffFlow"
        self.factory = factory
        self.settings = settings
        self.only_on_failures = only_on_failures
        self.in_ = Inlet("RestartFlow.in")
        self.out = Outlet("RestartFlow.out")
        self._shape = FlowShape(self.in_, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        stage = self
        in_, out = self.in_, self.out
        st = {"handle": None, "queue": None, "gen": 0, "demand": 0,
              "stash": None, "pulling": False, "want": False,
              "stopped": False, "finishing": False, "restarting": False}
        backoff = _BackoffState(self.settings)

        class _L(GraphStageLogic):
            def pre_start(self):
                self._start_inner()

            def _start_inner(self):
                from .dsl import Keep, Sink, Source
                st["gen"] += 1
                st["demand"] = 0
                st["pulling"] = False
                st["restarting"] = False
                handle = _BridgeHandle(
                    self.get_async_callback(self._on_demand), st["gen"])
                st["handle"] = handle
                st["queue"] = Source.from_graph(
                    lambda: _BridgeSource(handle)).via(stage.factory()) \
                    .to_mat(Sink.queue(), Keep.right).run(self.materializer)
                if st["finishing"] and st["stash"] is None:
                    handle.to_inner(("complete",))
                if st["want"]:
                    self._request()

            # ---- upstream side (elements INTO the inner flow) ----
            def _on_demand(self, pair):
                gen, ev = pair
                if gen != st["gen"] or st["stopped"]:
                    return
                if ev[0] == "demand":
                    st["demand"] += 1
                    if st["stash"] is not None:
                        elem, st["stash"] = st["stash"], None
                        st["demand"] -= 1
                        st["handle"].to_inner(("elem", elem))
                        if st["finishing"]:
                            st["handle"].to_inner(("complete",))
                    elif st["finishing"]:
                        pass  # already sent complete at start_inner
                    elif not self.has_been_pulled(in_) and \
                            not self.is_closed(in_):
                        self.pull(in_)
                elif ev[0] == "cancel":
                    # the inner flow cancelled its upstream without failing
                    # downstream (e.g. a take()): treat like termination
                    self._maybe_restart(None)

            # ---- downstream side (elements OUT of the inner flow) ----
            def _request(self):
                if st["pulling"] or st["queue"] is None:
                    return
                st["pulling"] = True
                gen = st["gen"]
                cb = self.get_async_callback(self._on_out)
                st["queue"].pull().add_done_callback(
                    lambda f: cb.invoke((gen, f)))

            def _on_out(self, pair):
                gen, f = pair
                if gen != st["gen"] or st["stopped"]:
                    return
                st["pulling"] = False
                ex = f.exception()
                if ex is not None:
                    self._maybe_restart(ex)
                    return
                item = f.result()
                if item is _QUEUE_END:
                    if st["finishing"]:
                        # inner flow drained after upstream completion:
                        # the wrap is done
                        st["stopped"] = True
                        self.complete(out)
                    elif stage.only_on_failures:
                        st["stopped"] = True
                        self.complete_stage()
                    else:
                        self._maybe_restart(None)
                    return
                st["want"] = False
                self.push(out, item)

            def _maybe_restart(self, ex):
                # the inner death surfaces on BOTH sides (queue pull future
                # failure AND the bridge's cancel event): restart once
                if st["stopped"] or st["restarting"]:
                    return
                st["restarting"] = True
                st["queue"] = None
                st["handle"] = None
                delay = backoff.next_delay()
                if delay is None:
                    st["stopped"] = True
                    if ex is not None:
                        self.fail_stage(ex)
                    else:
                        self.complete_stage()
                    return
                self.schedule_once("restart", delay)

            def on_timer(self, key):
                if key == "restart" and not st["stopped"]:
                    self._start_inner()

            def post_stop(self):
                q = st["queue"]
                if q is not None:
                    q.cancel()
                h = st["handle"]
                if h is not None and not st["stopped"]:
                    h.to_inner(("complete",))

        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            if st["handle"] is not None and st["demand"] > 0:
                st["demand"] -= 1
                st["handle"].to_inner(("elem", elem))
            else:
                st["stash"] = elem
            if st["demand"] > 0 and not logic.is_closed(in_):
                logic.pull(in_)

        def on_finish():
            st["finishing"] = True
            if st["stash"] is None and st["handle"] is not None:
                st["handle"].to_inner(("complete",))
            # keep the stage alive: the inner flow may still emit

        def on_failure(ex):
            st["stopped"] = True
            h = st["handle"]
            if h is not None:
                h.to_inner(("fail", ex))
            logic.fail_stage(ex)

        def on_pull():
            st["want"] = True
            logic._request()

        def on_cancel(cause=None):
            st["stopped"] = True
            q = st["queue"]
            if q is not None:
                q.cancel()
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        logic.set_handler(out, make_out_handler(on_pull, on_cancel))
        return logic


class RestartSource:
    """(reference: scaladsl/RestartSource.scala:20)"""

    @staticmethod
    def with_backoff(settings: RestartSettings,
                     factory: Callable[[], Any]):
        """Restart the source on failure AND completion, backing off
        exponentially. factory: () -> Source."""
        from .dsl import Source
        return Source.from_graph(
            lambda: _RestartWithBackoffSource(factory, settings,
                                              only_on_failures=False))

    @staticmethod
    def on_failures_with_backoff(settings: RestartSettings,
                                 factory: Callable[[], Any]):
        """Restart only on failure; completion completes the wrap."""
        from .dsl import Source
        return Source.from_graph(
            lambda: _RestartWithBackoffSource(factory, settings,
                                              only_on_failures=True))


class RestartFlow:
    """(reference: scaladsl/RestartFlow.scala)"""

    @staticmethod
    def with_backoff(settings: RestartSettings, factory: Callable[[], Any]):
        from .dsl import Flow
        return Flow.from_graph(
            lambda: _RestartWithBackoffFlow(factory, settings,
                                            only_on_failures=False))

    @staticmethod
    def on_failures_with_backoff(settings: RestartSettings,
                                 factory: Callable[[], Any]):
        from .dsl import Flow
        return Flow.from_graph(
            lambda: _RestartWithBackoffFlow(factory, settings,
                                            only_on_failures=True))


class RestartSink:
    """(reference: scaladsl/RestartSink.scala)"""

    @staticmethod
    def with_backoff(settings: RestartSettings, factory: Callable[[], Any]):
        from .dsl import Sink
        return Sink.from_graph(
            lambda: _RestartWithBackoffSink(factory, settings))
