"""Source/Flow/Sink DSL + materializer.

Reference parity: akka-stream/src/main/scala/akka/stream/scaladsl/
(Source.scala, Flow.scala, Sink.scala, Keep.scala, RunnableGraph in
Flow.scala) and impl/PhasedFusingActorMaterializer.scala — here every
materialization fuses the whole graph into ONE island hosted by one
ActorGraphInterpreter actor (the reference's default is maximal fusion too;
async islands come from mapAsync/hubs, which in this design use async
callbacks into the same interpreter instead of actor-to-actor batches).

Blueprints are REUSABLE: each Source/Flow/Sink holds a build function that
instantiates fresh stages per run (the reference's traversal re-walk).
Materialized values compose with Keep.left/right/both/none.
"""

from __future__ import annotations

import collections
import itertools
import threading
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..actor.props import Props
from .interpreter import ActorGraphInterpreter, Connection, GraphInterpreter
from .stage import (FlowShape, GraphStage, GraphStageLogic, Inlet, Outlet,
                    SinkShape, SourceShape, make_in_handler, make_out_handler)
from . import ops as _ops
from . import ops2 as _ops2
from . import ops3 as _ops3
from . import ops4 as _ops4


def _map_future(fut: Future, fn) -> Future:
    """Future[A] -> Future[fn(A)] (mat-value adaption for composed sinks)."""
    out: Future = Future()

    def done(f):
        ex = f.exception()
        if ex is not None:
            out.set_exception(ex)
        else:
            try:
                out.set_result(fn(f.result()))
            except Exception as e:  # noqa: BLE001
                out.set_exception(e)
    fut.add_done_callback(done)
    return out


class Keep:
    left = staticmethod(lambda l, r: l)
    right = staticmethod(lambda l, r: r)
    both = staticmethod(lambda l, r: (l, r))
    none = staticmethod(lambda l, r: None)


class _Builder:
    """Collects stage logics + edges during one materialization. Stages are
    tagged with the CURRENT ISLAND; `next_island()` (the `.async_()`
    boundary) starts a new one — edges that end up crossing islands become
    backpressured actor-to-actor channels (the reference's island tracking
    in PhasedFusingActorMaterializer.scala:391 islandTracking)."""

    def __init__(self, materializer: "Materializer"):
        self.materializer = materializer
        self.logics: List[GraphStageLogic] = []
        self.logic_by_port: Dict[int, GraphStageLogic] = {}
        self.edges: List[Tuple[Outlet, Inlet]] = []
        self.current_island = 0
        self.island_of: Dict[int, int] = {}  # id(logic) -> island
        # the with_attributes section currently being built; stamped onto
        # every stage added inside it (Attributes.scala section scoping)
        self.current_attributes = None

    def add(self, stage: GraphStage) -> Tuple[GraphStageLogic, Any]:
        logic, mat = stage.create_logic_and_mat()
        if self.current_attributes is not None and logic.attributes is None:
            logic.attributes = self.current_attributes
        self.logics.append(logic)
        self.island_of[id(logic)] = self.current_island
        for p in logic.shape.inlets:
            self.logic_by_port[p.id] = logic
        for p in logic.shape.outlets:
            self.logic_by_port[p.id] = logic
        return logic, mat

    def connect(self, outlet: Outlet, inlet: Inlet) -> None:
        self.edges.append((outlet, inlet))

    def next_island(self) -> None:
        self.current_island += 1


_CHANNEL_BATCH = 16


class _IslandChannel:
    """Backpressured element channel across an async boundary: both ends
    talk ONLY through the target interpreter's async-callback mailbox (the
    reference's BatchingActorInputBoundary / ActorOutputBoundary pair in
    impl/fusing/ActorGraphInterpreter.scala). Demand flows upstream in
    batches; elements, completion, and failure flow downstream."""

    def __init__(self):
        self.sink = None    # _ChannelSink (upstream island)
        self.source = None  # _ChannelSource (downstream island)
        # events sent before the peer island's actor started are held and
        # flushed from its pre_start (islands spawn in arbitrary order)
        self._lock = threading.Lock()
        self._sink_ready = False
        self._source_ready = False
        self._pend_sink: List[Any] = []
        self._pend_source: List[Any] = []

    def to_source(self, ev) -> None:
        with self._lock:
            if not self._source_ready:
                self._pend_source.append(ev)
                return
        self.source._cb.invoke(ev)

    def to_sink(self, ev) -> None:
        with self._lock:
            if not self._sink_ready:
                self._pend_sink.append(ev)
                return
        self.sink._cb.invoke(ev)

    def source_started(self) -> None:
        with self._lock:
            self._source_ready = True
            pending, self._pend_source = self._pend_source, []
        for ev in pending:
            self.source._cb.invoke(ev)

    def sink_started(self) -> None:
        with self._lock:
            self._sink_ready = True
            pending, self._pend_sink = self._pend_sink, []
        for ev in pending:
            self.sink._cb.invoke(ev)


class _ChannelSink(GraphStageLogic):
    """Upstream-island end of an async boundary (output boundary)."""

    def __init__(self, channel: _IslandChannel):
        in_ = Inlet("Island.in")
        super().__init__(SinkShape(in_))
        self.in_ = in_
        self.channel = channel
        self.demand = 0
        channel.sink = self
        self._cb = self.get_async_callback(self._on_event)

        def on_push():
            self.demand -= 1
            channel.to_source(("elem", self.grab(in_)))
            if self.demand > 0:
                self.pull(in_)

        def on_finish():
            channel.to_source(("complete", None))

        def on_fail(ex):
            channel.to_source(("fail", ex))

        self.set_handler(in_, make_in_handler(on_push, on_finish, on_fail))

    def pre_start(self):
        self.channel.sink_started()

    def _on_event(self, ev):
        kind, arg = ev
        if kind == "demand":
            self.demand += arg
            if self.demand > 0 and not self.has_been_pulled(self.in_) \
                    and not self.is_closed(self.in_):
                self.pull(self.in_)
        elif kind == "cancel":
            self.cancel(self.in_)


class _ChannelSource(GraphStageLogic):
    """Downstream-island end of an async boundary (input boundary):
    buffers up to a batch of elements and keeps demand outstanding. The
    batch size is the downstream stage's Attributes.input_buffer max (the
    reference's InputBuffer attribute sizes exactly this boundary buffer,
    BatchingActorInputBoundary)."""

    def __init__(self, channel: _IslandChannel, batch: int = _CHANNEL_BATCH):
        out = Outlet("Island.out")
        super().__init__(SourceShape(out))
        self.out = out
        self.channel = channel
        self.batch = max(int(batch), 1)
        self.buf = collections.deque()
        self.outstanding = 0
        self.done = False
        self.failure: Optional[BaseException] = None
        channel.source = self
        self._cb = self.get_async_callback(self._on_event)

        def on_cancel(cause=None):
            channel.to_sink(("cancel", None))

        self.set_handler(out, make_out_handler(self._pump, on_cancel))

    def pre_start(self):
        self.channel.source_started()
        self.outstanding = self.batch
        self.channel.to_sink(("demand", self.batch))

    def _pump(self):
        if self.failure is not None:
            self.fail(self.out, self.failure)
            return
        if self.buf and self.is_available(self.out):
            self.push(self.out, self.buf.popleft())
        if self.done and not self.buf:
            self.complete(self.out)
            return
        want = self.batch - len(self.buf) - self.outstanding
        if want >= max(self.batch // 2, 1) and not self.done:
            self.outstanding += want
            self.channel.to_sink(("demand", want))

    def _on_event(self, ev):
        kind, arg = ev
        if kind == "elem":
            self.outstanding -= 1
            self.buf.append(arg)
        elif kind == "complete":
            self.done = True
        elif kind == "fail":
            self.failure = arg
        self._pump()


class Materializer:
    """(reference: stream/Materializer.scala / SystemMaterializer.scala).

    Materialization walks the blueprint once, groups stages into fused
    ISLANDS split at `.async_()` boundaries, and spawns ONE
    ActorGraphInterpreter per island — cross-island edges run through
    backpressured async channels (PhasedFusingActorMaterializer.scala:391
    materialize + island assignment; a single-island graph stays one
    actor, the reference's default maximal fusion)."""

    _counter = itertools.count()

    def __init__(self, system):
        self.system = system

    @staticmethod
    def _island_props(interp, logics) -> "Props":
        """Island actor Props, honoring ActorAttributes.dispatcher: the
        first stage in the island that names one selects the dispatcher
        its interpreter runs on (reference: PhasedFusingActorMaterializer
        resolving Attributes.dispatcher per island)."""
        props = Props.create(ActorGraphInterpreter, interp)
        for lg in logics:
            attrs = getattr(lg, "attributes", None)
            if attrs is not None:
                d = attrs.get("dispatcher")
                if d:
                    return props.with_dispatcher(d)
        return props

    def materialize(self, build: Callable[[_Builder], Any]) -> Any:
        b = _Builder(self)
        mat = build(b)
        islands = sorted({b.island_of[id(lg)] for lg in b.logics})
        run_id = next(Materializer._counter)
        if len(islands) <= 1:
            connections = []
            for i, (outlet, inlet) in enumerate(b.edges):
                connections.append(Connection(
                    i, b.logic_by_port[outlet.id], outlet,
                    b.logic_by_port[inlet.id], inlet))
            interp = GraphInterpreter(b.logics, connections,
                                      materializer=self)
            self.system.actor_of(
                self._island_props(interp, b.logics), f"stream-{run_id}")
            return mat

        # multi-island: split edges at boundaries
        by_island: Dict[int, List[GraphStageLogic]] = {
            isl: [] for isl in islands}
        for lg in b.logics:
            by_island[b.island_of[id(lg)]].append(lg)
        island_edges: Dict[int, List[Tuple[Outlet, Inlet]]] = {
            isl: [] for isl in islands}
        for outlet, inlet in b.edges:
            out_isl = b.island_of[id(b.logic_by_port[outlet.id])]
            in_isl = b.island_of[id(b.logic_by_port[inlet.id])]
            if out_isl == in_isl:
                island_edges[out_isl].append((outlet, inlet))
            else:
                ch = _IslandChannel()
                snk = _ChannelSink(ch)
                # boundary buffer sized by the downstream stage's
                # Attributes.input_buffer (max), the reference's InputBuffer
                in_logic = b.logic_by_port[inlet.id]
                attrs = getattr(in_logic, "attributes", None)
                batch = attrs.effective_input_buffer(
                    (_CHANNEL_BATCH, _CHANNEL_BATCH))[1] \
                    if attrs is not None else _CHANNEL_BATCH
                src = _ChannelSource(ch, batch=batch)
                by_island[out_isl].append(snk)
                by_island[in_isl].append(src)
                island_edges[out_isl].append((outlet, snk.in_))
                island_edges[in_isl].append((src.out, inlet))

        for isl in islands:
            port_owner: Dict[int, GraphStageLogic] = {}
            for lg in by_island[isl]:
                for p in lg.shape.inlets:
                    port_owner[p.id] = lg
                for p in lg.shape.outlets:
                    port_owner[p.id] = lg
            connections = [
                Connection(i, port_owner[o.id], o, port_owner[i_.id], i_)
                for i, (o, i_) in enumerate(island_edges[isl])]
            interp = GraphInterpreter(by_island[isl], connections,
                                      materializer=self)
            self.system.actor_of(
                self._island_props(interp, by_island[isl]),
                f"stream-{run_id}-island-{isl}")
        return mat


# -- Source -------------------------------------------------------------------

class Source:
    """build(b) -> (open outlet, mat value)."""

    def __init__(self, build: Callable[[_Builder], Tuple[Outlet, Any]]):
        self._build = build

    # -- constructors ---------------------------------------------------------
    @staticmethod
    def from_graph(stage_factory: Callable[[], GraphStage]) -> "Source":
        def build(b: _Builder):
            logic, mat = b.add(stage_factory())
            return logic.shape.outlets[0], mat
        return Source(build)

    @staticmethod
    def from_iterable(it) -> "Source":
        return Source.from_graph(lambda: _ops.IterableSource(it))

    @staticmethod
    def apply(it) -> "Source":
        return Source.from_iterable(it)

    @staticmethod
    def single(elem) -> "Source":
        return Source.from_iterable([elem])

    @staticmethod
    def empty() -> "Source":
        return Source.from_iterable([])

    @staticmethod
    def failed(ex: BaseException) -> "Source":
        return Source.from_graph(lambda: _ops.FailedSource(ex))

    @staticmethod
    def repeat(elem) -> "Source":
        return Source.from_graph(lambda: _ops.RepeatSource(elem))

    @staticmethod
    def cycle(factory: Callable[[], Any]) -> "Source":
        return Source.from_graph(lambda: _ops.CycleSource(factory))

    @staticmethod
    def unfold(zero, fn: Callable[[Any], Optional[Tuple[Any, Any]]]) -> "Source":
        return Source.from_graph(lambda: _ops.UnfoldSource(zero, fn))

    @staticmethod
    def tick(initial_delay: float, interval: float, tick: Any) -> "Source":
        return Source.from_graph(lambda: _ops.TickSource(
            initial_delay, interval, tick))

    @staticmethod
    def queue(buffer_size: int = 256) -> "Source":
        """Materializes a SourceQueue with offer/complete/fail."""
        return Source.from_graph(lambda: _ops.QueueSource(buffer_size))

    @staticmethod
    def from_future(fut: Future) -> "Source":
        return Source.from_graph(lambda: _ops.FutureSource(fut))

    @staticmethod
    def never() -> "Source":
        """Emits nothing and never completes (scaladsl Source.never)."""
        return Source.from_graph(lambda: _ops3.NeverSource())

    @staticmethod
    def maybe() -> "Source":
        """Mat: a MaybePromise — success(elem) emits-and-completes,
        success(None) completes empty, failure(ex) fails
        (scaladsl Source.maybe)."""
        return Source.from_graph(lambda: _ops4.MaybeSource())

    @staticmethod
    def range(start: int, end: int, step: int = 1) -> "Source":
        """Emit start..end INCLUSIVE by step (javadsl Source.range)."""
        return Source.from_iterable(range(
            start, end + (1 if step > 0 else -1), step))

    @staticmethod
    def from_iterator(factory) -> "Source":
        """A FRESH iterator per materialization (Source.fromIterator) —
        unlike from_iterable, the factory is called each run."""
        class _PerRun:
            def __iter__(self):
                return iter(factory())
        return Source.from_graph(lambda: _ops.IterableSource(_PerRun()))

    @staticmethod
    def unfold_async(zero, fn) -> "Source":
        """unfoldAsync: fn(state) -> Future[None | (state, elem)]."""
        return Source.from_graph(lambda: _ops4.UnfoldAsync(zero, fn))

    @staticmethod
    def unfold_resource_async(create, read, close) -> "Source":
        """unfoldResourceAsync: create/read/close may return Futures; read
        resolving None completes; close runs on every termination path."""
        return Source.from_graph(
            lambda: _ops4.UnfoldResourceAsync(create, read, close))

    @staticmethod
    def actor_ref_with_backpressure(ack_message) -> "Source":
        """Mat: Future[ActorRef]; the ref replies `ack_message` to each
        sender once its element is accepted
        (Source.actorRefWithBackpressure)."""
        return Source.from_graph(
            lambda: _ops4.ActorRefBackpressureSource(ack_message))

    @staticmethod
    def zip_n(sources: Sequence["Source"]) -> "Source":
        """zipN: emit lists of one element from every source."""
        return Source.zip_with_n(lambda xs: list(xs), sources)

    @staticmethod
    def zip_with_n(fn, sources: Sequence["Source"]) -> "Source":
        """zipWithN: emit fn([heads...]) per zipped row."""
        builds = [s._build for s in sources]

        def build(b: _Builder):
            logic, _ = b.add(_ops4.ZipNStage(len(builds), fn))
            mat0 = None
            for i, sb in enumerate(builds):
                o, m = sb(b)
                if i == 0:
                    mat0 = m
                b.connect(o, logic.shape.ins[i])
            return logic.shape.out, mat0
        return Source(build)

    @staticmethod
    def merge_prioritized_n(sources_and_priorities) -> "Source":
        """mergePrioritizedN: [(source, priority)] — higher priority wins
        when several inputs have an element buffered."""
        pairs = list(sources_and_priorities)
        builds = [s._build for s, _p in pairs]
        prios = [p for _s, p in pairs]

        def build(b: _Builder):
            from .ops3 import MergePrioritizedStage
            logic, _ = b.add(MergePrioritizedStage(prios))
            mat0 = None
            for i, sb in enumerate(builds):
                o, m = sb(b)
                if i == 0:
                    mat0 = m
                b.connect(o, logic.shape.ins[i])
            return logic.shape.out, mat0
        return Source(build)

    @staticmethod
    def lazy_source(factory: Callable[[], "Source"]) -> "Source":
        """Defer building the inner Source until the stream is pulled
        (scaladsl Source.lazySource)."""
        return Source.single(None).flat_map_concat(lambda _: factory())

    @staticmethod
    def lazy_single(thunk: Callable[[], Any]) -> "Source":
        """Defer computing the single element until pulled
        (scaladsl Source.lazySingle)."""
        return Source.single(None).map(lambda _: thunk())

    @staticmethod
    def lazy_future(thunk: Callable[[], Future]) -> "Source":
        """Defer creating the Future until pulled (Source.lazyFuture)."""
        return Source.lazy_source(lambda: Source.from_future(thunk()))

    @staticmethod
    def unfold_resource(create: Callable[[], Any],
                        read: Callable[[Any], Optional[Any]],
                        close: Callable[[Any], None]) -> "Source":
        """Open a resource per materialization, emit read() values until it
        returns None, close on EVERY termination path — exhaustion, failure,
        AND downstream cancel (Source.unfoldResource; a real stage whose
        post_stop closes, not a generator finally that waited for GC —
        ADVICE r3)."""
        from .ops3 import UnfoldResourceSource
        return Source.from_graph(
            lambda: UnfoldResourceSource(create, read, close))

    @staticmethod
    def actor_ref(buffer_size: int = 256) -> "Source":
        """Materializes an ActorRef; messages sent to it are emitted
        (reference: Source.actorRef; complete with Status.Success)."""
        return Source.from_graph(lambda: _ops.ActorRefSource(buffer_size))

    @staticmethod
    def combine(first: "Source", second: "Source", *rest: "Source") -> "Source":
        return first.merge(second) if not rest else \
            Source.combine(first.merge(second), *rest)

    # -- composition ----------------------------------------------------------
    def via(self, flow: "Flow", combine=Keep.left) -> "Source":
        src_build, flow_build = self._build, flow._build

        def build(b: _Builder):
            outlet, m1 = src_build(b)
            outlet2, m2 = flow_build(b, outlet)
            return outlet2, combine(m1, m2)
        return Source(build)

    def via_mat(self, flow: "Flow", combine) -> "Source":
        return self.via(flow, combine)

    def to(self, sink: "Sink", combine=Keep.left) -> "RunnableGraph":
        src_build, sink_build = self._build, sink._build

        def build(b: _Builder):
            outlet, m1 = src_build(b)
            m2 = sink_build(b, outlet)
            return combine(m1, m2)
        return RunnableGraph(build)

    def to_mat(self, sink: "Sink", combine) -> "RunnableGraph":
        return self.to(sink, combine)

    def run_with(self, sink: "Sink", materializer_or_system) -> Any:
        return self.to(sink, Keep.right).run(materializer_or_system)

    # -- fan-in convenience ---------------------------------------------------
    def merge(self, other: "Source") -> "Source":
        b1, b2 = self._build, other._build

        def build(b: _Builder):
            o1, m1 = b1(b)
            o2, _m2 = b2(b)
            logic, _ = b.add(_ops.MergeStage(2))
            b.connect(o1, logic.shape.ins[0])
            b.connect(o2, logic.shape.ins[1])
            return logic.shape.out, m1
        return Source(build)

    def concat(self, other: "Source") -> "Source":
        b1, b2 = self._build, other._build

        def build(b: _Builder):
            o1, m1 = b1(b)
            o2, _m2 = b2(b)
            logic, _ = b.add(_ops.ConcatStage(2))
            b.connect(o1, logic.shape.ins[0])
            b.connect(o2, logic.shape.ins[1])
            return logic.shape.out, m1
        return Source(build)

    def prepend(self, other: "Source") -> "Source":
        return other.concat(self)

    def concat_lazy(self, other: "Source") -> "Source":
        """concatLazy: `other` is not built until this source completes
        and it is actually pulled (scaladsl concatLazy)."""
        return self.concat(Source.lazy_source(lambda: other))

    def prepend_lazy(self, other: "Source") -> "Source":
        """prependLazy (scaladsl prependLazy)."""
        return Source.lazy_source(lambda: other).concat(self)

    def map_materialized_value(self, fn) -> "Source":
        """mapMaterializedValue: transform this Source's mat value."""
        prev = self._build

        def build(b: _Builder):
            o, m = prev(b)
            return o, fn(m)
        return Source(build)

    def pre_materialize(self, materializer_or_system):
        """preMaterialize: run this source NOW; returns (mat, Source) where
        the Source replays the running stream's elements to one consumer
        (scaladsl Source.preMaterialize, via a queue bridge)."""
        pair = self.to_mat(Sink.queue(), Keep.both).run(materializer_or_system)
        mat, queue = pair

        def fn(state):
            fut = queue.pull()
            out: Future = Future()

            def done(f):
                if f.exception() is not None:
                    out.set_exception(f.exception())
                elif f.result() is _ops._QUEUE_END:
                    out.set_result(None)
                else:
                    out.set_result((state, f.result()))
            fut.add_done_callback(done)
            return out
        return mat, Source.unfold_async(None, fn)

    def or_else(self, other: "Source") -> "Source":
        b1, b2 = self._build, other._build

        def build(b: _Builder):
            o1, m1 = b1(b)
            o2, _m2 = b2(b)
            logic, _ = b.add(_ops.OrElseStage())
            b.connect(o1, logic.shape.ins[0])
            b.connect(o2, logic.shape.ins[1])
            return logic.shape.out, m1
        return Source(build)

    def zip(self, other: "Source") -> "Source":
        return self.zip_with(other, lambda a, b: (a, b))

    def zip_with(self, other: "Source", fn) -> "Source":
        b1, b2 = self._build, other._build

        def build(b: _Builder):
            o1, m1 = b1(b)
            o2, _m2 = b2(b)
            logic, _ = b.add(_ops.ZipWithStage(fn))
            b.connect(o1, logic.shape.ins[0])
            b.connect(o2, logic.shape.ins[1])
            return logic.shape.out, m1
        return Source(build)

    def interleave(self, other: "Source", segment_size: int) -> "Source":
        b1, b2 = self._build, other._build

        def build(b: _Builder):
            o1, m1 = b1(b)
            o2, _m2 = b2(b)
            logic, _ = b.add(_ops.InterleaveStage(segment_size))
            b.connect(o1, logic.shape.ins[0])
            b.connect(o2, logic.shape.ins[1])
            return logic.shape.out, m1
        return Source(build)

    def also_to(self, sink: "Sink") -> "Source":
        src_build, sink_build = self._build, sink._build

        def build(b: _Builder):
            o1, m1 = src_build(b)
            logic, _ = b.add(_ops.BroadcastStage(2, eager_cancel=False))
            b.connect(o1, logic.shape.in_)
            sink_build(b, logic.shape.outs[1])
            return logic.shape.outs[0], m1
        return Source(build)

    def wire_tap(self, fn: Callable[[Any], None]) -> "Source":
        return self.via(Flow().wire_tap(fn))

    # -- attributes -----------------------------------------------------------
    def with_attributes(self, attrs) -> "Source":
        """Attach Attributes to every stage this Source has built SO FAR
        (section scoping: operators appended after this call are outside —
        Attributes.scala:662; supervision deciders are the headline use)."""
        return Source(_scoped_attributes(self._build, attrs))

    add_attributes = with_attributes

    def named(self, name: str) -> "Source":
        from .attributes import Attributes
        return self.with_attributes(Attributes.name(name))

    # -- run ------------------------------------------------------------------
    def run(self, materializer_or_system) -> Any:
        return self.to(Sink.ignore(), Keep.left).run(materializer_or_system)

    def run_fold(self, zero, fn, materializer_or_system) -> Future:
        return self.run_with(Sink.fold(zero, fn), materializer_or_system)

    def run_foreach(self, fn, materializer_or_system) -> Future:
        return self.run_with(Sink.foreach(fn), materializer_or_system)

    def run_reduce(self, fn, materializer_or_system) -> Future:
        return self.run_with(Sink.reduce(fn), materializer_or_system)


def _linear(op_factory: Callable[[], GraphStage]):
    """Helper: append one 1-in/1-out stage to a Flow/Source chain."""
    def flow_build(b: _Builder, upstream: Outlet):
        logic, mat = b.add(op_factory())
        b.connect(upstream, logic.shape.in_)
        return logic.shape.out, mat
    return flow_build


def _scoped_attributes(prev_build, attrs):
    """Wrap a build so stages created inside it carry `attrs` layered over
    any enclosing section's attributes (innermost wins — the reference's
    `and` composition order)."""
    def build(b: _Builder, *args):
        saved = b.current_attributes
        b.current_attributes = attrs if saved is None \
            else saved.and_then(attrs)
        try:
            return prev_build(b, *args)
        finally:
            b.current_attributes = saved
    return build


class Flow:
    """build(b, upstream_outlet) -> (outlet, mat)."""

    def __init__(self, build: Optional[Callable] = None):
        if build is None:
            def build(b: _Builder, upstream: Outlet):
                return upstream, None
        self._build = build

    @staticmethod
    def from_graph(stage_factory: Callable[[], GraphStage]) -> "Flow":
        def build(b: _Builder, upstream: Outlet):
            logic, mat = b.add(stage_factory())
            b.connect(upstream, logic.shape.inlets[0])
            return logic.shape.outlets[0], mat
        return Flow(build)

    @staticmethod
    def from_function(fn: Callable[[Any], Any]) -> "Flow":
        return Flow().map(fn)

    @staticmethod
    def from_sink_and_source(sink: "Sink", source: "Source") -> "Flow":
        """fromSinkAndSource: inputs go to `sink`, outputs come from
        `source`; the two sides are NOT coupled (scaladsl
        Flow.fromSinkAndSource)."""
        sink_build, src_build = sink._build, source._build

        def build(b: _Builder, upstream: Outlet):
            m1 = sink_build(b, upstream)
            o, m2 = src_build(b)
            return o, (m1, m2)
        return Flow(build)

    @staticmethod
    def from_sink_and_source_coupled(sink: "Sink", source: "Source") -> "Flow":
        """fromSinkAndSourceCoupled: like from_sink_and_source but
        termination of either side tears down the other (coupled through a
        per-materialization shared kill switch — the reference's
        CoupledTerminationFlow)."""
        sink_build, src_build = sink._build, source._build

        def build(b: _Builder, upstream: Outlet):
            from .killswitch import KillSwitches
            ks = KillSwitches.shared("coupled")
            watched = Flow().via(ks.flow).watch_termination()  # .flow is a property

            def couple(f):
                # a FAILED side aborts the other with the error; a clean
                # completion shuts it down (CoupledTerminationFlow
                # propagates failure, not completion)
                ex = f.exception()
                if ex is not None:
                    ks.abort(ex)
                else:
                    ks.shutdown()

            o1, fut1 = watched._build(b, upstream)
            m1 = sink_build(b, o1)
            fut1.add_done_callback(couple)

            o2, m2 = src_build(b)
            o3, fut2 = watched._build(b, o2)
            fut2.add_done_callback(couple)
            return o3, (m1, m2)
        return Flow(build)

    @staticmethod
    def lazy_flow(factory: Callable[[], "Flow"]) -> "Flow":
        """lazyFlow: defer building the inner Flow until the first element
        arrives; that element and all following flow through it
        (scaladsl Flow.lazyFlow, via flatMapPrefix(1))."""
        def with_first(prefix):
            inner = factory()
            inner_build = inner._build

            def build(b: _Builder, upstream: Outlet):
                head, _ = b.add(_ops.IterableSource(list(prefix)))
                concat, _ = b.add(_ops.ConcatStage(2))
                b.connect(head.shape.outlets[0], concat.shape.ins[0])
                b.connect(upstream, concat.shape.ins[1])
                return inner_build(b, concat.shape.out)
            return Flow(build)
        return Flow().flat_map_prefix(1, with_first)

    def _append(self, op_factory: Callable[[], GraphStage],
                combine=Keep.left) -> "Flow":
        prev = self._build
        nxt = _linear(op_factory)

        def build(b: _Builder, upstream: Outlet):
            o1, m1 = prev(b, upstream)
            o2, m2 = nxt(b, o1)
            return o2, combine(m1, m2)
        return Flow(build)

    def via(self, other: "Flow", combine=Keep.left) -> "Flow":
        prev, nxt = self._build, other._build

        def build(b: _Builder, upstream: Outlet):
            o1, m1 = prev(b, upstream)
            o2, m2 = nxt(b, o1)
            return o2, combine(m1, m2)
        return Flow(build)

    via_mat = via

    def to(self, sink: "Sink", combine=Keep.left) -> "Sink":
        prev, sink_build = self._build, sink._build

        def build(b: _Builder, upstream: Outlet):
            o1, m1 = prev(b, upstream)
            m2 = sink_build(b, o1)
            return combine(m1, m2)
        return Sink(build)

    to_mat = to

    # -- attributes -----------------------------------------------------------
    def with_attributes(self, attrs) -> "Flow":
        """Attach Attributes to every stage this Flow has built so far
        (Attributes.scala:662 section scoping)."""
        return Flow(_scoped_attributes(self._build, attrs))

    add_attributes = with_attributes

    def named(self, name: str) -> "Flow":
        from .attributes import Attributes
        return self.with_attributes(Attributes.name(name))

    # -- operator library (reference: scaladsl/Flow.scala ~200 defs;
    #    the stages live in akka_tpu/stream/ops.py) --------------------------
    def via_stage(self, stage_factory) -> "Flow":
        """Append any custom 1-in/1-out GraphStage (the GraphStage SPI of
        stream/stage/GraphStage.scala for user-defined operators)."""
        return self._append(stage_factory)

    def map(self, fn) -> "Flow":
        return self._append(lambda: _ops.Map(fn))

    def map_concat(self, fn) -> "Flow":
        return self._append(lambda: _ops.MapConcat(fn))

    def stateful_map_concat(self, factory) -> "Flow":
        return self._append(lambda: _ops.StatefulMapConcat(factory))

    def filter(self, pred) -> "Flow":
        return self._append(lambda: _ops.Filter(pred))

    def filter_not(self, pred) -> "Flow":
        return self._append(lambda: _ops.Filter(lambda x: not pred(x)))

    def collect(self, fn) -> "Flow":
        """fn returns None to drop (partial-function analogue)."""
        return self._append(lambda: _ops.Collect(fn))

    def take(self, n: int) -> "Flow":
        return self._append(lambda: _ops.Take(n))

    def take_while(self, pred, inclusive: bool = False) -> "Flow":
        return self._append(lambda: _ops.TakeWhile(pred, inclusive))

    def drop(self, n: int) -> "Flow":
        return self._append(lambda: _ops.Drop(n))

    def drop_while(self, pred) -> "Flow":
        return self._append(lambda: _ops.DropWhile(pred))

    def scan(self, zero, fn) -> "Flow":
        return self._append(lambda: _ops.Scan(zero, fn))

    def fold(self, zero, fn) -> "Flow":
        return self._append(lambda: _ops.Fold(zero, fn))

    def reduce(self, fn) -> "Flow":
        return self._append(lambda: _ops.Reduce(fn))

    def grouped(self, n: int) -> "Flow":
        return self._append(lambda: _ops.Grouped(n))

    def sliding(self, n: int, step: int = 1) -> "Flow":
        return self._append(lambda: _ops.Sliding(n, step))

    def intersperse(self, sep, start=None, end=None) -> "Flow":
        return self._append(lambda: _ops.Intersperse(sep, start, end))

    def zip_with_index(self) -> "Flow":
        return self.stateful_map_concat(
            lambda: (lambda counter=itertools.count():
                     (lambda x: [(x, next(counter))]))())

    def buffer(self, size: int, overflow_strategy: str = "backpressure"
               ) -> "Flow":
        return self._append(lambda: _ops.Buffer(size, overflow_strategy))

    def conflate(self, aggregate) -> "Flow":
        return self.conflate_with_seed(lambda x: x, aggregate)

    def conflate_with_seed(self, seed, aggregate) -> "Flow":
        return self._append(lambda: _ops.Conflate(seed, aggregate))

    def batch(self, max_n: int, seed, aggregate) -> "Flow":
        return self._append(lambda: _ops.Batch(max_n, seed, aggregate))

    def expand(self, extrapolate) -> "Flow":
        return self._append(lambda: _ops.Expand(extrapolate))

    def map_async(self, parallelism: int, fn) -> "Flow":
        return self._append(lambda: _ops.MapAsync(parallelism, fn,
                                                  ordered=True))

    def map_async_unordered(self, parallelism: int, fn) -> "Flow":
        return self._append(lambda: _ops.MapAsync(parallelism, fn,
                                                  ordered=False))

    def throttle(self, elements: int, per: float,
                 maximum_burst: Optional[int] = None) -> "Flow":
        return self._append(lambda: _ops.Throttle(
            elements, per, maximum_burst or elements))

    def delay(self, of: float) -> "Flow":
        return self._append(lambda: _ops.Delay(of))

    def recover(self, fn) -> "Flow":
        """fn(exc) -> final element (or raise to propagate)."""
        return self._append(lambda: _ops.Recover(fn))

    def log(self, name: str, extract=lambda x: x) -> "Flow":
        return self._append(lambda: _ops.Log(name, extract))

    def wire_tap(self, fn) -> "Flow":
        return self._append(lambda: _ops.WireTap(fn))

    def also_to(self, sink: "Sink") -> "Flow":
        prev, sink_build = self._build, sink._build

        def build(b: _Builder, upstream: Outlet):
            o1, m1 = prev(b, upstream)
            logic, _ = b.add(_ops.BroadcastStage(2, eager_cancel=False))
            b.connect(o1, logic.shape.in_)
            sink_build(b, logic.shape.outs[1])
            return logic.shape.outs[0], m1
        return Flow(build)

    def flat_map_concat(self, fn: Callable[[Any], "Source"]) -> "Flow":
        return self._append(lambda: _ops.FlatMapConcat(fn))

    def _fan_in(self, other: Source, stage_factory,
                self_first: bool = True) -> "Flow":
        """Join this flow's output with another Source through a 2-in
        stage (the scaladsl pattern of merge/zip/concat/orElse/... taking
        a Graph[SourceShape] argument)."""
        prev, other_build = self._build, other._build

        def build(b: _Builder, upstream: Outlet):
            o1, m1 = prev(b, upstream)
            o2, _ = other_build(b)
            logic, _l = b.add(stage_factory())
            first, second = (o1, o2) if self_first else (o2, o1)
            b.connect(first, logic.shape.ins[0])
            b.connect(second, logic.shape.ins[1])
            return logic.shape.out, m1
        return Flow(build)

    def merge(self, other: Source) -> "Flow":
        return self._fan_in(other, lambda: _ops.MergeStage(2))

    def zip(self, other: Source) -> "Flow":
        return self._fan_in(
            other, lambda: _ops.ZipWithStage(lambda a, bb: (a, bb)))

    def zip_with(self, other: Source, fn) -> "Flow":
        return self._fan_in(other, lambda: _ops.ZipWithStage(fn))

    def zip_latest(self, other: Source) -> "Flow":
        return self.zip_latest_with(other, lambda a, b: (a, b))

    def zip_latest_with(self, other: Source, fn) -> "Flow":
        return self._fan_in(other, lambda: _ops3.ZipLatestStage(fn))

    def zip_all(self, other: Source, this_default, that_default) -> "Flow":
        return self._fan_in(other, lambda: _ops3.ZipAllStage(
            this_default, that_default))

    def concat(self, other: Source) -> "Flow":
        return self._fan_in(other, lambda: _ops.ConcatStage(2))

    def prepend(self, other: Source) -> "Flow":
        return self._fan_in(other, lambda: _ops.ConcatStage(2),
                            self_first=False)

    def or_else(self, other: Source) -> "Flow":
        return self._fan_in(other, lambda: _ops.OrElseStage())

    def interleave(self, other: Source, segment_size: int) -> "Flow":
        return self._fan_in(other, lambda: _ops.InterleaveStage(segment_size))

    def merge_sorted(self, other: Source, key=None) -> "Flow":
        return self._fan_in(other, lambda: _ops3.MergeSortedStage(key))

    def merge_prioritized(self, other: Source, this_prio: int,
                          that_prio: int) -> "Flow":
        return self._fan_in(other, lambda: _ops3.MergePrioritizedStage(
            [this_prio, that_prio]))

    def divert_to(self, sink: "Sink", when) -> "Flow":
        """Route elements matching `when` into `sink`, pass the rest on
        (scaladsl/Flow.scala divertTo)."""
        prev, sink_build = self._build, sink._build

        def build(b: _Builder, upstream: Outlet):
            o1, m1 = prev(b, upstream)
            logic, _ = b.add(_ops3.DivertToStage(when))
            b.connect(o1, logic.shape.in_)
            sink_build(b, logic.shape.outs[1])
            return logic.shape.outs[0], m1
        return Flow(build)

    def fold_async(self, zero, fn) -> "Flow":
        """fn(acc, elem) -> Future (or plain value); emits the final
        aggregate at completion (scaladsl foldAsync)."""
        return self._append(lambda: _ops3.FoldAsync(zero, fn))

    def scan_async(self, zero, fn) -> "Flow":
        return self._append(lambda: _ops3.FoldAsync(zero, fn,
                                                    emit_each=True))

    def on_error_complete(self, pred=None) -> "Flow":
        return self._append(lambda: _ops3.OnErrorComplete(pred))

    def also_to_all(self, *sinks: "Sink") -> "Flow":
        """also_to chained over every sink (scaladsl alsoToAll)."""
        flow = self
        for s in sinks:
            flow = flow.also_to(s)
        return flow

    def merge_all(self, sources) -> "Flow":
        """Merge every source into this flow (scaladsl mergeAll)."""
        flow = self
        for src in sources:
            flow = flow.merge(src)
        return flow

    def interleave_all(self, sources, segment_size: int) -> "Flow":
        """Round-robin interleave across this flow AND every source in ONE
        N-way stage (scaladsl interleaveAll) — chaining 2-way interleaves
        would scramble the round-robin order across sources."""
        sources = list(sources)
        prev = self._build
        builds = [s._build for s in sources]

        def build(b: _Builder, upstream: Outlet):
            o1, m1 = prev(b, upstream)
            logic, _l = b.add(_ops.InterleaveStage(segment_size,
                                                   n=1 + len(builds)))
            b.connect(o1, logic.shape.ins[0])
            for i, sb in enumerate(builds):
                oi, _mi = sb(b)
                b.connect(oi, logic.shape.ins[1 + i])
            return logic.shape.out, m1
        return Flow(build)

    def concat_all_lazy(self, *sources: Source) -> "Flow":
        """Concat every source after this flow's elements, each materialized
        only when reached (scaladsl concatAllLazy — our ConcatStage pulls
        an input only once it becomes active)."""
        flow = self
        for src in sources:
            flow = flow.concat(src)
        return flow

    def collect_type(self, cls) -> "Flow":
        """Pass through only instances of `cls` (scaladsl collectType).
        A dedicated filter, not collect's None-sentinel: a legitimate None
        element matching `cls` (e.g. collect_type(object)) must survive
        (ADVICE r3)."""
        return self.filter(lambda x: isinstance(x, cls))

    def flat_map_prefix(self, n: int, fn) -> "Flow":
        """Consume the first n elements, then run the REST of the stream
        through the Flow `fn(prefix)` returns (scaladsl flatMapPrefix) —
        composed from prefix_and_tail + flat_map_concat."""
        return self.prefix_and_tail(n).flat_map_concat(
            lambda pt: pt[1].via(fn(pt[0])))

    def extrapolate(self, extrapolator, initial=None) -> "Flow":
        """Meet faster downstream demand by extrapolating from the last
        element (scaladsl extrapolate, an expand specialization: the
        element itself is emitted first, then extrapolations)."""
        def expander(elem):
            def gen():
                yield elem
                yield from extrapolator(elem)
            return gen()
        flow = self.expand(expander)
        if initial is not None:
            flow = flow.prepend(Source.single(initial))
        return flow

    # -- fourth operator tranche (scaladsl/Flow.scala long tail) -------------
    def stateful_map(self, create, fn, on_complete=None) -> "Flow":
        """statefulMap(create)(f, onComplete): f(state, elem) ->
        (state, out); onComplete(state) may emit one final element."""
        return self._append(lambda: _ops4.StatefulMap(create, fn, on_complete))

    def map_with_resource(self, create, fn, close) -> "Flow":
        """mapWithResource: per-materialization resource used by
        fn(resource, elem), closed on every termination path."""
        return self._append(lambda: _ops4.MapWithResource(create, fn, close))

    def map_async_partitioned(self, parallelism: int, partitioner,
                              fn) -> "Flow":
        """mapAsyncPartitioned: one future in flight per partition,
        results in input order; fn(elem, partition) -> Future | value."""
        return self._append(lambda: _ops4.MapAsyncPartitioned(
            parallelism, partitioner, fn))

    def grouped_weighted(self, min_weight: float, cost) -> "Flow":
        return self._append(lambda: _ops4.GroupedWeighted(min_weight, cost))

    def grouped_weighted_within(self, max_weight: float, seconds: float,
                                cost, max_number: int = 0) -> "Flow":
        return self._append(lambda: _ops4.GroupedWeightedWithin(
            max_weight, seconds, cost, max_number))

    def batch_weighted(self, max_weight: float, cost, seed,
                       aggregate) -> "Flow":
        return self._append(lambda: _ops4.BatchWeighted(
            max_weight, cost, seed, aggregate))

    def initial_delay(self, seconds: float) -> "Flow":
        return self._append(lambda: _ops4.InitialDelay(seconds))

    def backpressure_timeout(self, seconds: float) -> "Flow":
        return self._append(lambda: _ops4.BackpressureTimeout(seconds))

    def delay_with(self, strategy_factory, buffer_size: int = 16) -> "Flow":
        """delayWith(DelayStrategy): strategy_factory() -> fn(elem) ->
        seconds, fresh per materialization."""
        return self._append(lambda: _ops4.DelayWith(strategy_factory,
                                                    buffer_size))

    def monitor(self) -> "Flow":
        """monitor: mat value is a FlowMonitor exposing the stream's last
        state (initialized/received/failed/finished)."""
        return self._append(lambda: _ops4.MonitorStage(), combine=Keep.right)

    def fold_while(self, zero, pred, fn) -> "Flow":
        """foldWhile(zero)(pred)(f): stop folding (and cancel upstream)
        once pred(acc) is false; emits the aggregate."""
        return self._append(lambda: _ops4.FoldWhile(zero, pred, fn))

    def merge_latest(self, other: Source) -> "Flow":
        """mergeLatest: after both inputs emitted once, emit [a, b] on
        every update from either side."""
        return self._fan_in(other, lambda: _ops4.MergeLatestStage(2))

    def merge_latest_with(self, other: Source, fn) -> "Flow":
        return self._fan_in(other, lambda: _ops4.MergeLatestStage(
            2, lambda xs: fn(*xs)))

    def ask(self, parallelism: int, ref, timeout: float = 5.0) -> "Flow":
        """ask: each element is asked to `ref`; replies emitted in order
        (scaladsl Flow.ask via mapAsync + pattern.ask)."""
        from ..pattern.ask import ask as _ask

        def do_ask(elem):
            return _ask(ref, elem, timeout)
        return self.map_async(parallelism, do_ask)

    def watch(self, ref) -> "Flow":
        """watch(ref): fail the stream with
        WatchedActorTerminatedException when `ref` terminates."""
        return self._append(lambda: _ops4.WatchStage(ref))

    def detach(self) -> "Flow":
        """detach: decouple upstream/downstream rates with a one-element
        pump (the reference's Detacher; a 1-slot backpressure buffer)."""
        return self.buffer(1, "backpressure")

    def recover_with(self, fn) -> "Flow":
        """recoverWith: switch to fn(exception)'s Source on failure,
        unlimited retries (recoverWithRetries(-1))."""
        return self.recover_with_retries(-1, fn)

    def collect_first(self, fn) -> "Flow":
        """collectFirst: emit the first element fn maps non-None, then
        complete."""
        return self.collect(fn).take(1)

    def collect_while(self, fn) -> "Flow":
        """collectWhile: map through fn until it first returns None, then
        complete (fn evaluated once per element)."""
        return self.map(fn).take_while(lambda v: v is not None)

    def flatten_merge(self, breadth: int = 8) -> "Flow":
        """flattenMerge: flatten a stream of Sources, running up to
        `breadth` concurrently."""
        return self.flat_map_merge(breadth, lambda s: s)

    def switch_map(self, fn) -> "Flow":
        """switchMap (flatMapLatest): a new element cancels the current
        inner Source and switches to fn(elem)."""
        return self._append(lambda: _ops4.SwitchMap(fn))

    def map_materialized_value(self, fn) -> "Flow":
        """mapMaterializedValue: transform this Flow's mat value."""
        prev = self._build

        def build(b: _Builder, upstream: Outlet):
            o, m = prev(b, upstream)
            return o, fn(m)
        return Flow(build)

    def async_(self) -> "Flow":
        """Mark an ASYNC BOUNDARY: stages after this point run in their own
        island (one interpreter actor per island), with backpressure across
        the boundary (scaladsl .async; PhasedFusingActorMaterializer
        island assignment)."""
        prev = self._build

        def build(b: _Builder, upstream: Outlet):
            o, m = prev(b, upstream)
            b.next_island()
            return o, m
        return Flow(build)

    # -- sub-streams (impl/fusing/StreamOfStreams.scala) ---------------------
    def group_by(self, max_substreams: int, key_fn,
                 sub_buffer: int = 1024) -> "Flow":
        """Demultiplex into (key, Source) pairs, one per distinct key."""
        from .substreams import GroupBy
        return self._append(lambda: GroupBy(max_substreams, key_fn,
                                            sub_buffer))

    def split_when(self, predicate) -> "Flow":
        from .substreams import SplitWhen
        return self._append(lambda: SplitWhen(predicate, after=False))

    def split_after(self, predicate) -> "Flow":
        from .substreams import SplitWhen
        return self._append(lambda: SplitWhen(predicate, after=True))

    def flat_map_merge(self, breadth: int, fn) -> "Flow":
        from .substreams import FlatMapMerge
        return self._append(lambda: FlatMapMerge(breadth, fn))

    def prefix_and_tail(self, n: int) -> "Flow":
        from .substreams import PrefixAndTail
        return self._append(lambda: PrefixAndTail(n))

    def merge_substreams(self, breadth: int = 16) -> "Flow":
        """Flatten a stream of Sources (or (key, Source) pairs from
        group_by) by merging up to `breadth` concurrently."""
        def pick(x):
            return x[1] if isinstance(x, tuple) and len(x) == 2 else x
        return self.flat_map_merge(breadth, pick)

    def concat_substreams(self) -> "Flow":
        def pick(x):
            return x[1] if isinstance(x, tuple) and len(x) == 2 else x
        return self.flat_map_concat(pick)

    # -- timed windows / limits / timeouts (impl/Timers.scala, Ops.scala) ----
    def take_within(self, seconds: float) -> "Flow":
        return self._append(lambda: _ops2.TakeWithin(seconds))

    def drop_within(self, seconds: float) -> "Flow":
        return self._append(lambda: _ops2.DropWithin(seconds))

    def grouped_within(self, n: int, seconds: float) -> "Flow":
        return self._append(lambda: _ops2.GroupedWithin(n, seconds))

    def limit(self, max_elements: int) -> "Flow":
        return self._append(lambda: _ops2.Limit(max_elements))

    def limit_weighted(self, max_cost: int, cost_fn) -> "Flow":
        return self._append(lambda: _ops2.Limit(max_cost, cost_fn))

    def initial_timeout(self, seconds: float) -> "Flow":
        return self._append(lambda: _ops2.InitialTimeout(seconds))

    def completion_timeout(self, seconds: float) -> "Flow":
        return self._append(lambda: _ops2.CompletionTimeout(seconds))

    def idle_timeout(self, seconds: float) -> "Flow":
        return self._append(lambda: _ops2.IdleTimeout(seconds))

    def keep_alive(self, seconds: float, inject_fn) -> "Flow":
        return self._append(lambda: _ops2.KeepAlive(seconds, inject_fn))

    # -- errors / termination ------------------------------------------------
    def map_error(self, fn) -> "Flow":
        return self._append(lambda: _ops2.MapError(fn))

    def deduplicate(self, key_fn=None) -> "Flow":
        return self._append(lambda: _ops2.Deduplicate(key_fn))

    def recover_with_retries(self, attempts: int, fn) -> "Flow":
        return self._append(lambda: _ops2.RecoverWithRetries(attempts, fn))

    def watch_termination(self) -> "Flow":
        """Mat value becomes a Future completing with the stream's end."""
        return self._append(lambda: _ops2.WatchTermination(),
                            combine=Keep.right)


class Sink:
    """build(b, upstream_outlet) -> mat."""

    def __init__(self, build: Callable[[_Builder, Outlet], Any]):
        self._build = build

    def with_attributes(self, attrs) -> "Sink":
        return Sink(_scoped_attributes(self._build, attrs))

    add_attributes = with_attributes

    def named(self, name: str) -> "Sink":
        from .attributes import Attributes
        return self.with_attributes(Attributes.name(name))

    @staticmethod
    def from_graph(stage_factory: Callable[[], GraphStage]) -> "Sink":
        def build(b: _Builder, upstream: Outlet):
            logic, mat = b.add(stage_factory())
            b.connect(upstream, logic.shape.inlets[0])
            return mat
        return Sink(build)

    @staticmethod
    def ignore() -> "Sink":
        return Sink.from_graph(lambda: _ops.IgnoreSink())

    @staticmethod
    def foreach(fn) -> "Sink":
        return Sink.from_graph(lambda: _ops.ForeachSink(fn))

    @staticmethod
    def foreach_async(parallelism: int, fn) -> "Sink":
        """foreachAsync: fn(elem) -> Future; up to `parallelism` in
        flight; mat Future completes at stream end."""
        return Flow().map_async(parallelism, fn).to(
            Sink.ignore(), Keep.right)

    @staticmethod
    def cancelled() -> "Sink":
        """Sink.cancelled: immediately cancel upstream."""
        return Sink.from_graph(lambda: _ops4.CancelledSink())

    @staticmethod
    def lazy_sink(factory: Callable[[], "Sink"]) -> "Sink":
        """lazySink: build+materialize the real sink only when the first
        element arrives (that element is delivered to it)."""
        return Sink.from_graph(lambda: _ops4.LazySink(factory))

    @staticmethod
    def future_sink(fut: Future) -> "Sink":
        """futureSink: materialize the Sink the future resolves to,
        buffering demand until then."""
        return Sink.from_graph(
            lambda: _ops4.LazySink(lambda: fut.result(), trigger=fut))

    @staticmethod
    def seq() -> "Sink":
        return Sink.from_graph(lambda: _ops.SeqSink())

    @staticmethod
    def fold(zero, fn) -> "Sink":
        return Sink.from_graph(lambda: _ops.FoldSink(zero, fn))

    @staticmethod
    def reduce(fn) -> "Sink":
        return Sink.from_graph(lambda: _ops.ReduceSink(fn))

    @staticmethod
    def head() -> "Sink":
        return Sink.from_graph(lambda: _ops.HeadSink(require=True))

    @staticmethod
    def head_option() -> "Sink":
        return Sink.from_graph(lambda: _ops.HeadSink(require=False))

    @staticmethod
    def last() -> "Sink":
        return Sink.from_graph(lambda: _ops.LastSink(require=True))

    @staticmethod
    def last_option() -> "Sink":
        return Sink.from_graph(lambda: _ops.LastSink(require=False))

    @staticmethod
    def on_complete(fn: Callable[[Optional[BaseException]], None]) -> "Sink":
        return Sink.from_graph(lambda: _ops.OnCompleteSink(fn))

    @staticmethod
    def queue(buffer_size: int = 256) -> "Sink":
        return Sink.from_graph(lambda: _ops.QueueSink(buffer_size))

    @staticmethod
    def actor_ref(ref, on_complete_message: Any,
                  on_failure_message: Callable[[BaseException], Any] = None
                  ) -> "Sink":
        return Sink.from_graph(lambda: _ops.ActorRefSink(
            ref, on_complete_message, on_failure_message))

    @staticmethod
    def actor_ref_with_backpressure(ref, on_init_message: Any,
                                    ack_message: Any,
                                    on_complete_message: Any,
                                    on_failure_message: Callable[
                                        [BaseException], Any] = None
                                    ) -> "Sink":
        """Each element waits for the target actor's `ack_message` before
        the next is pulled (scaladsl Sink.actorRefWithBackpressure)."""
        from . import ops4 as _ops4
        return Sink.from_graph(lambda: _ops4.ActorRefBackpressureSink(
            ref, on_init_message, ack_message, on_complete_message,
            on_failure_message))

    @staticmethod
    def combine(first: "Sink", second: "Sink", *rest: "Sink") -> "Sink":
        """Broadcast every element to all given sinks; mat value is the
        tuple of their mat values (scaladsl Sink.combine with a
        Broadcast strategy)."""
        sinks = [first, second, *rest]

        def build(b: _Builder, upstream: Outlet):
            bc, _ = b.add(_ops.BroadcastStage(len(sinks)))
            b.connect(upstream, bc.shape.inlets[0])
            return tuple(s._build(b, out)
                         for s, out in zip(sinks, bc.shape.outlets))
        return Sink(build)

    @staticmethod
    def count() -> "Sink":
        return Sink.fold(0, lambda acc, _elem: acc + 1)

    @staticmethod
    def take_last(n: int) -> "Sink":
        """Future completing with the last n elements (Sink.takeLast)."""
        import collections as _c

        def build(b: _Builder, upstream: Outlet):
            logic, mat = b.add(_ops.FoldSink(
                _c.deque(maxlen=n),
                lambda acc, e: (acc.append(e), acc)[1]))
            b.connect(upstream, logic.shape.inlets[0])
            return _map_future(mat, list)
        return Sink(build)

    @staticmethod
    def exists(pred) -> "Sink":
        """Future[bool]: does any element satisfy pred? Cancels upstream at
        the first match (Sink.exists)."""
        inner = Flow().filter(pred).take(1) \
            .to(Sink.head_option(), Keep.right)

        def build(b: _Builder, upstream: Outlet):
            fut = inner._build(b, upstream)
            return _map_future(fut, lambda v: v is not None)
        return Sink(build)

    @staticmethod
    def forall(pred) -> "Sink":
        """Future[bool]: do ALL elements satisfy pred? (Sink.forall)"""
        neg = Sink.exists(lambda x: not pred(x))

        def build(b: _Builder, upstream: Outlet):
            return _map_future(neg._build(b, upstream), lambda v: not v)
        return Sink(build)

    @staticmethod
    def never() -> "Sink":
        """Consumes nothing — never signals demand (Sink.never)."""
        def build(b: _Builder, upstream: Outlet):
            logic, mat = b.add(_ops3.NeverSink())
            b.connect(upstream, logic.shape.inlets[0])
            return mat
        return Sink(build)

    def contramap(self, fn) -> "Sink":
        return Flow().map(fn).to(self, Keep.right)


class RunnableGraph:
    def __init__(self, build: Callable[[_Builder], Any]):
        self._build = build

    def run(self, materializer_or_system) -> Any:
        mat = materializer_or_system
        if not isinstance(mat, Materializer):
            mat = Materializer(getattr(mat, "classic", mat))
        return mat.materialize(self._build)


class BidiFlow:
    """A pair of flows forming a protocol stage: `top` transforms traffic
    flowing one way (I1 -> O1), `bottom` the other way (I2 -> O2)
    (reference: scaladsl/BidiFlow.scala — the codec/framing stacking
    primitive: `codec.atop(framing).join(transport)`)."""

    def __init__(self, top: Flow, bottom: Flow):
        self.top = top
        self.bottom = bottom

    @staticmethod
    def from_flows(top: Flow, bottom: Flow) -> "BidiFlow":
        return BidiFlow(top, bottom)

    @staticmethod
    def from_functions(outbound: Callable[[Any], Any],
                       inbound: Callable[[Any], Any]) -> "BidiFlow":
        """(reference: BidiFlow.fromFunctions) — map each direction."""
        return BidiFlow(Flow().map(outbound), Flow().map(inbound))

    def atop(self, other: "BidiFlow") -> "BidiFlow":
        """Stack `other` below this stage: outbound runs self.top then
        other.top; inbound runs other.bottom then self.bottom."""
        return BidiFlow(self.top.via(other.top),
                        other.bottom.via(self.bottom))

    def reversed(self) -> "BidiFlow":
        return BidiFlow(self.bottom, self.top)

    def join(self, flow: Flow) -> Flow:
        """Close the stack over `flow`: I1 -> top -> flow -> bottom -> O2
        becomes one Flow (the transport at the bottom of a protocol
        stack — BidiFlow.join)."""
        return self.top.via(flow).via(self.bottom)


class _GraphBuilder:
    """User-facing graph assembly surface handed to GraphDSL.create's
    build function (reference: scaladsl/GraphDSL.Builder — add shapes,
    wire ports explicitly)."""

    def __init__(self, b: _Builder):
        self._b = b

    # -- adding shapes --------------------------------------------------------
    def add(self, stage: GraphStage):
        """Add any GraphStage; returns its logic (ports via .shape)."""
        logic, _mat = self._b.add(stage)
        return logic

    def source(self, source: Source) -> Outlet:
        outlet, _mat = source._build(self._b)
        return outlet

    def sink(self, sink: Sink, outlet: Outlet) -> Any:
        """Wire `outlet` into `sink`; returns the sink's mat value."""
        return sink._build(self._b, outlet)

    def flow(self, outlet: Outlet, flow: Flow) -> Outlet:
        """Append a linear flow after `outlet`; returns the new outlet."""
        new_outlet, _mat = flow._build(self._b, outlet)
        return new_outlet

    def edge(self, outlet: Outlet, inlet: Inlet) -> None:
        self._b.connect(outlet, inlet)

    # -- junction shorthands --------------------------------------------------
    def broadcast(self, n: int):
        return self.add(_ops.BroadcastStage(n))

    def merge(self, n: int):
        return self.add(_ops.MergeStage(n))

    def balance(self, n: int):
        return self.add(_ops.BalanceStage(n))

    def concat(self, n: int = 2):
        return self.add(_ops.ConcatStage(n))

    def zip(self):
        return self.add(_ops.ZipWithStage(lambda a, b: (a, b)))


class GraphDSL:
    """Arbitrary-graph construction (reference: scaladsl/GraphDSL.create):

        def build(g):
            bcast = g.broadcast(2)
            merge = g.merge(2)
            g.edge(g.source(Source.from_iterable(range(10))),
                   bcast.shape.in_)
            g.edge(g.flow(bcast.shape.outs[0], Flow().map(f)),
                   merge.shape.ins[0])
            g.edge(g.flow(bcast.shape.outs[1], Flow().map(h)),
                   merge.shape.ins[1])
            return g.sink(Sink.seq(), merge.shape.out)

        fut = GraphDSL.create(build).run(system)
    """

    @staticmethod
    def create(build_fn: Callable[["_GraphBuilder"], Any]) -> RunnableGraph:
        return RunnableGraph(lambda b: build_fn(_GraphBuilder(b)))


# -- Source gets the whole linear operator library ----------------------------
# (scaladsl/Source.scala mirrors Flow's operators; delegating through
# `self.via(Flow().<op>(...))` keeps one implementation per stage)
_SOURCE_MIRRORED_OPS = [
    "map", "map_concat", "stateful_map_concat", "filter", "filter_not",
    "collect", "take", "take_while", "drop", "drop_while", "scan", "fold",
    "reduce", "grouped", "sliding", "intersperse", "zip_with_index",
    "buffer", "conflate", "conflate_with_seed", "batch", "expand",
    "map_async", "map_async_unordered", "throttle", "delay", "recover",
    "log", "flat_map_concat", "via_stage",
    "group_by", "split_when", "split_after", "flat_map_merge",
    "prefix_and_tail", "merge_substreams", "concat_substreams",
    "take_within", "drop_within", "grouped_within", "limit",
    "limit_weighted", "initial_timeout", "completion_timeout",
    "idle_timeout", "keep_alive", "map_error", "deduplicate",
    "recover_with_retries", "watch_termination",
    "zip_latest", "zip_latest_with", "zip_all", "merge_sorted",
    "merge_prioritized", "divert_to", "fold_async", "scan_async",
    "on_error_complete", "async_", "also_to_all", "merge_all",
    "interleave_all", "concat_all_lazy", "collect_type",
    "flat_map_prefix", "extrapolate",
    "stateful_map", "map_with_resource", "map_async_partitioned",
    "grouped_weighted", "grouped_weighted_within", "batch_weighted",
    "initial_delay", "backpressure_timeout", "delay_with", "monitor",
    "fold_while", "merge_latest", "merge_latest_with", "ask", "watch",
    "detach", "recover_with", "collect_first", "collect_while",
    "flatten_merge", "switch_map",
]


def _mirror_op(name: str):
    def method(self, *args, **kwargs):
        flow = getattr(Flow(), name)(*args, **kwargs)
        combine = Keep.right if name == "watch_termination" else Keep.left
        return self.via(flow, combine)
    method.__name__ = name
    method.__qualname__ = f"Source.{name}"
    return method


for _name in _SOURCE_MIRRORED_OPS:
    if not hasattr(Source, _name):
        setattr(Source, _name, _mirror_op(_name))
del _name
