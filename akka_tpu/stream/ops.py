"""Operator stage library.

Reference parity: akka-stream/src/main/scala/akka/stream/impl/fusing/
Ops.scala (map/filter/take/drop/scan/fold/grouped/sliding/conflate/batch/
expand/recover/log...), Throttle.scala (token bucket), StreamOfStreams.scala
(flatMapConcat via sub-materialization), impl/fusing/GraphStages.scala
(tick source), impl/QueueSource.scala / QueueSink.scala, impl/ActorRefSource
/SinkStage, scaladsl/Merge/Concat/Zip/Broadcast/Balance/Partition/Interleave
(stream/scaladsl/Graph.scala).

Every class is a fresh-per-materialization GraphStage (ports are allocated
in __init__); the DSL instantiates via factories.
"""

from __future__ import annotations

import collections
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional

from .stage import (FanInShape, FanOutShape, FlowShape, GraphStage,
                    GraphStageLogic, Inlet, Outlet, SinkShape, SourceShape,
                    make_in_handler, make_out_handler)


class NoSuchElementException(RuntimeError):
    pass


# =============================== sources ====================================

class _SourceStage(GraphStage):
    def __init__(self, name: str):
        self.name = name
        self.out = Outlet(f"{name}.out")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape


class IterableSource(_SourceStage):
    def __init__(self, iterable):
        super().__init__("IterableSource")
        self.iterable = iterable

    def create_logic(self):
        out = self.out
        holder = {}
        logic = GraphStageLogic(self._shape)

        def on_pull():
            it = holder.get("it")
            if it is None:
                it = holder["it"] = iter(self.iterable)
                try:
                    holder["next"] = next(it)
                except StopIteration:
                    logic.complete(out)
                    return
                except Exception as e:  # noqa: BLE001
                    logic.fail(out, e)
                    return
            if "err" in holder:
                logic.fail(out, holder.pop("err"))
                return
            if "next" not in holder:
                logic.complete(out)
                return
            elem = holder.pop("next")
            # one-element lookahead so exhaustion is known NOW and
            # completion rides WITH the last element — a consumer with
            # exact demand must not need a bonus pull to learn the stream
            # ended (reference: Source.fromIterator pushes then checks
            # hasNext; reactive-streams 1.05 completion-without-demand)
            done = False
            try:
                holder["next"] = next(it)
            except StopIteration:
                done = True
            except Exception as e:  # noqa: BLE001
                holder["err"] = e
            logic.push(out, elem)
            if done:
                logic.complete(out)
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class FailedSource(_SourceStage):
    def __init__(self, ex: BaseException):
        super().__init__("FailedSource")
        self.ex = ex

    def create_logic(self):
        logic = GraphStageLogic(self._shape)
        out, ex = self.out, self.ex
        logic.set_handler(out, make_out_handler(
            lambda: logic.fail(out, ex)))
        return logic


class RepeatSource(_SourceStage):
    def __init__(self, elem):
        super().__init__("RepeatSource")
        self.elem = elem

    def create_logic(self):
        logic = GraphStageLogic(self._shape)
        out, elem = self.out, self.elem
        logic.set_handler(out, make_out_handler(lambda: logic.push(out, elem)))
        return logic


class CycleSource(_SourceStage):
    def __init__(self, factory):
        super().__init__("CycleSource")
        self.factory = factory

    def create_logic(self):
        logic = GraphStageLogic(self._shape)
        out, factory = self.out, self.factory
        state = {"it": None}

        def on_pull():
            for _ in range(2):
                if state["it"] is None:
                    state["it"] = iter(factory())
                try:
                    logic.push(out, next(state["it"]))
                    return
                except StopIteration:
                    state["it"] = None
            logic.fail(out, ValueError("empty cycle source"))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class UnfoldSource(_SourceStage):
    def __init__(self, zero, fn):
        super().__init__("UnfoldSource")
        self.zero = zero
        self.fn = fn

    def create_logic(self):
        logic = GraphStageLogic(self._shape)
        out, fn = self.out, self.fn
        state = {"s": self.zero}

        def on_pull():
            nxt = fn(state["s"])
            if nxt is None:
                logic.complete(out)
            else:
                state["s"], elem = nxt
                logic.push(out, elem)
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class TickCancellable:
    def __init__(self):
        self._cb = None
        self._cancelled = threading.Event()

    def cancel(self) -> None:
        self._cancelled.set()
        if self._cb is not None:
            self._cb.invoke(None)

    @property
    def is_cancelled(self) -> bool:
        return self._cancelled.is_set()


class TickSource(_SourceStage):
    """Emits `tick` every `interval`; ticks with no demand are DROPPED
    (reference: Source.tick)."""

    def __init__(self, initial_delay: float, interval: float, tick):
        super().__init__("TickSource")
        self.initial_delay = initial_delay
        self.interval = interval
        self.tick = tick

    def create_logic_and_mat(self):
        stage = self
        cancellable = TickCancellable()

        class _L(GraphStageLogic):
            def pre_start(self):
                cancellable._cb = self.get_async_callback(
                    lambda _: self.complete_stage())
                self.schedule_periodically("tick", stage.initial_delay,
                                           stage.interval)

            def on_timer(self, key):
                if cancellable.is_cancelled:
                    self.complete_stage()
                elif self.is_available(stage.out):
                    self.push(stage.out, stage.tick)

        logic = _L(self._shape)
        logic.set_handler(stage.out, make_out_handler(lambda: None))
        return logic, cancellable


class SourceQueue:
    """Mat value of Source.queue (reference: SourceQueueWithComplete)."""

    def __init__(self):
        self._offer_cb = None
        self._done_cb = None
        self._lock = threading.Lock()
        self._early: List = []  # offers before materialization finished

    def _bind(self, offer_cb, done_cb):
        with self._lock:
            self._offer_cb, self._done_cb = offer_cb, done_cb
            early, self._early = self._early, []
        for item in early:
            self._dispatch(item)

    def _dispatch(self, item):
        kind = item[0]
        if kind == "offer":
            self._offer_cb.invoke((item[1], item[2]))
        else:
            self._done_cb.invoke(item)

    def _set_closed(self) -> None:
        with self._lock:
            self._closed = True

    def offer(self, elem) -> Future:
        fut: Future = Future()
        with self._lock:
            if getattr(self, "_closed", False):
                fut.set_result(False)  # stream gone: offer rejected
                return fut
            if self._offer_cb is None:
                self._early.append(("offer", elem, fut))
                return fut
        self._dispatch(("offer", elem, fut))
        return fut

    def complete(self) -> None:
        with self._lock:
            if self._done_cb is None:
                self._early.append(("complete", None))
                return
        self._dispatch(("complete", None))

    def fail(self, ex: BaseException) -> None:
        with self._lock:
            if self._done_cb is None:
                self._early.append(("fail", ex))
                return
        self._dispatch(("fail", ex))


class QueueSource(_SourceStage):
    def __init__(self, buffer_size: int):
        super().__init__("QueueSource")
        self.buffer_size = buffer_size

    def create_logic_and_mat(self):
        stage = self
        queue_mat = SourceQueue()
        buf: collections.deque = collections.deque()
        state = {"completing": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                queue_mat._bind(
                    self.get_async_callback(self._on_offer),
                    self.get_async_callback(self._on_done))

            def _on_offer(self, pair):
                elem, fut = pair
                if state["completing"]:
                    fut.set_result(False)
                    return
                if self.is_available(stage.out) and not buf:
                    self.push(stage.out, elem)
                    fut.set_result(True)
                elif len(buf) < stage.buffer_size:
                    buf.append(elem)
                    fut.set_result(True)
                else:
                    fut.set_result(False)  # backpressured: dropped

            def _on_done(self, item):
                if item[0] == "fail":
                    self.fail_stage(item[1])
                    return
                state["completing"] = True
                if not buf:
                    self.complete(stage.out)

            def post_stop(self):
                queue_mat._set_closed()

        logic = _L(self._shape)

        def on_pull():
            if buf:
                logic.push(stage.out, buf.popleft())
            if state["completing"] and not buf:
                logic.complete(stage.out)
        logic.set_handler(stage.out, make_out_handler(on_pull))
        return logic, queue_mat


class FutureSource(_SourceStage):
    def __init__(self, fut: Future):
        super().__init__("FutureSource")
        self.fut = fut

    def create_logic(self):
        stage = self

        class _L(GraphStageLogic):
            def pre_start(self):
                cb = self.get_async_callback(self._done)
                stage.fut.add_done_callback(lambda f: cb.invoke(f))

            def _done(self, f):
                ex = f.exception()
                if ex is not None:
                    self.fail_stage(ex)
                else:
                    self.emit(stage.out, f.result())
                    self.complete(stage.out)

        logic = _L(self._shape)
        logic.set_handler(stage.out, make_out_handler(lambda: None))
        return logic


class ActorRefSource(_SourceStage):
    """Mat: an ActorRef; messages become elements, Status.Success completes,
    Status.Failure fails (reference: Source.actorRef)."""

    def __init__(self, buffer_size: int):
        super().__init__("ActorRefSource")
        self.buffer_size = buffer_size

    def create_logic_and_mat(self):
        from ..actor.messages import Status
        from ..actor.props import Props
        stage = self
        buf: collections.deque = collections.deque()
        state = {"completing": False, "ref": None}

        class _L(GraphStageLogic):
            def pre_start(self):
                cb = self.get_async_callback(self._on_msg)
                system = self.materializer.system

                def receive(_ctx, msg):
                    cb.invoke(msg)
                state["ref"] = system.actor_of(Props.from_receive(receive))

            def _on_msg(self, msg):
                if isinstance(msg, Status.Success):
                    state["completing"] = True
                    if not buf:
                        self.complete(stage.out)
                elif isinstance(msg, Status.Failure):
                    self.fail_stage(msg.cause if isinstance(
                        msg.cause, BaseException) else
                        RuntimeError(str(msg.cause)))
                elif state["completing"]:
                    pass  # dropped after completion
                elif self.is_available(stage.out) and not buf:
                    self.push(stage.out, msg)
                elif len(buf) < stage.buffer_size:
                    buf.append(msg)
                # else: overflow -> dropped (reference default dropTail-ish)

            def post_stop(self):
                if state["ref"] is not None:
                    self.materializer.system.stop(state["ref"])

        logic = _L(self._shape)

        def on_pull():
            if buf:
                logic.push(stage.out, buf.popleft())
            if state["completing"] and not buf:
                logic.complete(stage.out)
        logic.set_handler(stage.out, make_out_handler(on_pull))

        class _LazyRef:
            def tell(self, msg, sender=None):
                state["ref"].tell(msg, sender)

            @property
            def ref(self):
                return state["ref"]
        return logic, _LazyRef()


# =============================== linear ops =================================

class _LinearStage(GraphStage):
    def __init__(self, name: str):
        self.name = name
        self.in_ = Inlet(f"{name}.in")
        self.out = Outlet(f"{name}.out")
        self._shape = FlowShape(self.in_, self.out)

    @property
    def shape(self):
        return self._shape

    def _logic(self):
        return GraphStageLogic(self._shape)


class Map(_LinearStage):
    def __init__(self, fn):
        super().__init__("Map")
        self.fn = fn

    def create_logic(self):
        logic, in_, out, fn = self._logic(), self.in_, self.out, self.fn
        logic.set_handler(in_, make_in_handler(
            lambda: logic.push(out, fn(logic.grab(in_)))))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class MapConcat(_LinearStage):
    def __init__(self, fn):
        super().__init__("MapConcat")
        self.fn = fn

    def create_logic(self):
        logic, in_, out, fn = self._logic(), self.in_, self.out, self.fn

        def on_push():
            elems = list(fn(logic.grab(in_)))
            if elems:
                logic.emit_multiple(out, elems)
            else:
                logic.pull(in_)
        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class StatefulMapConcat(_LinearStage):
    def __init__(self, factory):
        super().__init__("StatefulMapConcat")
        self.factory = factory

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        fn = self.factory()

        def on_push():
            elems = list(fn(logic.grab(in_)))
            if elems:
                logic.emit_multiple(out, elems)
            else:
                logic.pull(in_)
        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class Filter(_LinearStage):
    def __init__(self, pred):
        super().__init__("Filter")
        self.pred = pred

    def create_logic(self):
        logic, in_, out, pred = self._logic(), self.in_, self.out, self.pred

        def on_push():
            elem = logic.grab(in_)
            if pred(elem):
                logic.push(out, elem)
            else:
                logic.pull(in_)
        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class Collect(_LinearStage):
    def __init__(self, fn):
        super().__init__("Collect")
        self.fn = fn

    def create_logic(self):
        logic, in_, out, fn = self._logic(), self.in_, self.out, self.fn

        def on_push():
            mapped = fn(logic.grab(in_))
            if mapped is not None:
                logic.push(out, mapped)
            else:
                logic.pull(in_)
        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class Take(_LinearStage):
    def __init__(self, n: int):
        super().__init__("Take")
        self.n = n

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        left = {"n": self.n}

        def on_push():
            elem = logic.grab(in_)
            if left["n"] > 0:
                left["n"] -= 1
                logic.push(out, elem)
            if left["n"] <= 0:
                logic.complete_stage()

        def on_pull():
            if left["n"] <= 0:
                logic.complete_stage()
            else:
                logic.pull(in_)
        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class TakeWhile(_LinearStage):
    def __init__(self, pred, inclusive: bool):
        super().__init__("TakeWhile")
        self.pred = pred
        self.inclusive = inclusive

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        pred, inclusive = self.pred, self.inclusive

        def on_push():
            elem = logic.grab(in_)
            if pred(elem):
                logic.push(out, elem)
            else:
                if inclusive:
                    logic.push(out, elem)
                logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class Drop(_LinearStage):
    def __init__(self, n: int):
        super().__init__("Drop")
        self.n = n

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        left = {"n": self.n}

        def on_push():
            elem = logic.grab(in_)
            if left["n"] > 0:
                left["n"] -= 1
                logic.pull(in_)
            else:
                logic.push(out, elem)
        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class DropWhile(_LinearStage):
    def __init__(self, pred):
        super().__init__("DropWhile")
        self.pred = pred

    def create_logic(self):
        logic, in_, out, pred = self._logic(), self.in_, self.out, self.pred
        state = {"dropping": True}

        def on_push():
            elem = logic.grab(in_)
            if state["dropping"] and pred(elem):
                logic.pull(in_)
            else:
                state["dropping"] = False
                logic.push(out, elem)
        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class Scan(_LinearStage):
    """Emits zero first, then each fold step (reference: Ops.scala Scan)."""

    def __init__(self, zero, fn):
        super().__init__("Scan")
        self.zero = zero
        self.fn = fn

    def create_logic(self):
        logic, in_, out, fn = self._logic(), self.in_, self.out, self.fn
        state = {"acc": self.zero, "sent_zero": False}
        # Supervision.restart resets the aggregate to zero (Ops.scala Scan
        # restart semantics); resume keeps the accumulated value
        logic.restart_state = lambda: state.update(acc=self.zero)

        def on_pull():
            if not state["sent_zero"]:
                state["sent_zero"] = True
                logic.push(out, state["acc"])
            else:
                logic.pull(in_)

        def on_push():
            state["acc"] = fn(state["acc"], logic.grab(in_))
            logic.push(out, state["acc"])

        def on_finish():
            if not state["sent_zero"]:
                logic.emit(out, state["acc"])
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class Fold(_LinearStage):
    def __init__(self, zero, fn):
        super().__init__("Fold")
        self.zero = zero
        self.fn = fn

    def create_logic(self):
        logic, in_, out, fn = self._logic(), self.in_, self.out, self.fn
        state = {"acc": self.zero}
        logic.restart_state = lambda: state.update(acc=self.zero)

        def on_push():
            state["acc"] = fn(state["acc"], logic.grab(in_))
            logic.pull(in_)

        def on_finish():
            logic.emit(out, state["acc"])
            logic.complete(out)
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(
            lambda: logic.pull(in_) if not logic.has_been_pulled(in_)
            and not logic.is_closed(in_) else None))
        return logic


class Reduce(_LinearStage):
    def __init__(self, fn):
        super().__init__("Reduce")
        self.fn = fn

    def create_logic(self):
        logic, in_, out, fn = self._logic(), self.in_, self.out, self.fn
        state = {"acc": None, "has": False}

        def on_push():
            elem = logic.grab(in_)
            state["acc"] = elem if not state["has"] else fn(state["acc"], elem)
            state["has"] = True
            logic.pull(in_)

        def on_finish():
            if not state["has"]:
                logic.fail(out, NoSuchElementException("reduce of empty stream"))
            else:
                logic.emit(out, state["acc"])
                logic.complete(out)
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(
            lambda: logic.pull(in_) if not logic.has_been_pulled(in_)
            and not logic.is_closed(in_) else None))
        return logic


class Grouped(_LinearStage):
    def __init__(self, n: int):
        super().__init__("Grouped")
        self.n = n

    def create_logic(self):
        logic, in_, out, n = self._logic(), self.in_, self.out, self.n
        buf: List = []

        def on_push():
            buf.append(logic.grab(in_))
            if len(buf) >= n:
                group, buf[:] = list(buf), []
                logic.push(out, group)
            else:
                logic.pull(in_)

        def on_finish():
            if buf:
                logic.emit(out, list(buf))
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class Sliding(_LinearStage):
    def __init__(self, n: int, step: int):
        super().__init__("Sliding")
        self.n = n
        self.step = step

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        n, step = self.n, self.step
        buf: List = []
        state = {"emitted": False}

        def on_push():
            buf.append(logic.grab(in_))
            if len(buf) >= n:
                logic.push(out, list(buf[:n]))
                state["emitted"] = True
                del buf[:step]
            else:
                logic.pull(in_)

        def on_finish():
            if buf and (not state["emitted"] or len(buf) > max(0, n - step)):
                logic.emit(out, list(buf))
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class Intersperse(_LinearStage):
    def __init__(self, sep, start=None, end=None):
        super().__init__("Intersperse")
        self.sep = sep
        self.start = start
        self.end = end

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        sep, start, end = self.sep, self.start, self.end
        state = {"first": True}

        def on_push():
            elem = logic.grab(in_)
            if state["first"]:
                state["first"] = False
                if start is not None:
                    logic.emit_multiple(out, [start, elem])
                else:
                    logic.push(out, elem)
            else:
                logic.emit_multiple(out, [sep, elem])

        def on_finish():
            if end is not None:
                logic.emit(out, end)
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class Buffer(_LinearStage):
    """(reference: Ops.scala Buffer; strategies: backpressure, drop_head,
    drop_tail, drop_new, drop_buffer, fail)"""

    def __init__(self, size: int, strategy: str):
        super().__init__("Buffer")
        self.size = size
        self.strategy = strategy

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        size, strategy = self.size, self.strategy
        buf: collections.deque = collections.deque()
        done = {"finishing": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.pull(in_)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            if logic.is_available(out) and not buf:
                # fast path only with an EMPTY buffer — pushing past
                # buffered elements would reorder the stream
                logic.push(out, elem)
                logic.pull(in_)
                return
            if len(buf) < size:
                buf.append(elem)
            elif strategy == "drop_head":
                buf.popleft(); buf.append(elem)
            elif strategy == "drop_tail":
                buf.pop(); buf.append(elem)
            elif strategy == "drop_new":
                pass
            elif strategy == "drop_buffer":
                buf.clear(); buf.append(elem)
            elif strategy == "fail":
                logic.fail_stage(BufferOverflowException(
                    f"buffer full ({size})"))
                return
            else:  # backpressure at capacity: the element MUST still be
                # kept — it was already pulled in-flight when the buffer
                # filled; only the NEXT pull is withheld
                buf.append(elem)
            # keep pulling unless backpressuring at capacity
            if not (strategy == "backpressure" and len(buf) >= size):
                logic.pull(in_)

        def on_pull():
            if buf:
                logic.push(out, buf.popleft())
            if done["finishing"] and not buf:
                logic.complete_stage()
                return
            if (not logic.has_been_pulled(in_) and not logic.is_closed(in_)
                    and len(buf) < size):
                logic.pull(in_)

        def on_finish():
            if buf:
                done["finishing"] = True
            else:
                logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class BufferOverflowException(RuntimeError):
    pass


class Conflate(_LinearStage):
    """Shrinks a fast upstream for a slow downstream (reference: Ops.scala
    Batch with seed/aggregate in conflate mode — never backpressures)."""

    def __init__(self, seed, aggregate):
        super().__init__("Conflate")
        self.seed = seed
        self.aggregate = aggregate

    def create_logic(self):
        in_, out = self.in_, self.out
        seed, aggregate = self.seed, self.aggregate
        state = {"agg": None, "has": False, "finishing": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.pull(in_)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            if state["has"]:
                state["agg"] = aggregate(state["agg"], elem)
            else:
                state["agg"], state["has"] = seed(elem), True
            if logic.is_available(out):
                logic.push(out, state["agg"])
                state["agg"], state["has"] = None, False
            logic.pull(in_)

        def on_pull():
            if state["has"]:
                logic.push(out, state["agg"])
                state["agg"], state["has"] = None, False
            if state["finishing"] and not state["has"]:
                logic.complete_stage()

        def on_finish():
            if state["has"]:
                state["finishing"] = True
                if logic.is_available(out):
                    logic.push(out, state["agg"])
                    logic.complete_stage()
            else:
                logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class Batch(_LinearStage):
    """Like conflate but backpressures once `max_n` elements are batched."""

    def __init__(self, max_n: int, seed, aggregate):
        super().__init__("Batch")
        self.max_n = max_n
        self.seed = seed
        self.aggregate = aggregate

    def create_logic(self):
        in_, out = self.in_, self.out
        max_n, seed, aggregate = self.max_n, self.seed, self.aggregate
        state = {"agg": None, "count": 0, "finishing": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.pull(in_)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            if state["count"]:
                state["agg"] = aggregate(state["agg"], elem)
            else:
                state["agg"] = seed(elem)
            state["count"] += 1
            if logic.is_available(out):
                logic.push(out, state["agg"])
                state["agg"], state["count"] = None, 0
            if state["count"] < max_n:
                logic.pull(in_)

        def on_pull():
            if state["count"]:
                logic.push(out, state["agg"])
                state["agg"], state["count"] = None, 0
                if state["finishing"]:
                    logic.complete_stage()
                elif not logic.has_been_pulled(in_) and not logic.is_closed(in_):
                    logic.pull(in_)
            elif state["finishing"]:
                logic.complete_stage()

        def on_finish():
            if state["count"]:
                state["finishing"] = True
            else:
                logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class Expand(_LinearStage):
    """Fills a fast downstream by extrapolating (reference: Ops.scala Expand)."""

    def __init__(self, extrapolate):
        super().__init__("Expand")
        self.extrapolate = extrapolate

    def create_logic(self):
        in_, out, extrapolate = self.in_, self.out, self.extrapolate
        state = {"it": None}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.pull(in_)
        logic = _L(self._shape)

        def on_push():
            state["it"] = iter(extrapolate(logic.grab(in_)))
            if logic.is_available(out):
                _push_next()

        def _push_next():
            try:
                logic.push(out, next(state["it"]))
            except StopIteration:
                state["it"] = None
            if not logic.has_been_pulled(in_) and not logic.is_closed(in_):
                logic.pull(in_)

        def on_pull():
            if state["it"] is not None:
                _push_next()
            elif logic.is_closed(in_):
                logic.complete_stage()

        def on_finish():
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class MapAsync(_LinearStage):
    """fn returns a concurrent.futures.Future (or a plain value). Up to
    `parallelism` in flight; ordered variant preserves upstream order
    (reference: Ops.scala MapAsync / MapAsyncUnordered)."""

    def __init__(self, parallelism: int, fn, ordered: bool):
        super().__init__("MapAsync")
        self.parallelism = parallelism
        self.fn = fn
        self.ordered = ordered

    def create_logic(self):
        in_, out = self.in_, self.out
        parallelism, fn, ordered = self.parallelism, self.fn, self.ordered
        in_flight: List[dict] = []  # slots: {"done": bool, "val":, "ex":}
        state = {"finishing": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.pull(in_)
        logic = _L(self._shape)

        def _drain():
            while in_flight:
                idx = 0 if ordered else next(
                    (i for i, s in enumerate(in_flight) if s["done"]), None)
                if idx is None:
                    break
                slot = in_flight[idx]
                if not slot["done"]:
                    break
                if slot["ex"] is not None:
                    logic.fail_stage(slot["ex"])
                    return
                if not logic.is_available(out):
                    break
                in_flight.pop(idx)
                logic.push(out, slot["val"])
            if state["finishing"] and not in_flight:
                logic.complete_stage()
                return
            if (len(in_flight) < parallelism and not state["finishing"]
                    and not logic.has_been_pulled(in_)
                    and not logic.is_closed(in_)):
                logic.pull(in_)

        def on_push():
            elem = logic.grab(in_)
            slot = {"done": False, "val": None, "ex": None}
            in_flight.append(slot)
            cb = logic.get_async_callback(lambda res: _complete(slot, res))
            try:
                fut = fn(elem)
            except Exception as e:  # noqa: BLE001
                slot["done"], slot["ex"] = True, e
                _drain()
                return
            if isinstance(fut, Future):
                fut.add_done_callback(
                    lambda f: cb.invoke((f.exception(), None)
                                        if f.exception() is not None
                                        else (None, f.result())))
            else:
                slot["done"], slot["val"] = True, fut
                _drain()
                return
            if len(in_flight) < parallelism:
                logic.pull(in_)

        def _complete(slot, res):
            ex, val = res
            slot["done"], slot["ex"], slot["val"] = True, ex, val
            _drain()

        def on_pull():
            _drain()

        def on_finish():
            if in_flight:
                state["finishing"] = True
            else:
                logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class Throttle(_LinearStage):
    """Token bucket (reference: impl/Throttle.scala)."""

    def __init__(self, elements: int, per: float, burst: int):
        super().__init__("Throttle")
        self.elements = elements
        self.per = per
        self.burst = max(1, burst)

    def create_logic(self):
        in_, out = self.in_, self.out
        interval = self.per / max(1, self.elements)
        burst = self.burst
        state = {"tokens": burst, "pending": None, "has_pending": False,
                 "finishing": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.schedule_periodically("token", interval, interval)

            def on_timer(self, key):
                state["tokens"] = min(burst, state["tokens"] + 1)
                if state["has_pending"] and state["tokens"] > 0 and \
                        self.is_available(out):
                    state["tokens"] -= 1
                    elem = state["pending"]
                    state["pending"], state["has_pending"] = None, False
                    self.push(out, elem)
                    if state["finishing"]:
                        self.complete_stage()
                    else:
                        self.pull(in_)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            if state["tokens"] > 0 and logic.is_available(out):
                state["tokens"] -= 1
                logic.push(out, elem)
                logic.pull(in_)
            else:
                state["pending"], state["has_pending"] = elem, True

        def on_pull():
            if state["has_pending"] and state["tokens"] > 0:
                state["tokens"] -= 1
                elem = state["pending"]
                state["pending"], state["has_pending"] = None, False
                logic.push(out, elem)
                if state["finishing"]:
                    logic.complete_stage()
                else:
                    logic.pull(in_)
            elif not logic.has_been_pulled(in_) and not logic.is_closed(in_) \
                    and not state["has_pending"]:
                logic.pull(in_)

        def on_finish():
            if state["has_pending"]:
                state["finishing"] = True
            else:
                logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class Delay(_LinearStage):
    def __init__(self, of: float):
        super().__init__("Delay")
        self.of = of

    def create_logic(self):
        import time as _time
        in_, out, of = self.in_, self.out, self.of
        buf: collections.deque = collections.deque()  # (deadline, elem)
        state = {"finishing": False, "timer_set": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.pull(in_)

            def on_timer(self, key):
                state["timer_set"] = False
                self._flush()

            def _flush(self):
                now = _time.monotonic()
                while buf and buf[0][0] <= now and self.is_available(out):
                    self.push(out, buf.popleft()[1])
                    if not self.has_been_pulled(in_) and \
                            not self.is_closed(in_):
                        self.pull(in_)
                if buf and not state["timer_set"]:
                    state["timer_set"] = True
                    self.schedule_once("delay",
                                       max(0.001, buf[0][0] - now))
                if state["finishing"] and not buf:
                    self.complete_stage()
        logic = _L(self._shape)

        def on_push():
            import time as _t
            buf.append((_t.monotonic() + of, logic.grab(in_)))
            logic._flush()
            if not state["timer_set"] and buf:
                state["timer_set"] = True
                logic.schedule_once("delay", of)

        def on_pull():
            logic._flush()

        def on_finish():
            if buf:
                state["finishing"] = True
            else:
                logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class Recover(_LinearStage):
    def __init__(self, fn):
        super().__init__("Recover")
        self.fn = fn

    def create_logic(self):
        logic, in_, out, fn = self._logic(), self.in_, self.out, self.fn

        def on_failure(ex):
            try:
                elem = fn(ex)
            except Exception as e:  # noqa: BLE001
                logic.fail_stage(e)
                return
            logic.emit(out, elem)
            logic.complete(out)
        logic.set_handler(in_, make_in_handler(
            lambda: logic.push(out, logic.grab(in_)),
            on_upstream_failure=on_failure))
        logic.set_handler(out, make_out_handler(
            lambda: logic.pull(in_) if not logic.is_closed(in_) else None))
        return logic


class Log(_LinearStage):
    def __init__(self, log_name: str, extract):
        super().__init__("Log")
        self.log_name = log_name
        self.extract = extract

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        log_name, extract = self.log_name, self.extract

        def _log(kind: str, msg: str):
            log = logic.materializer.system.log if logic.materializer else None
            if log is None:
                return
            # Attributes.log_levels picks the level per event kind
            # (reference: ActorAttributes.logLevels honored by Ops.scala Log)
            levels = ("debug", "debug", "error")
            if logic.attributes is not None:
                levels = logic.attributes.get("log_levels", levels)
            level = dict(zip(("element", "finish", "failure"), levels))[kind]
            getattr(log, level, log.debug)(msg)

        def on_push():
            elem = logic.grab(in_)
            _log("element", f"[{log_name}] element: {extract(elem)}")
            logic.push(out, elem)

        def on_finish():
            _log("finish", f"[{log_name}] upstream finished")
            logic.complete_stage()

        def on_failure(ex):
            _log("failure", f"[{log_name}] upstream failed: {ex!r}")
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class WireTap(_LinearStage):
    def __init__(self, fn):
        super().__init__("WireTap")
        self.fn = fn

    def create_logic(self):
        logic, in_, out, fn = self._logic(), self.in_, self.out, self.fn

        def on_push():
            elem = logic.grab(in_)
            try:
                fn(elem)
            except Exception:  # noqa: BLE001 — taps must not break the stream
                pass
            logic.push(out, elem)
        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class FlatMapConcat(_LinearStage):
    """Each element maps to a Source; sources run one after another via
    sub-materialization + queue bridge (reference: StreamOfStreams.scala)."""

    def __init__(self, fn):
        super().__init__("FlatMapConcat")
        self.fn = fn

    def create_logic(self):
        in_, out, fn = self.in_, self.out, self.fn
        state = {"sub": None, "finishing": False}

        class _L(GraphStageLogic):
            def _start_sub(self, elem):
                from .dsl import Keep, Sink
                source = fn(elem)
                mat = self.materializer
                queue = source.to_mat(Sink.queue(), Keep.right).run(mat)
                state["sub"] = queue
                self._pull_sub()

            def _pull_sub(self):
                cb = self.get_async_callback(self._sub_event)
                state["sub"].pull().add_done_callback(
                    lambda f: cb.invoke(f))

            def _sub_event(self, f):
                ex = f.exception()
                if ex is not None:
                    self.fail_stage(ex)
                    return
                item = f.result()
                if item is _QUEUE_END:
                    state["sub"] = None
                    if state["finishing"]:
                        self.complete_stage()
                    elif not self.is_closed(in_):
                        self.pull(in_)
                    else:
                        self.complete_stage()
                else:
                    self.emit(out, item, and_then=self._pull_sub)
        logic = _L(self._shape)

        def on_push():
            logic._start_sub(logic.grab(in_))

        def on_pull():
            if state["sub"] is None and not logic.has_been_pulled(in_) \
                    and not logic.is_closed(in_):
                logic.pull(in_)

        def on_finish():
            if state["sub"] is not None:
                state["finishing"] = True
            else:
                logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


# =============================== fan stages =================================

class MergeStage(GraphStage):
    def __init__(self, n: int):
        self.name = "Merge"
        self.ins = [Inlet(f"Merge.in{i}") for i in range(n)]
        self.out = Outlet("Merge.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        ins, out = self.ins, self.out
        # at most ONE buffered element per inlet (reference Merge holds one
        # pending per input; re-pull only after that element is consumed)
        buf: collections.deque = collections.deque()  # (elem, inlet)
        logic = GraphStageLogic(self._shape)

        def mk_push(inlet):
            def on_push():
                elem = logic.grab(inlet)
                if logic.is_available(out) and not buf:
                    logic.push(out, elem)
                    logic.pull(inlet)
                else:
                    buf.append((elem, inlet))  # backpressure this inlet
            return on_push

        def mk_finish(inlet):
            def on_finish():
                if all(logic.is_closed(i) for i in ins) and not buf:
                    logic.complete(out)
            return on_finish

        for inlet in ins:
            logic.set_handler(inlet, make_in_handler(mk_push(inlet),
                                                     mk_finish(inlet)))

        def on_pull():
            if buf:
                elem, inlet = buf.popleft()
                logic.push(out, elem)
                if not logic.is_closed(inlet):
                    logic.pull(inlet)
                if not buf and all(logic.is_closed(i) for i in ins):
                    logic.complete(out)
                return
            for inlet in ins:
                if not logic.has_been_pulled(inlet) and \
                        not logic.is_closed(inlet):
                    logic.pull(inlet)
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class ConcatStage(GraphStage):
    def __init__(self, n: int):
        self.name = "Concat"
        self.ins = [Inlet(f"Concat.in{i}") for i in range(n)]
        self.out = Outlet("Concat.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        ins, out = self.ins, self.out
        state = {"active": 0}
        logic = GraphStageLogic(self._shape)

        def mk_push(i, inlet):
            def on_push():
                logic.push(out, logic.grab(inlet))
            return on_push

        def mk_finish(i, inlet):
            def on_finish():
                if state["active"] == i:
                    state["active"] += 1
                    if state["active"] >= len(ins):
                        logic.complete(out)
                    elif logic.is_available(out) or True:
                        nxt = ins[state["active"]]
                        if logic.is_closed(nxt):
                            mk_finish(state["active"], nxt)()
                        elif logic.is_available(out) and \
                                not logic.has_been_pulled(nxt):
                            logic.pull(nxt)
            return on_finish

        for i, inlet in enumerate(ins):
            logic.set_handler(inlet, make_in_handler(mk_push(i, inlet),
                                                     mk_finish(i, inlet)))

        def on_pull():
            inlet = ins[state["active"]]
            if not logic.has_been_pulled(inlet) and not logic.is_closed(inlet):
                logic.pull(inlet)
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class OrElseStage(GraphStage):
    def __init__(self):
        self.name = "OrElse"
        self.ins = [Inlet("OrElse.primary"), Inlet("OrElse.secondary")]
        self.out = Outlet("OrElse.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        primary, secondary = self.ins
        out = self.out
        state = {"primary_emitted": False, "use_secondary": False}
        logic = GraphStageLogic(self._shape)

        def primary_push():
            state["primary_emitted"] = True
            if not logic.is_closed(secondary):
                logic.cancel(secondary)
            logic.push(out, logic.grab(primary))

        def primary_finish():
            if state["primary_emitted"]:
                logic.complete_stage()
            else:
                state["use_secondary"] = True
                if logic.is_available(out) and \
                        not logic.has_been_pulled(secondary) and \
                        not logic.is_closed(secondary):
                    logic.pull(secondary)
                elif logic.is_closed(secondary):
                    logic.complete(out)

        def secondary_push():
            logic.push(out, logic.grab(secondary))

        def secondary_finish():
            if state["use_secondary"]:
                logic.complete(out)

        logic.set_handler(primary, make_in_handler(primary_push,
                                                   primary_finish))
        logic.set_handler(secondary, make_in_handler(secondary_push,
                                                     secondary_finish))

        def on_pull():
            inlet = secondary if state["use_secondary"] else primary
            if logic.is_closed(inlet):
                logic.complete(out)
            elif not logic.has_been_pulled(inlet):
                logic.pull(inlet)
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class ZipWithStage(GraphStage):
    def __init__(self, fn):
        self.name = "ZipWith"
        self.fn = fn
        self.ins = [Inlet("Zip.in0"), Inlet("Zip.in1")]
        self.out = Outlet("Zip.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        i0, i1 = self.ins
        out, fn = self.out, self.fn
        logic = GraphStageLogic(self._shape)

        def try_push():
            if logic.is_available(i0) and logic.is_available(i1):
                a, b = logic.grab(i0), logic.grab(i1)
                logic.push(out, fn(a, b))
                if logic.is_closed(i0) or logic.is_closed(i1):
                    logic.complete_stage()

        def mk_finish(inlet):
            def on_finish():
                if not logic.is_available(inlet):
                    logic.complete_stage()
            return on_finish

        logic.set_handler(i0, make_in_handler(try_push, mk_finish(i0)))
        logic.set_handler(i1, make_in_handler(try_push, mk_finish(i1)))

        def on_pull():
            for inlet in (i0, i1):
                if not logic.has_been_pulled(inlet) and \
                        not logic.is_closed(inlet) and \
                        not logic.is_available(inlet):
                    logic.pull(inlet)
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class InterleaveStage(GraphStage):
    """N-way round-robin interleave: `segment` elements from each input in
    turn (reference Interleave supports any input count — interleaveAll
    must yield round-robin order ACROSS all sources, which chained 2-way
    interleaves would not)."""

    def __init__(self, segment_size: int, n: int = 2):
        self.name = "Interleave"
        self.segment = max(1, segment_size)
        self.ins = [Inlet(f"Ilv.in{i}") for i in range(n)]
        self.out = Outlet("Ilv.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        ins, out, segment = self.ins, self.out, self.segment
        state = {"cur": 0, "count": 0}
        logic = GraphStageLogic(self._shape)

        def switch():
            state["count"] = 0
            for step in range(1, len(ins) + 1):
                nxt = (state["cur"] + step) % len(ins)
                if not logic.is_closed(ins[nxt]):
                    state["cur"] = nxt
                    return

        def mk_push(i, inlet):
            def on_push():
                logic.push(out, logic.grab(inlet))
                state["count"] += 1
                if state["count"] >= segment:
                    switch()
            return on_push

        def mk_finish(i, inlet):
            def on_finish():
                if all(logic.is_closed(x) for x in ins):
                    logic.complete(out)
                elif state["cur"] == i:
                    switch()
                    if logic.is_available(out):
                        nxt = ins[state["cur"]]
                        if not logic.has_been_pulled(nxt) and \
                                not logic.is_closed(nxt):
                            logic.pull(nxt)
            return on_finish

        for i, inlet in enumerate(ins):
            logic.set_handler(inlet, make_in_handler(mk_push(i, inlet),
                                                     mk_finish(i, inlet)))

        def on_pull():
            inlet = ins[state["cur"]]
            if logic.is_closed(inlet):
                switch()
                inlet = ins[state["cur"]]
            if logic.is_closed(inlet):
                logic.complete(out)
            elif not logic.has_been_pulled(inlet):
                logic.pull(inlet)
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class BroadcastStage(GraphStage):
    def __init__(self, n: int, eager_cancel: bool = False):
        self.name = "Broadcast"
        self.eager_cancel = eager_cancel
        self.in_ = Inlet("Bcast.in")
        self.outs = [Outlet(f"Bcast.out{i}") for i in range(n)]
        self._shape = FanOutShape(self.in_, self.outs)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        in_, outs, eager = self.in_, self.outs, self.eager_cancel
        logic = GraphStageLogic(self._shape)

        def _ready() -> bool:
            """Pull upstream only when every OPEN output has demand —
            cancellation of one output must re-evaluate, not freeze, the
            wait condition."""
            open_outs = [o for o in outs if not logic.is_closed(o)]
            return bool(open_outs) and all(logic.is_available(o)
                                           for o in open_outs)

        def _maybe_pull():
            if _ready() and not logic.has_been_pulled(in_) \
                    and not logic.is_closed(in_):
                logic.pull(in_)

        def on_push():
            elem = logic.grab(in_)
            for o in outs:
                if not logic.is_closed(o):
                    logic.push(o, elem)

        def on_finish():
            logic.complete_stage()

        logic.set_handler(in_, make_in_handler(on_push, on_finish))

        def mk_pull(o):
            return lambda: _maybe_pull()

        def mk_cancel(o):
            def on_cancel(cause=None):
                if eager:
                    logic.complete_stage()
                    return
                if all(logic.is_closed(x) for x in outs):
                    logic.cancel(in_)
                else:
                    _maybe_pull()
            return on_cancel

        for o in outs:
            logic.set_handler(o, make_out_handler(mk_pull(o), mk_cancel(o)))
        return logic


class BalanceStage(GraphStage):
    def __init__(self, n: int):
        self.name = "Balance"
        self.in_ = Inlet("Balance.in")
        self.outs = [Outlet(f"Balance.out{i}") for i in range(n)]
        self._shape = FanOutShape(self.in_, self.outs)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        in_, outs = self.in_, self.outs
        logic = GraphStageLogic(self._shape)

        def on_push():
            elem = logic.grab(in_)
            for o in outs:
                if logic.is_available(o):
                    logic.push(o, elem)
                    return
            # no one pulled meanwhile (shouldn't happen): drop

        def on_finish():
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))

        def mk_pull(o):
            def on_pull():
                if not logic.has_been_pulled(in_) and not logic.is_closed(in_):
                    logic.pull(in_)
            return on_pull

        def mk_cancel(o):
            def on_cancel(cause=None):
                if all(logic.is_closed(x) for x in outs):
                    logic.cancel(in_)
            return on_cancel
        for o in outs:
            logic.set_handler(o, make_out_handler(mk_pull(o), mk_cancel(o)))
        return logic


class PartitionStage(GraphStage):
    def __init__(self, n: int, partitioner):
        self.name = "Partition"
        self.partitioner = partitioner
        self.in_ = Inlet("Partition.in")
        self.outs = [Outlet(f"Partition.out{i}") for i in range(n)]
        self._shape = FanOutShape(self.in_, self.outs)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        in_, outs, partitioner = self.in_, self.outs, self.partitioner
        logic = GraphStageLogic(self._shape)
        waiting = {"elem": None, "target": None}

        def on_push():
            elem = logic.grab(in_)
            i = partitioner(elem)
            o = outs[i]
            if logic.is_closed(o):
                logic.pull(in_)  # partition target gone: drop
            elif logic.is_available(o):
                logic.push(o, elem)
            else:
                waiting["elem"], waiting["target"] = elem, o

        def on_finish():
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))

        def mk_pull(o):
            def on_pull():
                if waiting["target"] is o:
                    elem = waiting["elem"]
                    waiting["elem"] = waiting["target"] = None
                    logic.push(o, elem)
                elif waiting["target"] is None and \
                        not logic.has_been_pulled(in_) and \
                        not logic.is_closed(in_):
                    logic.pull(in_)
            return on_pull

        def mk_cancel(o):
            def on_cancel(cause=None):
                if all(logic.is_closed(x) for x in outs):
                    logic.cancel(in_)
            return on_cancel
        for o in outs:
            logic.set_handler(o, make_out_handler(mk_pull(o), mk_cancel(o)))
        return logic


# =============================== sinks ======================================

class _SinkStage(GraphStage):
    def __init__(self, name: str):
        self.name = name
        self.in_ = Inlet(f"{name}.in")
        self._shape = SinkShape(self.in_)

    @property
    def shape(self):
        return self._shape


class _PullAllLogic(GraphStageLogic):
    def __init__(self, shape, inlet):
        super().__init__(shape)
        self._inlet = inlet

    def pre_start(self):
        self.pull(self._inlet)


def _sink_logic(stage: "_SinkStage", on_elem, fut: Future,
                result_fn=lambda: None,
                empty_error: Optional[Callable[[], BaseException]] = None,
                cleanup_fn=None):
    logic = _PullAllLogic(stage._shape, stage.in_)
    in_ = stage.in_

    def _cleanup():
        if cleanup_fn is not None:
            try:
                cleanup_fn()
            except Exception:  # noqa: BLE001 — cleanup must not mask the error
                pass

    def on_push():
        try:
            on_elem(logic.grab(in_))
        except Exception as e:  # noqa: BLE001
            if not fut.done():
                fut.set_exception(e)
            _cleanup()
            logic.cancel_stage(e)
            return
        logic.pull(in_)

    def on_finish():
        if not fut.done():
            err = empty_error() if empty_error is not None else None
            if err is not None:
                fut.set_exception(err)
            else:
                fut.set_result(result_fn())
        logic.complete_stage()

    def on_failure(ex):
        if not fut.done():
            fut.set_exception(ex)
        _cleanup()
        logic.fail_stage(ex)
    logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
    return logic


class IgnoreSink(_SinkStage):
    def __init__(self):
        super().__init__("IgnoreSink")

    def create_logic_and_mat(self):
        fut: Future = Future()
        return _sink_logic(self, lambda e: None, fut,
                           result_fn=lambda: None), fut


class ForeachSink(_SinkStage):
    def __init__(self, fn):
        super().__init__("ForeachSink")
        self.fn = fn

    def create_logic_and_mat(self):
        fut: Future = Future()
        return _sink_logic(self, self.fn, fut, result_fn=lambda: None), fut


class SeqSink(_SinkStage):
    def __init__(self):
        super().__init__("SeqSink")

    def create_logic_and_mat(self):
        fut: Future = Future()
        acc: List = []
        return _sink_logic(self, acc.append, fut,
                           result_fn=lambda: list(acc)), fut


class FoldSink(_SinkStage):
    def __init__(self, zero, fn):
        super().__init__("FoldSink")
        self.zero = zero
        self.fn = fn

    def create_logic_and_mat(self):
        fut: Future = Future()
        state = {"acc": self.zero}
        fn = self.fn

        def on_elem(e):
            state["acc"] = fn(state["acc"], e)
        return _sink_logic(self, on_elem, fut,
                           result_fn=lambda: state["acc"]), fut


class ReduceSink(_SinkStage):
    def __init__(self, fn):
        super().__init__("ReduceSink")
        self.fn = fn

    def create_logic_and_mat(self):
        fut: Future = Future()
        state = {"acc": None, "has": False}
        fn = self.fn

        def on_elem(e):
            state["acc"] = e if not state["has"] else fn(state["acc"], e)
            state["has"] = True

        def empty_error():
            return None if state["has"] else \
                NoSuchElementException("reduce of empty stream")
        return _sink_logic(self, on_elem, fut,
                           result_fn=lambda: state["acc"],
                           empty_error=empty_error), fut


class HeadSink(_SinkStage):
    def __init__(self, require: bool):
        super().__init__("HeadSink")
        self.require = require

    def create_logic_and_mat(self):
        fut: Future = Future()
        stage = self
        logic = _PullAllLogic(self._shape, self.in_)
        in_ = self.in_

        def on_push():
            elem = logic.grab(in_)
            if not fut.done():
                fut.set_result(elem)
            logic.cancel(in_)

        def on_finish():
            if not fut.done():
                if stage.require:
                    fut.set_exception(NoSuchElementException("empty stream"))
                else:
                    fut.set_result(None)

        def on_failure(ex):
            if not fut.done():
                fut.set_exception(ex)
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic, fut


class LastSink(_SinkStage):
    def __init__(self, require: bool):
        super().__init__("LastSink")
        self.require = require

    def create_logic_and_mat(self):
        fut: Future = Future()
        state = {"last": None, "has": False}
        require = self.require

        def on_elem(e):
            state["last"], state["has"] = e, True

        def empty_err():
            return NoSuchElementException("empty stream") \
                if require and not state["has"] else None
        logic = _PullAllLogic(self._shape, self.in_)
        in_ = self.in_

        def on_push():
            on_elem(logic.grab(in_))
            logic.pull(in_)

        def on_finish():
            if not fut.done():
                if not state["has"] and require:
                    fut.set_exception(NoSuchElementException("empty stream"))
                else:
                    fut.set_result(state["last"])

        def on_failure(ex):
            if not fut.done():
                fut.set_exception(ex)
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic, fut


class OnCompleteSink(_SinkStage):
    def __init__(self, fn):
        super().__init__("OnCompleteSink")
        self.fn = fn

    def create_logic_and_mat(self):
        fn = self.fn
        logic = _PullAllLogic(self._shape, self.in_)
        in_ = self.in_

        def on_push():
            logic.grab(in_)
            logic.pull(in_)
        logic.set_handler(in_, make_in_handler(
            on_push,
            on_upstream_finish=lambda: (fn(None), logic.complete_stage()),
            on_upstream_failure=lambda ex: (fn(ex), logic.fail_stage(ex))))
        return logic, None


_QUEUE_END = object()


class SinkQueue:
    """Mat value of Sink.queue: pull() -> Future[elem | QUEUE_END];
    cancel() tears the upstream down (reference SinkQueueWithCancel)."""

    def __init__(self):
        self._cb = None
        self._cancel_cb = None
        self._lock = threading.Lock()
        self._early: List[Future] = []
        self._early_cancel = False
        self._terminal = None  # ("complete",) | ("fail", ex) once drained
        # every unresolved pull future: a pull dispatched into the stage's
        # interpreter just before it shuts down would otherwise be dropped
        # with the mailbox and never resolve — _set_terminal sweeps these
        self._outstanding: List[Future] = []

    def _bind(self, cb, cancel_cb=None):
        with self._lock:
            self._cb, self._cancel_cb = cb, cancel_cb
            early, self._early = self._early, []
            do_cancel = self._early_cancel
        for fut in early:
            self._cb.invoke(fut)
        if do_cancel and cancel_cb is not None:
            cancel_cb.invoke(None)

    def cancel(self) -> None:
        with self._lock:
            if self._terminal is not None:
                return
            cb = self._cancel_cb
            if cb is None:
                self._early_cancel = True
                return
        cb.invoke(None)

    def _set_terminal(self, done) -> None:
        with self._lock:
            self._terminal = done
            swept = [f for f in self._outstanding if not f.done()]
            self._outstanding = []
        for fut in swept:
            if fut.done():
                continue
            if done[0] == "complete":
                fut.set_result(_QUEUE_END)
            else:
                fut.set_exception(done[1])

    def pull(self) -> Future:
        fut: Future = Future()
        with self._lock:
            if self._terminal is not None:
                # stage may already be gone: answer from the cached terminal
                if self._terminal[0] == "complete":
                    fut.set_result(_QUEUE_END)
                else:
                    fut.set_exception(self._terminal[1])
                return fut
            # prune resolved futures so a long-lived consumer doesn't pin
            # one Future (and its element) per pull for the stream's life
            self._outstanding = [f for f in self._outstanding
                                 if not f.done()]
            self._outstanding.append(fut)
            if self._cb is None:
                self._early.append(fut)
                return fut
        self._cb.invoke(fut)
        return fut


class QueueSink(_SinkStage):
    def __init__(self, buffer_size: int):
        super().__init__("QueueSink")
        self.buffer_size = buffer_size

    def create_logic_and_mat(self):
        stage = self
        in_ = self.in_
        mat = SinkQueue()
        buf: collections.deque = collections.deque()
        waiters: collections.deque = collections.deque()
        state = {"done": None}  # None | ("complete",) | ("fail", ex)

        class _L(GraphStageLogic):
            def pre_start(self):
                # stay alive after upstream completes until the buffer is
                # pulled dry (reference: QueueSink setKeepGoing(true))
                self.set_keep_going(True)
                mat._bind(self.get_async_callback(self._on_pull_req),
                          self.get_async_callback(self._on_cancel_req))
                self.pull(in_)

            def _on_cancel_req(self, _):
                if state["done"] is None:
                    state["done"] = ("complete",)
                buf.clear()
                while waiters:
                    waiters.popleft().set_result(_QUEUE_END)
                if not self.is_closed(in_):
                    self.cancel(in_)
                self._finish_drained()

            def _on_pull_req(self, fut: Future):
                if fut.done():
                    return  # already swept by _set_terminal
                if buf:
                    fut.set_result(buf.popleft())
                    if not buf and state["done"] is not None:
                        self._finish_drained()
                    if not self.has_been_pulled(in_) and \
                            not self.is_closed(in_):
                        self.pull(in_)
                elif state["done"] is not None:
                    if state["done"][0] == "complete":
                        fut.set_result(_QUEUE_END)
                    else:
                        fut.set_exception(state["done"][1])
                    self._finish_drained()
                else:
                    waiters.append(fut)

            def _finish_drained(self):
                mat._set_terminal(state["done"])
                self.set_keep_going(False)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            if waiters:
                waiters.popleft().set_result(elem)
                logic.pull(in_)
            else:
                buf.append(elem)
                if len(buf) < stage.buffer_size:
                    logic.pull(in_)

        def on_finish():
            state["done"] = ("complete",)
            while waiters:
                waiters.popleft().set_result(_QUEUE_END)
            if not buf:
                logic._finish_drained()

        def on_failure(ex):
            state["done"] = ("fail", ex)
            while waiters:
                waiters.popleft().set_exception(ex)
            if not buf:
                logic._finish_drained()
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic, mat


class ActorRefSink(_SinkStage):
    def __init__(self, ref, on_complete_message, on_failure_message=None):
        super().__init__("ActorRefSink")
        self.ref = ref
        self.on_complete_message = on_complete_message
        self.on_failure_message = on_failure_message

    def create_logic_and_mat(self):
        stage = self
        in_ = self.in_
        logic = _PullAllLogic(self._shape, in_)

        def on_push():
            stage.ref.tell(logic.grab(in_), None)
            logic.pull(in_)

        def on_finish():
            stage.ref.tell(stage.on_complete_message, None)
            logic.complete_stage()

        def on_failure(ex):
            if stage.on_failure_message is not None:
                stage.ref.tell(stage.on_failure_message(ex), None)
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic, None
