"""GraphStage API: user-definable stream operators.

Reference parity: akka-stream/src/main/scala/akka/stream/stage/
GraphStage.scala — GraphStageLogic with per-port InHandler/OutHandler,
pull/push/grab/complete/fail/cancel, completeStage/failStage, emit,
AsyncCallback (getAsyncCallback), timers (TimerGraphStageLogic); Shape/
Inlet/Outlet from akka-stream/src/main/scala/akka/stream/Shape.scala.

The port-state machine semantics these helpers enforce are the interpreter's
(see interpreter.py, mirroring impl/fusing/GraphInterpreter.scala:154-198).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

_port_ids = itertools.count()


class Inlet:
    __slots__ = ("name", "id")

    def __init__(self, name: str = "in"):
        self.name = name
        self.id = next(_port_ids)

    def __repr__(self):
        return f"Inlet({self.name})"


class Outlet:
    __slots__ = ("name", "id")

    def __init__(self, name: str = "out"):
        self.name = name
        self.id = next(_port_ids)

    def __repr__(self):
        return f"Outlet({self.name})"


class Shape:
    """(reference: stream/Shape.scala)"""

    def __init__(self, inlets: Sequence[Inlet], outlets: Sequence[Outlet]):
        self.inlets = list(inlets)
        self.outlets = list(outlets)


class SourceShape(Shape):
    def __init__(self, out: Outlet):
        super().__init__([], [out])
        self.out = out


class SinkShape(Shape):
    def __init__(self, in_: Inlet):
        super().__init__([in_], [])
        self.in_ = in_


class FlowShape(Shape):
    def __init__(self, in_: Inlet, out: Outlet):
        super().__init__([in_], [out])
        self.in_ = in_
        self.out = out


class FanInShape(Shape):
    def __init__(self, ins: Sequence[Inlet], out: Outlet):
        super().__init__(list(ins), [out])
        self.ins = list(ins)
        self.out = out


class FanOutShape(Shape):
    def __init__(self, in_: Inlet, outs: Sequence[Outlet]):
        super().__init__([in_], list(outs))
        self.in_ = in_
        self.outs = list(outs)


class InHandler:
    """(reference: stage/GraphStage.scala InHandler)"""

    def on_push(self) -> None:
        raise NotImplementedError

    def on_upstream_finish(self) -> None:
        self._logic.complete_stage()  # type: ignore[attr-defined]

    def on_upstream_failure(self, ex: BaseException) -> None:
        self._logic.fail_stage(ex)  # type: ignore[attr-defined]


class OutHandler:
    """(reference: stage/GraphStage.scala OutHandler)"""

    def on_pull(self) -> None:
        raise NotImplementedError

    def on_downstream_finish(self, cause: Optional[BaseException] = None) -> None:
        self._logic.cancel_stage(cause)  # type: ignore[attr-defined]


def make_in_handler(on_push: Callable[[], None],
                    on_upstream_finish: Optional[Callable[[], None]] = None,
                    on_upstream_failure: Optional[
                        Callable[[BaseException], None]] = None) -> InHandler:
    h = InHandler()
    h.on_push = on_push  # type: ignore[method-assign]
    if on_upstream_finish is not None:
        h.on_upstream_finish = on_upstream_finish  # type: ignore[method-assign]
    if on_upstream_failure is not None:
        h.on_upstream_failure = on_upstream_failure  # type: ignore[method-assign]
    return h


def make_out_handler(on_pull: Callable[[], None],
                     on_downstream_finish: Optional[
                         Callable[[Optional[BaseException]], None]] = None
                     ) -> OutHandler:
    h = OutHandler()
    h.on_pull = on_pull  # type: ignore[method-assign]
    if on_downstream_finish is not None:
        h.on_downstream_finish = on_downstream_finish  # type: ignore[method-assign]
    return h


class AsyncCallback:
    """Thread-safe entry back into the stream (reference:
    GraphStageLogic.getAsyncCallback). invoke() may be called from any
    thread; the handler runs inside the interpreter."""

    def __init__(self, interpreter, logic, handler: Callable[[Any], None]):
        self._interpreter = interpreter
        self._logic = logic
        self._handler = handler

    def invoke(self, event: Any = None) -> None:
        # resolve lazily: a callback created inside create_logic (before the
        # logic is wired into an interpreter) must still work at runtime
        interp = self._interpreter if self._interpreter is not None \
            else self._logic.interpreter
        interp.enqueue_async(self._logic, self._handler, event)


class GraphStageLogic:
    """Per-materialization mutable operator state + port operations."""

    def __init__(self, shape: Shape):
        self.shape = shape
        self.handlers: Dict[int, Any] = {}
        self.interpreter = None  # set at materialization
        self._emit_queues: Dict[int, List[Any]] = {}
        self._closed = False
        self._keep_going = False
        # stamped by the builder from the enclosing with_attributes section
        # (Attributes.scala analogue); consulted by the interpreter for the
        # supervision decider
        self.attributes = None
        # stages with accumulated state set this to a zero-state reset
        # callback; the Supervision.restart directive invokes it (the
        # reference's restart recreating operator state, Ops.scala Scan etc.)
        self.restart_state: Optional[Callable[[], None]] = None

    # -- wiring ---------------------------------------------------------------
    def set_handler(self, port, handler) -> None:
        handler._logic = self
        self.handlers[port.id] = handler

    def in_handler(self, inlet: Inlet) -> InHandler:
        return self.handlers[inlet.id]

    def out_handler(self, outlet: Outlet) -> OutHandler:
        return self.handlers[outlet.id]

    # -- lifecycle hooks ------------------------------------------------------
    def pre_start(self) -> None:
        pass

    def post_stop(self) -> None:
        pass

    # -- port ops (delegate to the interpreter's port-state machine) ---------
    def pull(self, inlet: Inlet) -> None:
        self.interpreter.pull(self, inlet)

    def push(self, outlet: Outlet, elem: Any) -> None:
        q = self._emit_queues.get(outlet.id)
        if q:
            q.append(elem)  # keep emit order
            return
        self.interpreter.push(self, outlet, elem)

    def grab(self, inlet: Inlet) -> Any:
        return self.interpreter.grab(self, inlet)

    def is_available(self, port) -> bool:
        return self.interpreter.is_available(self, port)

    def has_been_pulled(self, inlet: Inlet) -> bool:
        return self.interpreter.has_been_pulled(self, inlet)

    def is_closed(self, port) -> bool:
        return self.interpreter.is_port_closed(self, port)

    def complete(self, outlet: Outlet) -> None:
        q = self._emit_queues.get(outlet.id)
        if q:
            q.append("__COMPLETE__")  # in place: _drain_emit may be iterating
            return
        self.interpreter.complete(self, outlet)

    def fail(self, outlet: Outlet, ex: BaseException) -> None:
        self.interpreter.fail(self, outlet, ex)

    def cancel(self, inlet: Inlet, cause: Optional[BaseException] = None) -> None:
        self.interpreter.cancel(self, inlet, cause)

    def complete_stage(self) -> None:
        for inlet in self.shape.inlets:
            if not self.is_closed(inlet):
                self.cancel(inlet)
        for outlet in self.shape.outlets:
            if not self.is_closed(outlet):
                self.complete(outlet)

    def fail_stage(self, ex: BaseException) -> None:
        for inlet in self.shape.inlets:
            if not self.is_closed(inlet):
                self.cancel(inlet, ex)
        for outlet in self.shape.outlets:
            if not self.is_closed(outlet):
                self.fail(outlet, ex)

    def cancel_stage(self, cause: Optional[BaseException] = None) -> None:
        if cause is None:
            self.complete_stage()
        else:
            self.fail_stage(cause)

    # -- emit: push now or as soon as pulled (reference: emit/emitMultiple) --
    def emit(self, outlet: Outlet, elem: Any,
             and_then: Optional[Callable[[], None]] = None) -> None:
        if self.is_available(outlet) and not self._emit_queues.get(outlet.id):
            self.interpreter.push(self, outlet, elem)
            if and_then is not None:
                and_then()
        else:
            self._emit_queues.setdefault(outlet.id, []).append(elem)
            if and_then is not None:
                self._emit_queues[outlet.id].append(("__THEN__", and_then))

    def emit_multiple(self, outlet: Outlet, elems,
                      and_then: Optional[Callable[[], None]] = None) -> None:
        elems = list(elems)
        if not elems:
            if and_then is not None:
                and_then()
            return
        for e in elems:
            self.emit(outlet, e)
        if and_then is not None:
            self._emit_queues.setdefault(outlet.id, []).append(
                ("__THEN__", and_then))

    def _drain_emit(self, outlet: Outlet) -> bool:
        """Called by the interpreter on pull; returns True if it pushed."""
        q = self._emit_queues.get(outlet.id)
        while q:
            head = q.pop(0)
            if head == "__COMPLETE__":
                self.interpreter.complete(self, outlet)
                return True
            if isinstance(head, tuple) and len(head) == 2 and \
                    head[0] == "__THEN__":
                head[1]()
                continue
            self.interpreter.push(self, outlet, head)
            return True
        return False

    def has_pending_emits(self, outlet: Outlet) -> bool:
        return bool(self._emit_queues.get(outlet.id))

    # -- async + timers -------------------------------------------------------
    def get_async_callback(self, handler: Callable[[Any], None]
                           ) -> AsyncCallback:
        return AsyncCallback(self.interpreter, self, handler)

    def schedule_once(self, key: Any, delay: float) -> None:
        self.interpreter.schedule_timer(self, key, delay, repeat=None)

    def schedule_periodically(self, key: Any, initial: float,
                              interval: float) -> None:
        self.interpreter.schedule_timer(self, key, initial, repeat=interval)

    def cancel_timer(self, key: Any) -> None:
        self.interpreter.cancel_timer(self, key)

    def on_timer(self, key: Any) -> None:
        """Override for timer callbacks (reference: TimerGraphStageLogic)."""

    # -- keep-going (stage alive with all ports closed) ----------------------
    def set_keep_going(self, enabled: bool) -> None:
        self._keep_going = enabled

    @property
    def materializer(self):
        return self.interpreter.materializer


class GraphStage:
    """A reusable blueprint: shape + create_logic (reference:
    stage/GraphStage.scala GraphStageWithMaterializedValue)."""

    name = "stage"

    @property
    def shape(self) -> Shape:
        raise NotImplementedError

    def create_logic_and_mat(self) -> Tuple[GraphStageLogic, Any]:
        return self.create_logic(), None

    def create_logic(self) -> GraphStageLogic:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"
