"""Hubs: dynamic fan-in/fan-out across independent materializations.

Reference parity: akka-stream/src/main/scala/akka/stream/scaladsl/Hub.scala —
MergeHub.source materializes a Sink that MANY producer streams can attach to
at runtime; BroadcastHub.sink materializes a Source that MANY consumer
streams can attach to (slowest-consumer backpressure over a bounded buffer).
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional

from .stage import (GraphStage, GraphStageLogic, Inlet, Outlet, SinkShape,
                    SourceShape, make_in_handler, make_out_handler)


# ============================== MergeHub ====================================

class _MergeHubState:
    """Shared between the hub source stage and attached producer sinks."""

    def __init__(self, per_producer_buffer: int):
        self.lock = threading.Lock()
        self.buffer_size = per_producer_buffer
        self.buf: collections.deque = collections.deque()
        self.waiting_producers: collections.deque = collections.deque()
        self.consumer_cb = None      # async callback into the hub source
        self.closed = False


class _MergeHubSource(GraphStage):
    def __init__(self, state: _MergeHubState):
        self.name = "MergeHubSource"
        self.state = state
        self.out = Outlet("MergeHub.out")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        st, out = self.state, self.out

        class _L(GraphStageLogic):
            def pre_start(self):
                with st.lock:
                    st.consumer_cb = self.get_async_callback(self._wakeup)

            def _wakeup(self, _):
                self._try_emit()

            def _try_emit(self):
                while self.is_available(out):
                    with st.lock:
                        if not st.buf:
                            return
                        elem = st.buf.popleft()
                        resume = None
                        if st.waiting_producers:
                            resume = st.waiting_producers.popleft()
                    self.push(out, elem)
                    if resume is not None:
                        resume.invoke(None)

            def post_stop(self):
                with st.lock:
                    st.closed = True
                    waiting = list(st.waiting_producers)
                    st.waiting_producers.clear()
                for w in waiting:
                    w.invoke(None)
        logic = _L(self._shape)
        logic.set_handler(out, make_out_handler(
            lambda: logic._try_emit(),
            lambda cause=None: logic.post_stop() or logic.cancel_stage(cause)))
        return logic


class _MergeHubSink(GraphStage):
    def __init__(self, state: _MergeHubState):
        self.name = "MergeHubSink"
        self.state = state
        self.in_ = Inlet("MergeHub.in")
        self._shape = SinkShape(self.in_)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        st, in_ = self.state, self.in_

        class _L(GraphStageLogic):
            def pre_start(self):
                self._resume_cb = self.get_async_callback(
                    lambda _: self._resume())
                self.pull(in_)

            def _resume(self):
                with st.lock:
                    closed = st.closed
                if closed:
                    self.complete_stage()
                elif not self.has_been_pulled(in_) and \
                        not self.is_closed(in_):
                    self.pull(in_)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            wake = None
            with st.lock:
                if st.closed:
                    pass  # consumer gone: drop + complete below
                else:
                    st.buf.append(elem)
                    wake = st.consumer_cb
                    if len(st.buf) >= st.buffer_size:
                        st.waiting_producers.append(logic._resume_cb)
                        if wake is not None:
                            wake.invoke(None)
                        return  # backpressure this producer
            if st.closed:
                logic.complete_stage()
                return
            if wake is not None:
                wake.invoke(None)
            logic.pull(in_)
        logic.set_handler(in_, make_in_handler(on_push))
        return logic


class MergeHub:
    @staticmethod
    def source(per_producer_buffer_size: int = 16):
        """Source whose mat value is a reusable Sink producers attach to."""
        from .dsl import Sink, Source

        def build(b):
            state = _MergeHubState(per_producer_buffer_size)
            logic, _ = b.add(_MergeHubSource(state))
            attach_sink = Sink.from_graph(lambda: _MergeHubSink(state))
            return logic.shape.outlets[0], attach_sink
        return Source(build)


# ============================= BroadcastHub =================================

class _BroadcastHubState:
    def __init__(self, buffer_size: int):
        self.lock = threading.Lock()
        self.buffer_size = buffer_size
        self.consumers: List["_ConsumerSlot"] = []
        self.pending: collections.deque = collections.deque()  # pre-consumer
        self.upstream_cb = None
        self.done = None  # ("complete",) | ("fail", ex)


class _ConsumerSlot:
    def __init__(self, cb):
        self.cb = cb  # async callback into the consumer source stage
        self.buf: collections.deque = collections.deque()


class _BroadcastHubSink(GraphStage):
    def __init__(self, state: _BroadcastHubState):
        self.name = "BroadcastHubSink"
        self.state = state
        self.in_ = Inlet("BcastHub.in")
        self._shape = SinkShape(self.in_)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        st, in_ = self.state, self.in_

        class _L(GraphStageLogic):
            def pre_start(self):
                self.set_keep_going(True)
                with st.lock:
                    st.upstream_cb = self.get_async_callback(
                        lambda _: self._maybe_pull())
                self.pull(in_)

            def _maybe_pull(self):
                with st.lock:
                    room = all(len(c.buf) < st.buffer_size
                               for c in st.consumers) \
                        and len(st.pending) < st.buffer_size
                if room and not self.has_been_pulled(in_) and \
                        not self.is_closed(in_):
                    self.pull(in_)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            wakes = []
            with st.lock:
                if st.consumers:
                    for c in st.consumers:
                        c.buf.append(elem)
                        wakes.append(c.cb)
                    room = all(len(c.buf) < st.buffer_size
                               for c in st.consumers)
                else:
                    st.pending.append(elem)
                    room = len(st.pending) < st.buffer_size
            for w in wakes:
                w.invoke(None)
            if room:
                logic.pull(in_)
            # else: slowest consumer backpressures; resumed via upstream_cb

        def on_finish():
            wakes = []
            with st.lock:
                st.done = ("complete",)
                wakes = [c.cb for c in st.consumers]
            for w in wakes:
                w.invoke(None)
            logic.set_keep_going(False)
            logic.complete_stage()

        def on_failure(ex):
            wakes = []
            with st.lock:
                st.done = ("fail", ex)
                wakes = [c.cb for c in st.consumers]
            for w in wakes:
                w.invoke(None)
            logic.set_keep_going(False)
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic


class _BroadcastHubSource(GraphStage):
    def __init__(self, state: _BroadcastHubState):
        self.name = "BroadcastHubSource"
        self.state = state
        self.out = Outlet("BcastHub.out")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        st, out = self.state, self.out
        slot_holder: Dict[str, _ConsumerSlot] = {}

        class _L(GraphStageLogic):
            def pre_start(self):
                slot = _ConsumerSlot(self.get_async_callback(
                    lambda _: self._deliver()))
                slot_holder["slot"] = slot
                with st.lock:
                    # late joiner takes over any pre-consumer backlog once
                    if not st.consumers and st.pending:
                        slot.buf.extend(st.pending)
                        st.pending.clear()
                    st.consumers.append(slot)

            def _deliver(self):
                slot = slot_holder["slot"]
                pulled_upstream = None
                while self.is_available(out):
                    with st.lock:
                        if not slot.buf:
                            break
                        elem = slot.buf.popleft()
                        pulled_upstream = st.upstream_cb
                    self.push(out, elem)
                with st.lock:
                    done = st.done if not slot.buf else None
                if done is not None:
                    if done[0] == "complete":
                        self.complete(out)
                    else:
                        self.fail(out, done[1])
                    return
                if pulled_upstream is not None:
                    pulled_upstream.invoke(None)

            def post_stop(self):
                with st.lock:
                    slot = slot_holder.get("slot")
                    if slot in st.consumers:
                        st.consumers.remove(slot)
                    cb = st.upstream_cb
                if cb is not None:
                    cb.invoke(None)  # fewer consumers: maybe unblock
        logic = _L(self._shape)
        logic.set_handler(out, make_out_handler(lambda: logic._deliver()))
        return logic


class BroadcastHub:
    @staticmethod
    def sink(buffer_size: int = 256):
        """Sink whose mat value is a reusable Source consumers attach to."""
        from .dsl import Sink, Source

        def build(b, upstream):
            state = _BroadcastHubState(buffer_size)
            logic, _ = b.add(_BroadcastHubSink(state))
            b.connect(upstream, logic.shape.inlets[0])
            attach_source = Source.from_graph(
                lambda: _BroadcastHubSource(state))
            return attach_source
        return Sink(build)


# ============================= PartitionHub =================================

class ConsumerInfo:
    """View handed to a stateful partitioner (reference: Hub.scala
    PartitionHub.ConsumerInfo): registered consumer ids in attach order,
    plus per-consumer queue sizes for load-aware routing. Valid only for
    the duration of the partitioner call (it reads the live registry,
    which the hub lock protects during routing — no per-element copies)."""

    __slots__ = ("_order", "_consumers")

    def __init__(self, order, consumers):
        self._order = order
        self._consumers = consumers

    @property
    def consumer_ids(self):
        return tuple(self._order)

    @property
    def size(self) -> int:
        return len(self._order)

    def queue_size(self, consumer_id: int) -> int:
        slot = self._consumers.get(consumer_id)
        return len(slot.buf) if slot is not None else 0

    def consumer_id_by_idx(self, idx: int) -> int:
        return self._order[idx]


class _PartitionHubState:
    def __init__(self, buffer_size: int, start_after: int):
        self.lock = threading.Lock()
        self.buffer_size = buffer_size
        self.start_after = start_after
        self.consumers: Dict[int, _ConsumerSlot] = {}
        self.order: List[int] = []          # attach order (consumerIdByIdx)
        self.next_id = 0
        self.upstream_cb = None
        self.done = None                    # ("complete",) | ("fail", ex)
        self.stash = None                   # (target_id, elem) awaiting room
        self.done_pending = None            # completion awaiting stash flush
        self.started = False                # start_after gate passed once

    def info(self) -> ConsumerInfo:
        # called under lock; the view reads the live registry lazily
        return ConsumerInfo(self.order, self.consumers)


class _PartitionHubSink(GraphStage):
    def __init__(self, state: _PartitionHubState, partitioner):
        self.name = "PartitionHubSink"
        self.state = state
        self.partitioner = partitioner      # (ConsumerInfo, elem) -> id
        self.in_ = Inlet("PartitionHub.in")
        self._shape = SinkShape(self.in_)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):  # noqa: C901
        st, in_, partitioner = self.state, self.in_, self.partitioner

        class _L(GraphStageLogic):
            def pre_start(self):
                self.set_keep_going(True)
                with st.lock:
                    st.upstream_cb = self.get_async_callback(
                        lambda _: self._maybe_pull())
                    st.started = st.started or \
                        len(st.order) >= st.start_after
                    ready = st.started
                if ready:
                    self.pull(in_)
                # else: the start_after'th consumer's registration wakes us

            def _maybe_pull(self):
                """Woken on consumer attach/detach/drain: flush a stashed
                element whose target now has room (or vanished), start
                pulling once start_after consumers registered, and finish a
                deferred completion once the stash is flushed."""
                wake = None
                with st.lock:
                    # the gate is an INITIAL gate only: once passed it never
                    # re-engages when consumers later drop below the
                    # threshold (the reference's RegistrationPending model)
                    if not st.started:
                        if len(st.order) < st.start_after:
                            return
                        st.started = True
                    if st.stash is not None:
                        target, elem = st.stash
                        slot = st.consumers.get(target)
                        if slot is None:
                            st.stash = None      # target left: element drops
                        elif len(slot.buf) < st.buffer_size:
                            st.stash = None
                            slot.buf.append(elem)
                            wake = slot.cb
                        else:
                            return               # still blocked
                if wake is not None:
                    wake.invoke(None)
                if st.done_pending is not None:
                    self._finalize()             # stash flushed: finish now
                    return
                if not self.has_been_pulled(in_) and not self.is_closed(in_):
                    self.pull(in_)

            def _finalize(self):
                with st.lock:
                    st.done = st.done_pending or ("complete",)
                    wakes = [c.cb for c in st.consumers.values()]
                for w in wakes:
                    w.invoke(None)
                self.set_keep_going(False)
                self.complete_stage()
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            wake = None
            blocked = False
            try:
                with st.lock:
                    target = partitioner(st.info(), elem)
            except Exception as ex:  # noqa: BLE001 — user partitioner threw:
                on_failure(ex)       # consumers must see the failure too
                return
            with st.lock:
                slot = st.consumers.get(target)
                if slot is not None:
                    if len(slot.buf) < st.buffer_size:
                        slot.buf.append(elem)
                        wake = slot.cb
                    else:
                        # chosen consumer is full: backpressure upstream
                        # until ITS queue drains (reference PartitionHub
                        # blocks only on the targeted queue)
                        st.stash = (target, elem)
                        wake = slot.cb
                        blocked = True
                # unknown id: element dropped (reference contract)
            if wake is not None:
                wake.invoke(None)
            if not blocked:
                logic.pull(in_)

        def on_finish():
            with st.lock:
                st.done_pending = ("complete",)
                stash = st.stash
                wakes = [c.cb for c in st.consumers.values()]
            if stash is None:
                logic._finalize()
                return
            # a stashed element is still owed to a full consumer: stay
            # alive (keep_going) until its drain wakes _maybe_pull, which
            # flushes the stash and finalizes
            for w in wakes:
                w.invoke(None)

        def on_failure(ex):
            with st.lock:
                st.done = ("fail", ex)
                st.stash = None
                wakes = [c.cb for c in st.consumers.values()]
            for w in wakes:
                w.invoke(None)
            logic.set_keep_going(False)
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic


class _PartitionHubSource(GraphStage):
    def __init__(self, state: _PartitionHubState):
        self.name = "PartitionHubSource"
        self.state = state
        self.out = Outlet("PartitionHub.out")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        st, out = self.state, self.out
        holder: Dict[str, Any] = {}

        class _L(GraphStageLogic):
            def pre_start(self):
                slot = _ConsumerSlot(self.get_async_callback(
                    lambda _: self._deliver()))
                with st.lock:
                    cid = st.next_id
                    st.next_id += 1
                    st.consumers[cid] = slot
                    st.order.append(cid)
                    cb = st.upstream_cb
                holder["slot"], holder["id"] = slot, cid
                if cb is not None:
                    cb.invoke(None)  # may be the start_after'th consumer

            def _deliver(self):
                slot = holder["slot"]
                drained = False
                while self.is_available(out):
                    with st.lock:
                        if not slot.buf:
                            break
                        elem = slot.buf.popleft()
                        drained = True
                    self.push(out, elem)
                with st.lock:
                    done = st.done if not slot.buf else None
                    cb = st.upstream_cb
                if done is not None:
                    if done[0] == "complete":
                        self.complete(out)
                    else:
                        self.fail(out, done[1])
                    return
                if drained and cb is not None:
                    cb.invoke(None)  # room again: unblock a stashed element

            def post_stop(self):
                with st.lock:
                    cid = holder.get("id")
                    st.consumers.pop(cid, None)
                    if cid in st.order:
                        st.order.remove(cid)
                    cb = st.upstream_cb
                if cb is not None:
                    cb.invoke(None)  # a stash targeting us must not wedge
        logic = _L(self._shape)
        logic.set_handler(out, make_out_handler(lambda: logic._deliver()))
        return logic


class PartitionHub:
    """(reference: Hub.scala:737 PartitionHub)"""

    @staticmethod
    def stateful_sink(partitioner_factory, start_after_nr_of_consumers: int = 0,
                      buffer_size: int = 256):
        """Sink whose mat is a reusable Source; `partitioner_factory()`
        yields a fresh `(ConsumerInfo, elem) -> consumer_id` per
        materialization of the sink. Elements routed to an unknown id are
        dropped; upstream is not pulled until start_after consumers
        attached; the targeted consumer's full queue backpressures."""
        from .dsl import Sink, Source

        def build(b, upstream):
            state = _PartitionHubState(buffer_size,
                                       start_after_nr_of_consumers)
            logic, _ = b.add(_PartitionHubSink(state, partitioner_factory()))
            b.connect(upstream, logic.shape.inlets[0])
            return Source.from_graph(lambda: _PartitionHubSource(state))
        return Sink(build)

    @staticmethod
    def sink(partitioner, start_after_nr_of_consumers: int = 1,
             buffer_size: int = 256):
        """Stateless variant: `partitioner(size, elem) -> index` into the
        consumers in attach order (reference PartitionHub.sink). Defaults
        to waiting for one consumer (an index partitioner is meaningless
        against zero consumers); if every consumer later detaches,
        elements are dropped until one re-attaches."""
        def factory():
            def route(info: ConsumerInfo, elem):
                if info.size == 0:
                    return -1  # no consumers: unknown id -> drop
                idx = partitioner(info.size, elem)
                if not 0 <= idx < info.size:
                    # out of range is a user bug either way: fail loudly
                    # rather than letting Python's negative indexing
                    # silently misroute to the last-attached consumer
                    raise IndexError(
                        f"PartitionHub partitioner returned index {idx} "
                        f"outside [0, {info.size})")
                return info.consumer_id_by_idx(idx)
            return route
        return PartitionHub.stateful_sink(
            factory, start_after_nr_of_consumers, buffer_size)
