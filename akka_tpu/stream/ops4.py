"""Operator library, fourth tranche: the long tail VERDICT r3 #5 named —
statefulMap/mapWithResource, mapAsyncPartitioned, weighted grouping/batching,
timer ops (initialDelay, backpressureTimeout, delayWith), monitor/foldWhile/
mergeLatest/watch, async sources (maybe, unfoldAsync, unfoldResourceAsync,
zipN, actorRefWithBackpressure), lazy/future/cancelled sinks, switchMap.

Reference parity: scaladsl/Flow.scala (statefulMap, mapWithResource,
mapAsyncPartitioned, groupedWeighted, groupedWeightedWithin, batchWeighted,
initialDelay, backpressureTimeout, delayWith, monitor, foldWhile,
mergeLatest/mergeLatestWith, watch, switchMap/flatMapLatest),
scaladsl/Source.scala (maybe, unfoldAsync, unfoldResourceAsync, zipN,
zipWithN, actorRefWithBackpressure), scaladsl/Sink.scala (lazySink,
futureSink, cancelled, foreachAsync); impl/fusing/StatefulMap.scala,
MapAsyncPartitioned.scala, impl/Timers.scala, FlowMonitorImpl.scala.
"""

from __future__ import annotations

import collections
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional

from .ops import _LinearStage, _SinkStage, _SourceStage, _QUEUE_END, \
    make_in_handler, make_out_handler
from .ops2 import _TimerLogic
from .stage import (FanInShape, GraphStage, GraphStageLogic, Inlet, Outlet,
                    SourceShape, make_in_handler as _mk_in)


# =========================== stateful element ops ===========================

class StatefulMap(_LinearStage):
    """scaladsl statefulMap(create)(f, onComplete): per-materialization
    state threaded through f(state, elem) -> (state, out); onComplete(state)
    may emit one final element (impl/fusing/StatefulMap.scala)."""

    def __init__(self, create: Callable[[], Any],
                 fn: Callable[[Any, Any], tuple],
                 on_complete: Optional[Callable[[Any], Optional[Any]]] = None):
        super().__init__("StatefulMap")
        self.create = create
        self.fn = fn
        self.on_complete = on_complete

    def create_logic(self):
        stage = self
        logic, in_, out = self._logic(), self.in_, self.out
        state = {"s": None, "init": False}

        def _ensure():
            if not state["init"]:
                state["s"] = stage.create()
                state["init"] = True

        logic.restart_state = lambda: state.update(init=False, s=None)

        def on_push():
            _ensure()
            state["s"], emitted = stage.fn(state["s"], logic.grab(in_))
            logic.push(out, emitted)

        def on_finish():
            if stage.on_complete is not None:
                _ensure()
                final = stage.on_complete(state["s"])
                if final is not None:
                    logic.emit(out, final)
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class MapWithResource(_LinearStage):
    """scaladsl mapWithResource(create)(f, close): a resource opened per
    materialization, used by f(resource, elem), closed on EVERY termination
    path; close may emit one final element."""

    def __init__(self, create: Callable[[], Any],
                 fn: Callable[[Any, Any], Any],
                 close: Callable[[Any], Optional[Any]]):
        super().__init__("MapWithResource")
        self.create = create
        self.fn = fn
        self.close = close

    def create_logic(self):
        stage = self
        in_, out = self.in_, self.out
        state = {"resource": None, "open": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                state["resource"] = stage.create()
                state["open"] = True

            def post_stop(self):
                if state["open"]:
                    state["open"] = False
                    stage.close(state["resource"])

        logic = _L(self._shape)

        def _reopen():
            if state["open"]:
                stage.close(state["resource"])
            state["resource"] = stage.create()
            state["open"] = True
        logic.restart_state = _reopen

        def on_push():
            logic.push(out, stage.fn(state["resource"], logic.grab(in_)))

        def on_finish():
            if state["open"]:
                state["open"] = False
                final = stage.close(state["resource"])
                if final is not None:
                    logic.emit(out, final)
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class MapAsyncPartitioned(_LinearStage):
    """scaladsl mapAsyncPartitioned(parallelism)(partitioner)(f): total
    concurrency `parallelism`, at most ONE future in flight per partition,
    results emitted in INPUT order (impl/fusing/MapAsyncPartitioned.scala)."""

    def __init__(self, parallelism: int, partitioner: Callable[[Any], Any],
                 fn: Callable[[Any, Any], Any]):
        super().__init__("MapAsyncPartitioned")
        self.parallelism = max(int(parallelism), 1)
        self.partitioner = partitioner
        self.fn = fn

    def create_logic(self):
        stage = self
        in_, out = self.in_, self.out
        # entries in input order: [elem, partition, started, done, result/ex]
        entries: collections.deque = collections.deque()
        state = {"in_flight": 0, "finishing": False}
        busy_partitions: set = set()

        class _L(GraphStageLogic):
            def _start_ready(self):
                # synchronous results are collected and applied AFTER the
                # scan: _on_done mutates `entries` (popleft on emit), which
                # must not happen while iterating it
                sync_done = []
                for e in entries:
                    if state["in_flight"] >= stage.parallelism:
                        break
                    if e["started"] or e["partition"] in busy_partitions:
                        continue
                    e["started"] = True
                    busy_partitions.add(e["partition"])
                    state["in_flight"] += 1
                    cb = self.get_async_callback(self._on_done)
                    try:
                        fut = stage.fn(e["elem"], e["partition"])
                    except Exception as ex:  # noqa: BLE001
                        sync_done.append((e, ex, None))
                        continue
                    if isinstance(fut, Future):
                        fut.add_done_callback(
                            lambda f, entry=e: cb.invoke(
                                (entry, f.exception(),
                                 None if f.exception() else f.result())))
                    else:
                        sync_done.append((e, None, fut))
                for triple in sync_done:
                    self._on_done(triple)

            def _on_done(self, triple):
                e, ex, val = triple
                state["in_flight"] -= 1
                busy_partitions.discard(e["partition"])
                if ex is not None:
                    self.fail_stage(ex)
                    return
                e["done"], e["result"] = True, val
                self._emit_ready()
                self._start_ready()
                self._maybe_pull()

            def _emit_ready(self):
                while entries and entries[0]["done"] and \
                        self.is_available(out):
                    self.push(out, entries.popleft()["result"])
                if state["finishing"] and not entries:
                    self.complete_stage()

            def _maybe_pull(self):
                if len(entries) < stage.parallelism and \
                        not state["finishing"] and \
                        not self.has_been_pulled(in_) and \
                        not self.is_closed(in_):
                    self.pull(in_)

        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            entries.append({"elem": elem,
                            "partition": stage.partitioner(elem),
                            "started": False, "done": False, "result": None})
            logic._start_ready()
            logic._maybe_pull()

        def on_finish():
            state["finishing"] = True
            if not entries:
                logic.complete_stage()

        def on_pull():
            logic._emit_ready()
            logic._maybe_pull()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


# ============================ weighted grouping =============================

class GroupedWeighted(_LinearStage):
    """scaladsl groupedWeighted(minWeight)(cost): emit a group once its
    accumulated cost reaches minWeight."""

    def __init__(self, min_weight: float, cost: Callable[[Any], float]):
        super().__init__("GroupedWeighted")
        self.min_weight = min_weight
        self.cost = cost

    def create_logic(self):
        stage = self
        logic, in_, out = self._logic(), self.in_, self.out
        buf: List[Any] = []
        state = {"w": 0.0}

        def on_push():
            elem = logic.grab(in_)
            buf.append(elem)
            state["w"] += stage.cost(elem)
            if state["w"] >= stage.min_weight:
                group, buf[:] = list(buf), []
                state["w"] = 0.0
                logic.push(out, group)
            else:
                logic.pull(in_)

        def on_finish():
            if buf:
                logic.emit(out, list(buf))
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class GroupedWeightedWithin(_LinearStage):
    """scaladsl groupedWeightedWithin(maxWeight, d)(cost): group until the
    weight cap or the time window, whichever first."""

    def __init__(self, max_weight: float, seconds: float,
                 cost: Callable[[Any], float], max_number: int = 0):
        super().__init__("GroupedWeightedWithin")
        self.max_weight = max_weight
        self.seconds = seconds
        self.cost = cost
        self.max_number = max_number  # 0 = unbounded

    def create_logic(self):
        stage = self
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        buf: List[Any] = []
        pending: List[List[Any]] = []
        state = {"w": 0.0}

        def flush():
            if buf:
                pending.append(list(buf))
                buf.clear()
                state["w"] = 0.0

        def deliver():
            if pending and logic.is_available(out):
                logic.push(out, pending.pop(0))

        logic._on_timer_fn = lambda key: (flush(), deliver())

        def pre_start():
            logic.schedule_periodically("window", stage.seconds, stage.seconds)
            logic.pull(in_)
        logic.pre_start = pre_start  # type: ignore[method-assign]

        def on_push():
            elem = logic.grab(in_)
            buf.append(elem)
            state["w"] += stage.cost(elem)
            if state["w"] >= stage.max_weight or \
                    (stage.max_number and len(buf) >= stage.max_number):
                flush()
            deliver()
            if len(pending) < 2 and not logic.is_closed(in_) and \
                    not logic.has_been_pulled(in_):
                logic.pull(in_)

        def on_finish():
            flush()
            for group in pending:
                logic.emit(out, group)
            pending.clear()
            logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(
            lambda: (deliver(),
                     logic.pull(in_)
                     if not logic.has_been_pulled(in_)
                     and not logic.is_closed(in_) and len(pending) < 2
                     else None)))
        return logic


class BatchWeighted(_LinearStage):
    """scaladsl batchWeighted(max, cost, seed)(aggregate): conflate-like
    batching that backpressures once the batch weight reaches max."""

    def __init__(self, max_weight: float, cost: Callable[[Any], float],
                 seed: Callable[[Any], Any],
                 aggregate: Callable[[Any, Any], Any]):
        super().__init__("BatchWeighted")
        self.max_weight = max_weight
        self.cost = cost
        self.seed = seed
        self.aggregate = aggregate

    def create_logic(self):
        stage = self
        logic, in_, out = self._logic(), self.in_, self.out
        state = {"agg": None, "has": False, "w": 0.0, "finishing": False}

        def on_push():
            elem = logic.grab(in_)
            if not state["has"]:
                state["agg"], state["has"] = stage.seed(elem), True
                state["w"] = stage.cost(elem)
            else:
                state["agg"] = stage.aggregate(state["agg"], elem)
                state["w"] += stage.cost(elem)
            if logic.is_available(out):
                logic.push(out, state["agg"])
                state["has"], state["agg"], state["w"] = False, None, 0.0
            if state["w"] < stage.max_weight and not logic.is_closed(in_) \
                    and not logic.has_been_pulled(in_):
                logic.pull(in_)

        def on_finish():
            if state["has"]:
                logic.emit(out, state["agg"])
            logic.complete_stage()

        def on_pull():
            if state["has"]:
                logic.push(out, state["agg"])
                state["has"], state["agg"], state["w"] = False, None, 0.0
            if not logic.is_closed(in_) and not logic.has_been_pulled(in_):
                logic.pull(in_)
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


# ================================ timer ops =================================

class InitialDelay(_LinearStage):
    """scaladsl initialDelay(d): hold the FIRST element for d seconds."""

    def __init__(self, seconds: float):
        super().__init__("InitialDelay")
        self.seconds = seconds

    def create_logic(self):
        stage = self
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        state = {"open": False, "held": None, "finishing": False}

        def on_timer(key):
            state["open"] = True
            if state["held"] is not None:
                (elem,) = state["held"]
                state["held"] = None
                logic.push(out, elem)
                if state["finishing"]:
                    logic.complete_stage()
        logic._on_timer_fn = on_timer

        def pre_start():
            logic.schedule_once("gate", stage.seconds)
        logic.pre_start = pre_start  # type: ignore[method-assign]

        def on_push():
            elem = logic.grab(in_)
            if state["open"]:
                logic.push(out, elem)
            else:
                state["held"] = (elem,)

        def on_finish():
            if state["held"] is not None:
                state["finishing"] = True
            else:
                logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(
            lambda: logic.pull(in_) if not logic.has_been_pulled(in_)
            and not logic.is_closed(in_) else None))
        return logic


class BackpressureTimeoutException(TimeoutError):
    pass


class BackpressureTimeout(_LinearStage):
    """scaladsl backpressureTimeout(d): fail if downstream leaves a pushed
    element un-consumed (no fresh pull) for longer than d."""

    def __init__(self, seconds: float):
        super().__init__("BackpressureTimeout")
        self.seconds = seconds

    def create_logic(self):
        stage = self
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        state = {"waiting": False}

        def on_timer(key):
            if state["waiting"]:
                logic.fail_stage(BackpressureTimeoutException(
                    f"no downstream demand for {stage.seconds}s"))
        logic._on_timer_fn = on_timer

        def on_push():
            logic.push(out, logic.grab(in_))
            state["waiting"] = True
            logic.schedule_once("bp", stage.seconds)

        def on_pull():
            state["waiting"] = False
            logic.cancel_timer("bp")
            if not logic.has_been_pulled(in_) and not logic.is_closed(in_):
                logic.pull(in_)
        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class DelayWith(_LinearStage):
    """scaladsl delayWith(strategyFactory): per-element delay from a
    DelayStrategy — here a per-materialization factory returning
    fn(elem) -> seconds (reference DelayStrategy.linearIncreasingDelay
    etc. are plain closures over this shape)."""

    def __init__(self, strategy_factory: Callable[[], Callable[[Any], float]],
                 buffer_size: int = 16):
        super().__init__("DelayWith")
        self.strategy_factory = strategy_factory
        self.buffer_size = buffer_size

    def create_logic(self):
        import time as _time
        stage = self
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        strategy = {"fn": None}
        buf: collections.deque = collections.deque()  # (ready_time, elem)
        state = {"finishing": False, "armed": False}

        def _arm():
            # arm only while the head is NOT yet due: a due-but-unpushable
            # head (downstream hasn't pulled) must wait for on_pull, not
            # spin a zero-delay timer loop
            if buf and not state["armed"]:
                delay = buf[0][0] - _time.monotonic()
                if delay > 0:
                    state["armed"] = True
                    logic.schedule_once("ready", delay)

        def _deliver():
            now = _time.monotonic()
            if buf and buf[0][0] <= now and logic.is_available(out):
                logic.push(out, buf.popleft()[1])
            if state["finishing"] and not buf:
                logic.complete_stage()
                return
            _arm()
            if len(buf) < stage.buffer_size and not logic.is_closed(in_) \
                    and not logic.has_been_pulled(in_):
                logic.pull(in_)

        def on_timer(key):
            state["armed"] = False
            _deliver()
        logic._on_timer_fn = on_timer

        def pre_start():
            strategy["fn"] = stage.strategy_factory()
            logic.pull(in_)
        logic.pre_start = pre_start  # type: ignore[method-assign]

        def on_push():
            elem = logic.grab(in_)
            buf.append((_time.monotonic() + strategy["fn"](elem), elem))
            _deliver()

        def on_finish():
            if buf:
                state["finishing"] = True
            else:
                logic.complete_stage()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(_deliver))
        return logic


# ========================= monitor / foldWhile / watch ======================

class FlowMonitor:
    """Mat value of .monitor(): the stream's last state
    (reference: akka.stream.FlowMonitor / FlowMonitorState)."""

    def __init__(self):
        self._state = ("initialized",)
        self._lock = threading.Lock()

    def _set(self, *state):
        with self._lock:
            self._state = state

    @property
    def state(self):
        """("initialized",) | ("received", elem) | ("failed", ex) |
        ("finished",)"""
        with self._lock:
            return self._state


class MonitorStage(_LinearStage):
    def __init__(self):
        super().__init__("Monitor")

    def create_logic_and_mat(self):
        mon = FlowMonitor()
        logic, in_, out = self._logic(), self.in_, self.out

        def on_push():
            elem = logic.grab(in_)
            mon._set("received", elem)
            logic.push(out, elem)

        def on_finish():
            mon._set("finished")
            logic.complete_stage()

        def on_failure(ex):
            mon._set("failed", ex)
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic, mon


class FoldWhile(_LinearStage):
    """scaladsl foldWhile(zero)(pred)(f): fold while pred(acc) holds; emit
    the aggregate (and complete, cancelling upstream) once it does not."""

    def __init__(self, zero, pred: Callable[[Any], bool],
                 fn: Callable[[Any, Any], Any]):
        super().__init__("FoldWhile")
        self.zero = zero
        self.pred = pred
        self.fn = fn

    def create_logic(self):
        stage = self
        logic, in_, out = self._logic(), self.in_, self.out
        state = {"acc": self.zero, "done": False}
        logic.restart_state = lambda: state.update(acc=stage.zero, done=False)

        def _finish():
            state["done"] = True
            logic.emit(out, state["acc"])
            logic.complete_stage()

        def on_push():
            state["acc"] = stage.fn(state["acc"], logic.grab(in_))
            if not stage.pred(state["acc"]):
                _finish()
            else:
                logic.pull(in_)

        def on_finish():
            if not state["done"]:
                _finish()
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(
            lambda: logic.pull(in_) if not logic.has_been_pulled(in_)
            and not logic.is_closed(in_) else None))
        return logic


class WatchedActorTerminatedException(RuntimeError):
    pass


class WatchStage(_LinearStage):
    """scaladsl watch(ref): pass elements through; fail the stream with
    WatchedActorTerminatedException when the watched actor terminates."""

    def __init__(self, ref):
        super().__init__("Watch")
        self.ref = ref

    def create_logic(self):
        from ..actor.actor import Actor
        from ..actor.messages import Terminated
        from ..actor.props import Props
        stage = self
        in_, out = self.in_, self.out
        state = {"watcher": None}

        class _Watcher(Actor):
            def __init__(self, target, cb):
                super().__init__()
                self._target = target
                self._cb = cb

            def pre_start(self):
                self.context.watch(self._target)

            def receive(self, message):
                if isinstance(message, Terminated):
                    self._cb.invoke(message)
                    self.context.stop(self.self_ref)

        class _L(GraphStageLogic):
            def pre_start(self):
                cb = self.get_async_callback(self._on_terminated)
                state["watcher"] = self.materializer.system.actor_of(
                    Props.create(_Watcher, stage.ref, cb))

            def _on_terminated(self, _t):
                self.fail_stage(WatchedActorTerminatedException(
                    f"watched actor {stage.ref} terminated"))

            def post_stop(self):
                w = state["watcher"]
                if w is not None:
                    self.materializer.system.stop(w)

        logic = _L(self._shape)
        logic.set_handler(in_, make_in_handler(
            lambda: logic.push(out, logic.grab(in_))))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


# ============================== async sources ===============================

class MaybePromise:
    """Mat value of Source.maybe: complete with an element, or None to
    complete empty, or fail (reference: Promise[Option[T]])."""

    def __init__(self):
        self._cb = None
        self._lock = threading.Lock()
        self._early = None  # ("ok", v) | ("fail", ex)

    def _bind(self, cb):
        with self._lock:
            self._cb = cb
            early = self._early
        if early is not None:
            cb.invoke(early)

    def _send(self, item):
        with self._lock:
            if self._early is not None:
                return  # already completed
            if self._cb is None:
                self._early = item
                return
            self._early = item
        self._cb.invoke(item)

    def success(self, value: Optional[Any]) -> None:
        self._send(("ok", value))

    def failure(self, ex: BaseException) -> None:
        self._send(("fail", ex))


class MaybeSource(_SourceStage):
    def __init__(self):
        super().__init__("MaybeSource")

    def create_logic_and_mat(self):
        stage = self
        promise = MaybePromise()
        state = {"value": None, "done": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.set_keep_going(True)  # stay alive while unfulfilled
                promise._bind(self.get_async_callback(self._on_value))

            def _on_value(self, item):
                kind, v = item
                state["done"] = True
                self.set_keep_going(False)
                if kind == "fail":
                    self.fail(stage.out, v)
                elif v is None:
                    self.complete(stage.out)
                else:
                    state["value"] = v
                    if self.is_available(stage.out):
                        self.push(stage.out, v)
                        self.complete(stage.out)

        logic = _L(self._shape)

        def on_pull():
            if state["done"] and state["value"] is not None:
                logic.push(stage.out, state["value"])
                logic.complete(stage.out)

        def on_cancel(cause=None):
            # downstream gave up before fulfilment: drop keep-going or the
            # island actor never shuts down (leaks one actor per run)
            state["done"] = True
            logic.set_keep_going(False)
            logic.cancel_stage(cause)
        logic.set_handler(stage.out, make_out_handler(on_pull, on_cancel))
        return logic, promise


class UnfoldAsync(_SourceStage):
    """scaladsl unfoldAsync: fn(state) -> Future[None | (state, elem)]."""

    def __init__(self, zero, fn):
        super().__init__("UnfoldAsync")
        self.zero = zero
        self.fn = fn

    def create_logic(self):
        stage = self
        out = self.out
        state = {"s": self.zero, "busy": False}

        class _L(GraphStageLogic):
            def _step(self):
                state["busy"] = True
                cb = self.get_async_callback(self._on_done)
                try:
                    fut = stage.fn(state["s"])
                except Exception as e:  # noqa: BLE001
                    self.fail(out, e)
                    return
                if isinstance(fut, Future):
                    fut.add_done_callback(
                        lambda f: cb.invoke((f.exception(),
                                             None if f.exception()
                                             else f.result())))
                else:
                    self._on_done((None, fut))

            def _on_done(self, pair):
                ex, nxt = pair
                state["busy"] = False
                if ex is not None:
                    self.fail(out, ex)
                elif nxt is None:
                    self.complete(out)
                else:
                    state["s"], elem = nxt
                    self.push(out, elem)

        logic = _L(self._shape)
        logic.set_handler(out, make_out_handler(
            lambda: logic._step() if not state["busy"] else None))
        return logic


class UnfoldResourceAsync(_SourceStage):
    """scaladsl unfoldResourceAsync: create/read/close all return Futures
    (read resolves to None at the end)."""

    def __init__(self, create, read, close):
        super().__init__("UnfoldResourceAsync")
        self.create = create
        self.read = read
        self.close = close

    def create_logic(self):
        stage = self
        out = self.out
        state = {"resource": None, "open": False, "busy": False,
                 "pending_read": False}

        def _as_future(v):
            if isinstance(v, Future):
                return v
            f = Future()
            f.set_result(v)
            return f

        class _L(GraphStageLogic):
            def pre_start(self):
                state["busy"] = True
                cb = self.get_async_callback(self._on_created)
                _as_future(stage.create()).add_done_callback(
                    lambda f: cb.invoke((f.exception(),
                                         None if f.exception()
                                         else f.result())))

            def _on_created(self, pair):
                ex, res = pair
                state["busy"] = False
                if ex is not None:
                    self.fail(out, ex)
                    return
                state["resource"], state["open"] = res, True
                if state["pending_read"]:
                    state["pending_read"] = False
                    self._read()

            def _read(self):
                state["busy"] = True
                cb = self.get_async_callback(self._on_read)
                try:
                    fut = _as_future(stage.read(state["resource"]))
                except Exception as e:  # noqa: BLE001
                    self.fail(out, e)
                    return
                fut.add_done_callback(
                    lambda f: cb.invoke((f.exception(),
                                         None if f.exception()
                                         else f.result())))

            def _on_read(self, pair):
                ex, v = pair
                state["busy"] = False
                if ex is not None:
                    self.fail(out, ex)
                elif v is None:
                    self.complete(out)
                else:
                    self.push(out, v)

            def post_stop(self):
                if state["open"]:
                    state["open"] = False
                    stage.close(state["resource"])

        logic = _L(self._shape)

        def on_pull():
            if not state["open"]:
                # create() still in flight: remember the demand
                state["pending_read"] = True
            elif not state["busy"]:
                logic._read()
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class ZipNStage(GraphStage):
    """scaladsl Source.zipN / zipWithN: n inputs -> fn(list of heads)."""

    def __init__(self, n: int, fn: Optional[Callable[[List[Any]], Any]] = None):
        self.name = "ZipN"
        self.fn = fn or (lambda xs: list(xs))
        self.ins = [Inlet(f"ZipN.in{i}") for i in range(n)]
        self.out = Outlet("ZipN.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        stage = self
        ins, out = self.ins, self.out
        heads = {i: None for i in range(len(ins))}
        logic = GraphStageLogic(self._shape)

        def _emit_if_ready():
            if not logic.is_available(out):
                return
            if any(h is None for h in heads.values()):
                for i, inlet in enumerate(ins):
                    if heads[i] is None:
                        if logic.is_closed(inlet):
                            logic.complete_stage()
                            return
                        if not logic.has_been_pulled(inlet):
                            logic.pull(inlet)
                return
            vals = [heads[i][0] for i in range(len(ins))]
            for i in range(len(ins)):
                heads[i] = None
            logic.push(out, stage.fn(vals))

        def mk_push(i, inlet):
            def on_push():
                heads[i] = (logic.grab(inlet),)
                _emit_if_ready()
            return on_push

        def mk_finish(i):
            def on_finish():
                if heads[i] is None:
                    logic.complete_stage()  # can never zip again
            return on_finish

        for i, inlet in enumerate(ins):
            logic.set_handler(inlet, _mk_in(mk_push(i, inlet), mk_finish(i)))
        logic.set_handler(out, make_out_handler(_emit_if_ready))
        return logic


class MergeLatestStage(GraphStage):
    """scaladsl mergeLatest: once every input has emitted, emit the list of
    latest values each time ANY input emits."""

    def __init__(self, n: int, fn: Optional[Callable[[List[Any]], Any]] = None):
        self.name = "MergeLatest"
        self.fn = fn or (lambda xs: list(xs))
        self.ins = [Inlet(f"MergeLatest.in{i}") for i in range(n)]
        self.out = Outlet("MergeLatest.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        stage = self
        ins, out = self.ins, self.out
        latest = {i: None for i in range(len(ins))}
        pending: collections.deque = collections.deque()
        logic = GraphStageLogic(self._shape)

        def _repull():
            # backpressure: hold inlets once a couple of combined rows are
            # queued; resume pulling as downstream drains (the reference
            # MergeLatest backpressures its inlets)
            if len(pending) < 2:
                for inlet in ins:
                    if not logic.is_closed(inlet) and \
                            not logic.has_been_pulled(inlet) and \
                            not logic.is_available(inlet):
                        logic.pull(inlet)

        def _deliver():
            if pending and logic.is_available(out):
                logic.push(out, pending.popleft())
            if not pending and all(logic.is_closed(i) for i in ins):
                logic.complete_stage()
                return
            _repull()

        def mk_push(i, inlet):
            def on_push():
                latest[i] = (logic.grab(inlet),)
                if all(v is not None for v in latest.values()):
                    pending.append(stage.fn(
                        [latest[j][0] for j in range(len(ins))]))
                _deliver()
            return on_push

        def on_finish():
            if all(logic.is_closed(i) for i in ins) and not pending:
                logic.complete_stage()

        for i, inlet in enumerate(ins):
            logic.set_handler(inlet, _mk_in(mk_push(i, inlet), on_finish))

        def pre_start():
            for inlet in ins:
                logic.pull(inlet)
        logic.pre_start = pre_start  # type: ignore[method-assign]
        logic.set_handler(out, make_out_handler(_deliver))
        return logic


class ActorRefBackpressureSource(_SourceStage):
    """scaladsl Source.actorRefWithBackpressure(ack): the mat ActorRef
    replies `ack` to the SENDER once each element is accepted into the
    stream, so producers can send-one-await-ack."""

    def __init__(self, ack_message: Any):
        super().__init__("ActorRefBackpressureSource")
        self.ack_message = ack_message

    def create_logic_and_mat(self):
        from ..actor.actor import Actor
        from ..actor.messages import Status
        from ..actor.props import Props
        stage = self
        state = {"ref": None, "completing": False}
        held: collections.deque = collections.deque()  # (msg, sender) FIFO
        mat_holder = {}

        class _Fwd(Actor):
            def __init__(self, cb):
                super().__init__()
                self._cb = cb

            def receive(self, message):
                self._cb.invoke((message, self.context.sender))

        class _L(GraphStageLogic):
            def pre_start(self):
                cb = self.get_async_callback(self._on_msg)
                state["ref"] = self.materializer.system.actor_of(
                    Props.create(_Fwd, cb))
                mat_holder["ref"].set_result(state["ref"])

            def _on_msg(self, pair):
                msg, sender = pair
                if isinstance(msg, Status.Success):
                    state["completing"] = True
                    if not held:
                        self.complete(stage.out)
                    return
                if isinstance(msg, Status.Failure):
                    self.fail_stage(msg.cause if isinstance(
                        msg.cause, BaseException) else
                        RuntimeError(str(msg.cause)))
                    return
                if self.is_available(stage.out) and not held:
                    self.push(stage.out, msg)
                    self._ack(sender)
                else:
                    # queue every unacked message (one per waiting sender —
                    # each well-behaved producer awaits its ack; a single
                    # slot here would silently drop a concurrent sender's
                    # element and deadlock it)
                    held.append((msg, sender))

            def _ack(self, sender):
                if sender is not None:
                    sender.tell(stage.ack_message, state["ref"])

            def _drain(self):
                if held and self.is_available(stage.out):
                    msg, sender = held.popleft()
                    self.push(stage.out, msg)
                    self._ack(sender)
                    if state["completing"] and not held:
                        self.complete(stage.out)

            def post_stop(self):
                # the forwarder outlives no materialization (WatchStage
                # stops its helper the same way); without this every run
                # leaked one live actor
                if state["ref"] is not None:
                    self.materializer.system.stop(state["ref"])

        logic = _L(self._shape)
        fut: Future = Future()
        mat_holder["ref"] = fut
        logic.set_handler(stage.out, make_out_handler(logic._drain))
        return logic, fut


# ================================= sinks ====================================

class ActorRefBackpressureSink(_SinkStage):
    """scaladsl Sink.actorRefWithBackpressure: `on_init` then each element
    goes to `ref` with an ack-forwarder as sender; the next element is
    pulled only after `ack_message` comes back, so the target actor paces
    the stream. `on_complete`/`on_failure(ex)` close the conversation."""

    def __init__(self, ref: Any, on_init: Any, ack_message: Any,
                 on_complete: Any, on_failure=None):
        super().__init__("ActorRefBackpressureSink")
        self.ref = ref
        self.on_init = on_init
        self.ack_message = ack_message
        self.on_complete = on_complete
        self.on_failure = on_failure

    def create_logic(self):
        from ..actor.actor import Actor
        from ..actor.props import Props
        stage = self
        in_ = self.in_
        st = {"fwd": None, "awaiting": 0, "finishing": False}

        class _Fwd(Actor):
            def __init__(self, cb):
                super().__init__()
                self._cb = cb

            def receive(self, message):
                self._cb.invoke(message)

        class _L(GraphStageLogic):
            def pre_start(self):
                cb = self.get_async_callback(self._on_reply)
                st["fwd"] = self.materializer.system.actor_of(
                    Props.create(_Fwd, cb))
                st["awaiting"] = 1  # the on_init ack gates the first pull
                stage.ref.tell(stage.on_init, st["fwd"])

            def _on_reply(self, msg):
                if msg != stage.ack_message:
                    return  # unrelated chatter to the forwarder
                st["awaiting"] -= 1
                if st["awaiting"] > 0:
                    return
                if st["finishing"]:
                    self._close()
                elif not self.has_been_pulled(in_) and \
                        not self.is_closed(in_):
                    self.pull(in_)

            def _close(self):
                stage.ref.tell(stage.on_complete, st["fwd"])
                self.set_keep_going(False)
                self.complete_stage()

            def post_stop(self):
                if st["fwd"] is not None:
                    self.materializer.system.stop(st["fwd"])

        logic = _L(self._shape)

        def on_push():
            st["awaiting"] += 1
            stage.ref.tell(logic.grab(in_), st["fwd"])

        def on_finish():
            # on_complete only after every sent element was acked
            # (reference: the sink completes when the actor has consumed
            # the whole stream, not merely received it)
            if st["awaiting"] > 0:
                st["finishing"] = True
                logic.set_keep_going(True)  # outlive the closed inlet
            else:
                logic._close()

        def on_failure(ex):
            if stage.on_failure is not None:
                stage.ref.tell(stage.on_failure(ex), st["fwd"])
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic

class CancelledSink(_SinkStage):
    """scaladsl Sink.cancelled: cancel upstream immediately."""

    def __init__(self):
        super().__init__("CancelledSink")

    def create_logic(self):
        in_ = self.in_

        class _L(GraphStageLogic):
            def pre_start(self):
                self.cancel(in_)
        logic = _L(self._shape)
        logic.set_handler(in_, make_in_handler(lambda: None))
        return logic


class NeverMaterializedException(RuntimeError):
    """The lazy/future sink's inner sink was never materialized
    (reference: akka.stream.NeverMaterializedException)."""


class LazySink(_SinkStage):
    """scaladsl Sink.lazySink: defer building+materializing the real sink
    until the first element arrives (sub-materialized through the restart
    bridge machinery; the first element is delivered to the inner sink).
    Mat: Future resolving to the INNER sink's mat value once it
    materializes; fails with NeverMaterializedException if it never does."""

    def __init__(self, factory: Callable[[], Any], trigger: Optional[Future] = None):
        super().__init__("LazySink" if trigger is None else "FutureSink")
        self.factory = factory
        self.trigger = trigger  # None = first element; Future = when done

    def create_logic_and_mat(self):
        from .restart import _BridgeHandle, _BridgeSource
        stage = self
        in_ = self.in_
        mat_fut: Future = Future()
        st = {"handle": None, "demand": 0, "stash": None,
              "finishing": False, "failed": None}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.set_keep_going(True)
                if stage.trigger is not None:
                    cb = self.get_async_callback(self._on_trigger)
                    stage.trigger.add_done_callback(lambda f: cb.invoke(f))
                else:
                    self.pull(in_)

            def _on_trigger(self, f):
                ex = f.exception()
                if ex is not None:
                    self.set_keep_going(False)
                    self.fail_stage(ex)
                    return
                self._start_inner()
                if not self.has_been_pulled(in_) and not self.is_closed(in_):
                    self.pull(in_)

            def _start_inner(self):
                from .dsl import Keep, Source
                handle = _BridgeHandle(
                    self.get_async_callback(self._on_inner), 1)
                st["handle"] = handle
                try:
                    inner_mat = Source.from_graph(
                        lambda: _BridgeSource(handle)).to_mat(
                        stage.factory(), Keep.right).run(self.materializer)
                except Exception as ex:  # noqa: BLE001
                    if not mat_fut.done():
                        mat_fut.set_exception(ex)
                    raise
                if not mat_fut.done():
                    mat_fut.set_result(inner_mat)

            def _on_inner(self, pair):
                _gen, ev = pair
                if ev[0] == "demand":
                    st["demand"] += 1
                    if st["stash"] is not None:
                        elem, st["stash"] = st["stash"], None
                        st["demand"] -= 1
                        st["handle"].to_inner(("elem", elem))
                        if st["finishing"]:
                            self._finish_inner()
                    elif st["finishing"]:
                        self._finish_inner()
                    elif not self.has_been_pulled(in_) and \
                            not self.is_closed(in_):
                        self.pull(in_)
                elif ev[0] == "cancel":
                    # inner sink cancelled: cancel the wrap
                    self.set_keep_going(False)
                    self.complete_stage()

            def _finish_inner(self):
                st["handle"].to_inner(("complete",))
                self.set_keep_going(False)
                self.complete_stage()

            def post_stop(self):
                if st["handle"] is not None and st["failed"] is None and \
                        not st["finishing"]:
                    st["handle"].to_inner(("complete",))
                if not mat_fut.done():
                    mat_fut.set_exception(NeverMaterializedException(
                        "inner sink was never materialized"))

        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            if st["handle"] is None and stage.trigger is None:
                st["stash"] = elem
                logic._start_inner()
            elif st["handle"] is not None and st["demand"] > 0:
                st["demand"] -= 1
                st["handle"].to_inner(("elem", elem))
            else:
                st["stash"] = elem
            if st["demand"] > 0 and not logic.is_closed(in_):
                logic.pull(in_)

        def on_finish():
            if st["handle"] is None:
                # no element ever arrived: the inner sink is never built
                logic.set_keep_going(False)
                logic.complete_stage()
            elif st["stash"] is None:
                logic._finish_inner()
            else:
                st["finishing"] = True

        def on_failure(ex):
            st["failed"] = ex
            if st["handle"] is not None:
                st["handle"].to_inner(("fail", ex))
            if not mat_fut.done():
                mat_fut.set_exception(ex)
            logic.set_keep_going(False)
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic, mat_fut


# ============================== switchMap ===================================

class SwitchMap(_LinearStage):
    """scaladsl switchMap (flatMapLatest): each element maps to a Source;
    a NEW element cancels the current inner source and switches to the new
    one (uses SinkQueue.cancel)."""

    def __init__(self, fn):
        super().__init__("SwitchMap")
        self.fn = fn

    def create_logic(self):
        stage = self
        in_, out = self.in_, self.out
        st = {"queue": None, "gen": 0, "pulling": False, "finishing": False}

        class _L(GraphStageLogic):
            def _switch_to(self, elem):
                from .dsl import Keep, Sink
                if st["queue"] is not None:
                    st["queue"].cancel()
                st["gen"] += 1
                st["pulling"] = False
                st["queue"] = stage.fn(elem).to_mat(
                    Sink.queue(), Keep.right).run(self.materializer)
                if self.is_available(out):
                    self._request()
                if not self.has_been_pulled(in_) and not self.is_closed(in_):
                    self.pull(in_)

            def _request(self):
                if st["pulling"] or st["queue"] is None:
                    return
                st["pulling"] = True
                gen = st["gen"]
                cb = self.get_async_callback(self._on_sub)
                st["queue"].pull().add_done_callback(
                    lambda f: cb.invoke((gen, f)))

            def _on_sub(self, pair):
                gen, f = pair
                if gen != st["gen"]:
                    return  # stale inner
                st["pulling"] = False
                ex = f.exception()
                if ex is not None:
                    self.fail_stage(ex)
                    return
                item = f.result()
                if item is _QUEUE_END:
                    st["queue"] = None
                    if st["finishing"]:
                        self.complete_stage()
                    elif not self.has_been_pulled(in_) and \
                            not self.is_closed(in_):
                        self.pull(in_)
                    return
                self.push(out, item)

            def post_stop(self):
                if st["queue"] is not None:
                    st["queue"].cancel()

        logic = _L(self._shape)

        def on_push():
            logic._switch_to(logic.grab(in_))

        def on_finish():
            if st["queue"] is None:
                logic.complete_stage()
            else:
                st["finishing"] = True

        def on_pull():
            if st["queue"] is not None:
                logic._request()
            elif not logic.has_been_pulled(in_) and not logic.is_closed(in_):
                logic.pull(in_)
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic
