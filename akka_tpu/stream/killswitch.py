"""Kill switches: external stream termination.

Reference parity: akka-stream/src/main/scala/akka/stream/KillSwitch.scala —
UniqueKillSwitch (one materialization, via KillSwitches.single) and
SharedKillSwitch (many materializations share one switch).
"""

from __future__ import annotations

import threading
from typing import List, Optional

from .stage import (FlowShape, GraphStage, GraphStageLogic, Inlet, Outlet,
                    make_in_handler, make_out_handler)


class UniqueKillSwitch:
    def __init__(self):
        self._cb = None
        self._lock = threading.Lock()
        self._pending = None  # buffered shutdown/abort before bind

    def _bind(self, cb) -> None:
        with self._lock:
            self._cb = cb
            pending = self._pending
        if pending is not None:
            cb.invoke(pending)

    def shutdown(self) -> None:
        with self._lock:
            if self._cb is None:
                self._pending = ("shutdown", None)
                return
        self._cb.invoke(("shutdown", None))

    def abort(self, ex: BaseException) -> None:
        with self._lock:
            if self._cb is None:
                self._pending = ("abort", ex)
                return
        self._cb.invoke(("abort", ex))


class SharedKillSwitch:
    def __init__(self, name: str = "shared"):
        self.name = name
        self._lock = threading.Lock()
        self._switches: List[UniqueKillSwitch] = []
        self._terminated = None  # ("shutdown", None) | ("abort", ex)

    def _register(self, switch: UniqueKillSwitch) -> None:
        with self._lock:
            if self._terminated is not None:
                kind, ex = self._terminated
            else:
                self._switches.append(switch)
                return
        if kind == "shutdown":
            switch.shutdown()
        else:
            switch.abort(ex)

    def shutdown(self) -> None:
        with self._lock:
            self._terminated = ("shutdown", None)
            switches = list(self._switches)
        for s in switches:
            s.shutdown()

    def abort(self, ex: BaseException) -> None:
        with self._lock:
            self._terminated = ("abort", ex)
            switches = list(self._switches)
        for s in switches:
            s.abort(ex)

    @property
    def flow(self) -> "object":
        """A Flow stage joining this shared switch (reference:
        SharedKillSwitch.flow)."""
        from .dsl import Flow
        shared = self

        def factory():
            stage = KillSwitchStage()
            shared._register(stage.switch)
            return stage
        return Flow.from_graph(factory)


class KillSwitchStage(GraphStage):
    """Pass-through until the switch fires (reference: KillSwitches.single)."""

    def __init__(self):
        self.name = "KillSwitch"
        self.in_ = Inlet("KillSwitch.in")
        self.out = Outlet("KillSwitch.out")
        self._shape = FlowShape(self.in_, self.out)
        self.switch = UniqueKillSwitch()

    @property
    def shape(self):
        return self._shape

    def create_logic_and_mat(self):
        in_, out, switch = self.in_, self.out, self.switch

        class _L(GraphStageLogic):
            def pre_start(self):
                switch._bind(self.get_async_callback(self._on_kill))

            def _on_kill(self, cmd):
                kind, ex = cmd
                if kind == "shutdown":
                    self.complete_stage()
                else:
                    self.fail_stage(ex)
        logic = _L(self._shape)
        logic.set_handler(in_, make_in_handler(
            lambda: logic.push(out, logic.grab(in_))))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic, switch


class KillSwitches:
    @staticmethod
    def single():
        """Flow materializing a UniqueKillSwitch (use with Keep.right)."""
        from .dsl import Flow
        return Flow.from_graph(KillSwitchStage)

    @staticmethod
    def shared(name: str = "shared") -> SharedKillSwitch:
        return SharedKillSwitch(name)
