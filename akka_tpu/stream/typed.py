"""Stream ↔ typed-actor interop.

Reference parity: akka-stream-typed/src/main/scala/akka/stream/typed/
scaladsl/ActorSource.scala & ActorSink.scala — ActorSource.actorRef (mat an
ActorRef fed into the stream, complete/fail match functions),
ActorSink.actorRef (elements as messages + onComplete message),
ActorSink.actorRefWithBackpressure (ack-based: the actor replies with an
ack message before the next element is sent).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..actor.ref import ActorRef
from .dsl import Sink, Source
from .stage import (GraphStage, GraphStageLogic, Inlet, SinkShape,
                    make_in_handler)


class ActorSource:
    @staticmethod
    def actor_ref(complete_matcher: Callable[[Any], bool],
                  failure_matcher: Callable[[Any], Optional[BaseException]],
                  buffer_size: int = 256) -> Source:
        """Messages to the mat ActorRef stream out; a message matching
        `complete_matcher` completes, `failure_matcher` returning an
        exception fails."""
        from ..actor.messages import Status

        base = Source.actor_ref(buffer_size)

        def adapt(b):
            outlet, lazy_ref = base._build(b)

            class _AdaptedRef:
                def tell(self, msg, sender=None):
                    ex = failure_matcher(msg)
                    if ex is not None:
                        lazy_ref.tell(Status.Failure(ex), sender)
                    elif complete_matcher(msg):
                        lazy_ref.tell(Status.Success(), sender)
                    else:
                        lazy_ref.tell(msg, sender)

                @property
                def ref(self):
                    return lazy_ref.ref
            return outlet, _AdaptedRef()
        return Source(adapt)


@dataclass(frozen=True)
class _AckReceived:
    pass


class _AckedActorSink(GraphStage):
    """Ack-based backpressure: wait for `ack_message` from the target before
    pulling the next element (reference: ActorSink.actorRefWithBackpressure)."""

    def __init__(self, ref: ActorRef, message_adapter, on_init_message,
                 ack_message, on_complete_message, on_failure_message):
        self.name = "AckedActorSink"
        self.ref = ref
        self.message_adapter = message_adapter
        self.on_init_message = on_init_message
        self.ack_message = ack_message
        self.on_complete_message = on_complete_message
        self.on_failure_message = on_failure_message
        self.in_ = Inlet("AckedActorSink.in")
        self._shape = SinkShape(self.in_)

    @property
    def shape(self):
        return self._shape

    def create_logic_and_mat(self):
        stage = self
        in_ = self.in_

        class _L(GraphStageLogic):
            def pre_start(self):
                from ..actor.props import Props
                system = self.materializer.system
                cb = self.get_async_callback(lambda _: self._on_ack())

                def receive(_ctx, msg):
                    if msg == stage.ack_message or stage.ack_message is None:
                        cb.invoke(None)
                self._ack_ref = system.actor_of(Props.from_receive(receive))
                if stage.on_init_message is not None:
                    stage.ref.tell(stage.on_init_message(self._ack_ref)
                                   if callable(stage.on_init_message)
                                   else stage.on_init_message, self._ack_ref)
                else:
                    self.pull(in_)

            def _on_ack(self):
                if not self.has_been_pulled(in_) and not self.is_closed(in_):
                    self.pull(in_)

            def post_stop(self):
                self.materializer.system.stop(self._ack_ref)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            msg = stage.message_adapter(logic._ack_ref, elem) \
                if stage.message_adapter else elem
            stage.ref.tell(msg, logic._ack_ref)
            # next pull happens on ack

        def on_finish():
            if stage.on_complete_message is not None:
                stage.ref.tell(stage.on_complete_message, None)
            logic.complete_stage()

        def on_failure(ex):
            if stage.on_failure_message is not None:
                stage.ref.tell(stage.on_failure_message(ex), None)
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic, None


class ActorSink:
    @staticmethod
    def actor_ref(ref: ActorRef, on_complete_message: Any,
                  on_failure_message: Optional[Callable] = None) -> Sink:
        return Sink.actor_ref(ref, on_complete_message, on_failure_message)

    @staticmethod
    def actor_ref_with_backpressure(
            ref: ActorRef, message_adapter: Callable[[ActorRef, Any], Any],
            on_init_message: Any, ack_message: Any,
            on_complete_message: Any,
            on_failure_message: Optional[Callable] = None) -> Sink:
        return Sink.from_graph(lambda: _AckedActorSink(
            ref, message_adapter, on_init_message, ack_message,
            on_complete_message, on_failure_message))
