"""Operator library, second tranche: timed windows, limits, timeouts,
dedup, recover-with, watch-termination.

Reference parity: scaladsl/Flow.scala (196 defs) — takeWithin/dropWithin/
groupedWithin (impl/fusing/Ops.scala timed stages), limit/limitWeighted,
initialTimeout/completionTimeout/idleTimeout (impl/Timers.scala),
keepAlive, recoverWithRetries, watchTermination, statefulMap-backed
deduplicate."""

from __future__ import annotations

import time as _time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional

from .ops import _LinearStage, make_in_handler, make_out_handler
from .stage import GraphStageLogic


class StreamLimitReachedException(RuntimeError):
    pass


class _TimerLogic(GraphStageLogic):
    """GraphStageLogic with a pluggable on_timer."""

    def __init__(self, shape, on_timer_fn=None):
        super().__init__(shape)
        self._on_timer_fn = on_timer_fn

    def on_timer(self, key):
        if self._on_timer_fn is not None:
            self._on_timer_fn(key)


class TakeWithin(_LinearStage):
    def __init__(self, seconds: float):
        super().__init__("TakeWithin")
        self.seconds = seconds

    def create_logic(self):
        stage = self
        logic = _TimerLogic(self._shape)
        logic._on_timer_fn = lambda key: logic.complete_stage()
        in_, out = self.in_, self.out

        def pre_start():
            logic.schedule_once("deadline", stage.seconds)
        logic.pre_start = pre_start  # type: ignore[method-assign]

        logic.set_handler(in_, make_in_handler(
            lambda: logic.push(out, logic.grab(in_))))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class DropWithin(_LinearStage):
    def __init__(self, seconds: float):
        super().__init__("DropWithin")
        self.seconds = seconds

    def create_logic(self):
        stage = self
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        state = {"open": False}
        logic._on_timer_fn = lambda key: state.update(open=True)

        def pre_start():
            logic.schedule_once("deadline", stage.seconds)
        logic.pre_start = pre_start  # type: ignore[method-assign]

        def on_push():
            elem = logic.grab(in_)
            if state["open"]:
                logic.push(out, elem)
            else:
                logic.pull(in_)

        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class GroupedWithin(_LinearStage):
    """Batch up to n elements or a time window, whichever fires first
    (groupedWithin)."""

    def __init__(self, n: int, seconds: float):
        super().__init__("GroupedWithin")
        self.n = n
        self.seconds = seconds

    def create_logic(self):
        stage = self
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        buf: List[Any] = []
        pending: List[List[Any]] = []

        def flush():
            if buf:
                pending.append(list(buf))
                buf.clear()

        def deliver():
            if pending and logic.is_available(out):
                logic.push(out, pending.pop(0))
                return True
            return False

        def on_timer(key):
            flush()
            deliver()

        logic._on_timer_fn = on_timer

        def pre_start():
            logic.schedule_periodically("window", stage.seconds,
                                        stage.seconds)
            logic.pull(in_)
        logic.pre_start = pre_start  # type: ignore[method-assign]

        def on_push():
            buf.append(logic.grab(in_))
            if len(buf) >= stage.n:
                flush()
            deliver()
            # backpressure: stop pulling while flushed groups back up (the
            # reference's groupedWithin holds demand until consumed)
            if len(pending) < 2 and not logic.is_closed(in_) and \
                    not logic.has_been_pulled(in_):
                logic.pull(in_)

        def on_finish():
            flush()
            for group in pending:
                logic.emit(out, group)
            pending.clear()
            logic.complete_stage()

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(
            lambda: deliver() or (not logic.has_been_pulled(in_)
                                  and not logic.is_closed(in_)
                                  and logic.pull(in_))))
        return logic


class Limit(_LinearStage):
    def __init__(self, max_elements: int, cost_fn: Optional[Callable] = None):
        super().__init__("Limit")
        self.max = max_elements
        self.cost_fn = cost_fn

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        stage = self
        seen = [0]

        def on_push():
            elem = logic.grab(in_)
            seen[0] += stage.cost_fn(elem) if stage.cost_fn else 1
            if seen[0] > stage.max:
                logic.fail_stage(StreamLimitReachedException(
                    f"limit of {stage.max} exceeded"))
                return
            logic.push(out, elem)

        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class _TimeoutBase(_LinearStage):
    kind = "initial"   # initial | completion | idle

    def __init__(self, seconds: float):
        super().__init__(f"{self.kind.capitalize()}Timeout")
        self.seconds = seconds

    def create_logic(self):
        stage = self
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        state = {"got_first": False}

        def on_timer(key):
            if stage.kind == "initial" and state["got_first"]:
                return
            logic.fail_stage(TimeoutError(
                f"{stage.kind} timeout after {stage.seconds}s"))

        logic._on_timer_fn = on_timer

        def pre_start():
            logic.schedule_once("t", stage.seconds)
        logic.pre_start = pre_start  # type: ignore[method-assign]

        def on_push():
            state["got_first"] = True
            if stage.kind == "idle":
                logic.schedule_once("t", stage.seconds)  # re-arm
            logic.push(out, logic.grab(in_))

        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class InitialTimeout(_TimeoutBase):
    kind = "initial"


class CompletionTimeout(_TimeoutBase):
    kind = "completion"


class IdleTimeout(_TimeoutBase):
    kind = "idle"


class KeepAlive(_LinearStage):
    """Inject a heartbeat element when no element flowed for `seconds`
    (keepAlive)."""

    def __init__(self, seconds: float, inject_fn: Callable[[], Any]):
        super().__init__("KeepAlive")
        self.seconds = seconds
        self.inject_fn = inject_fn

    def create_logic(self):
        stage = self
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        held: List[Any] = []  # upstream element that arrived demand-less
                              # because a heartbeat consumed the pull

        def on_timer(key):
            # inject only when demand exists AND no upstream element is in
            # flight toward that demand (we pulled but not yet received) —
            # otherwise the real element would arrive with no demand left
            if logic.is_available(out) and not held and \
                    not logic.has_been_pulled(in_):
                logic.push(out, stage.inject_fn())

        logic._on_timer_fn = on_timer

        def pre_start():
            logic.schedule_periodically("ka", stage.seconds, stage.seconds)
        logic.pre_start = pre_start  # type: ignore[method-assign]

        def on_push():
            logic.schedule_periodically("ka", stage.seconds, stage.seconds)
            elem = logic.grab(in_)
            if logic.is_available(out):
                logic.push(out, elem)
            else:
                held.append(elem)

        def on_pull():
            if held:
                logic.push(out, held.pop())
            elif not logic.is_closed(in_) and not logic.has_been_pulled(in_):
                logic.pull(in_)

        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class MapError(_LinearStage):
    def __init__(self, fn: Callable[[BaseException], BaseException]):
        super().__init__("MapError")
        self.fn = fn

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        stage = self

        def on_failure(ex):
            try:
                mapped = stage.fn(ex)
            except Exception as e:  # noqa: BLE001
                mapped = e
            logic.fail_stage(mapped)

        logic.set_handler(in_, make_in_handler(
            lambda: logic.push(out, logic.grab(in_)), None, on_failure))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class Deduplicate(_LinearStage):
    """Drop consecutive repeats (the statefulMap-based dedup pattern)."""

    def __init__(self, key_fn: Optional[Callable] = None):
        super().__init__("Deduplicate")
        self.key_fn = key_fn or (lambda x: x)

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        stage = self
        last: List[Any] = []

        def on_push():
            elem = logic.grab(in_)
            key = stage.key_fn(elem)
            if last and last[0] == key:
                logic.pull(in_)
            else:
                last[:] = [key]
                logic.push(out, elem)

        logic.set_handler(in_, make_in_handler(on_push))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class RecoverWithRetries(_LinearStage):
    """On upstream failure, switch to fn(exception)'s Source, at most
    `attempts` times (recoverWithRetries). The fallback materializes as its
    own interpreter feeding this stage through async callbacks."""

    def __init__(self, attempts: int, fn: Callable[[BaseException], Any]):
        super().__init__("RecoverWithRetries")
        self.attempts = attempts
        self.fn = fn

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        stage = self
        import collections
        buf: collections.deque = collections.deque()
        state = {"left": stage.attempts, "fallback": False, "done": False}

        def sub_elem(elem):
            if logic.is_available(out) and not buf:
                logic.push(out, elem)
            else:
                buf.append(elem)

        def sub_done(fut):
            exc = fut.exception()
            if exc is not None:
                switch(exc)
                return
            state["done"] = True
            if not buf:
                logic.complete_stage()

        def switch(ex):
            # attempts < 0 = unlimited (scaladsl recoverWithRetries(-1) /
            # recoverWith semantics)
            if state["left"] == 0:
                logic.fail_stage(ex)
                return
            if state["left"] > 0:
                state["left"] -= 1
            state["fallback"] = True
            try:
                src = stage.fn(ex)
            except Exception as e:  # noqa: BLE001
                logic.fail_stage(e)
                return
            on_elem = logic.get_async_callback(sub_elem)
            on_done = logic.get_async_callback(sub_done)
            fut = src.run_foreach(lambda e: on_elem.invoke(e),
                                  logic.materializer)
            fut.add_done_callback(lambda f: on_done.invoke(f))

        def on_push():
            logic.push(out, logic.grab(in_))

        def on_pull():
            if state["fallback"]:
                if buf:
                    logic.push(out, buf.popleft())
                if state["done"] and not buf:
                    logic.complete_stage()
            else:
                logic.pull(in_)

        logic.set_handler(in_, make_in_handler(on_push, None, switch))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class WatchTermination(_LinearStage):
    """Pass-through whose mat Future completes (or fails) with the stream's
    end (watchTermination)."""

    def __init__(self):
        super().__init__("WatchTermination")

    def create_logic_and_mat(self):
        fut: Future = Future()
        logic, in_, out = self._logic(), self.in_, self.out

        def on_finish():
            if not fut.done():
                fut.set_result(None)
            logic.complete_stage()

        def on_failure(ex):
            if not fut.done():
                fut.set_exception(ex)
            logic.fail_stage(ex)

        def on_downstream_finish(cause=None):
            # downstream cancel IS termination: the future completes
            # (watchTermination resolves with Done on cancellation)
            if not fut.done():
                fut.set_result(None)
            logic.cancel_stage(cause)

        logic.set_handler(in_, make_in_handler(
            lambda: logic.push(out, logic.grab(in_)), on_finish, on_failure))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_),
                                                on_downstream_finish))
        return logic, fut
