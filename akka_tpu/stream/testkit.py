"""Stream testkit: manually driven sources and asserting sinks.

Reference parity: akka-stream-testkit/src/main/scala/akka/stream/testkit/
scaladsl/TestSource.scala & TestSink.scala and StreamTestKit.scala probes —
TestPublisher.Probe (sendNext/sendComplete/sendError, expectRequest) and
TestSubscriber.Probe (request/expectNext/expectComplete/expectError/
expectNoMessage).
"""

from __future__ import annotations

import collections
import queue as _queue
import threading
from typing import Any, List, Optional

from .stage import (GraphStage, GraphStageLogic, Inlet, Outlet, SinkShape,
                    SourceShape, make_in_handler, make_out_handler)


class AssertionFailure(AssertionError):
    pass


class SourceProbe:
    """Mat value of TestSource.probe: drive the stream by hand."""

    def __init__(self):
        self._cb = None
        self._lock = threading.Lock()
        self._early: List = []
        self._demand = 0
        self._demand_cv = threading.Condition()
        self._cancelled = threading.Event()

    def _bind(self, cb):
        with self._lock:
            self._cb = cb
            early, self._early = self._early, []
        for item in early:
            cb.invoke(item)

    def _send(self, item):
        with self._lock:
            if self._cb is None:
                self._early.append(item)
                return
        self._cb.invoke(item)

    def send_next(self, elem) -> "SourceProbe":
        self._send(("next", elem))
        return self

    def send_complete(self) -> "SourceProbe":
        self._send(("complete", None))
        return self

    def send_error(self, ex: BaseException) -> "SourceProbe":
        self._send(("error", ex))
        return self

    # -- driven by the stage --------------------------------------------------
    def _on_pull(self):
        with self._demand_cv:
            self._demand += 1
            self._demand_cv.notify_all()

    def _on_cancel(self):
        self._cancelled.set()
        with self._demand_cv:
            self._demand_cv.notify_all()

    def expect_request(self, timeout: float = 3.0) -> int:
        with self._demand_cv:
            if self._demand == 0:
                self._demand_cv.wait(timeout)
            if self._demand == 0:
                raise AssertionFailure("no demand within timeout")
            d, self._demand = self._demand, 0
            return d

    def expect_cancellation(self, timeout: float = 3.0) -> None:
        if not self._cancelled.wait(timeout):
            raise AssertionFailure("no cancellation within timeout")


class _TestSourceStage(GraphStage):
    def __init__(self):
        self.name = "TestSource"
        self.out = Outlet("TestSource.out")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic_and_mat(self):
        out = self.out
        probe = SourceProbe()
        buf: collections.deque = collections.deque()
        state = {"done": None}

        class _L(GraphStageLogic):
            def pre_start(self):
                probe._bind(self.get_async_callback(self._on_cmd))

            def _on_cmd(self, item):
                kind, payload = item
                if kind == "next":
                    if self.is_available(out) and not buf:
                        self.push(out, payload)
                    else:
                        buf.append(payload)
                elif kind == "complete":
                    state["done"] = ("complete", None)
                    if not buf:
                        self.complete(out)
                else:
                    self.fail(out, payload)
        logic = _L(self._shape)

        def on_pull():
            if buf:
                logic.push(out, buf.popleft())
                if state["done"] and not buf:
                    logic.complete(out)
            else:
                probe._on_pull()
                if state["done"]:
                    logic.complete(out)

        def on_cancel(cause=None):
            probe._on_cancel()
            logic.cancel_stage(cause)
        logic.set_handler(out, make_out_handler(on_pull, on_cancel))
        return logic, probe


class SinkProbe:
    """Mat value of TestSink.probe: assert on received elements."""

    def __init__(self):
        self._cb = None
        self._lock = threading.Lock()
        self._early: List[int] = []
        self._events: _queue.Queue = _queue.Queue()

    def _bind(self, cb):
        with self._lock:
            self._cb = cb
            early, self._early = self._early, []
        for n in early:
            cb.invoke(n)

    def request(self, n: int) -> "SinkProbe":
        with self._lock:
            if self._cb is None:
                self._early.append(n)
                return self
        self._cb.invoke(n)
        return self

    # -- events from the stage ------------------------------------------------
    def _event(self, ev) -> None:
        self._events.put(ev)

    def _next_event(self, timeout: float):
        try:
            return self._events.get(timeout=timeout)
        except _queue.Empty:
            raise AssertionFailure(
                f"no stream event within {timeout}s") from None

    def expect_next(self, expected: Any = None, timeout: float = 3.0) -> Any:
        ev = self._next_event(timeout)
        if ev[0] != "next":
            raise AssertionFailure(f"expected element, got {ev}")
        if expected is not None and ev[1] != expected:
            raise AssertionFailure(f"expected {expected!r}, got {ev[1]!r}")
        return ev[1]

    def request_next(self, expected: Any = None, timeout: float = 3.0) -> Any:
        self.request(1)
        return self.expect_next(expected, timeout)

    def expect_next_n(self, elems, timeout: float = 3.0) -> "SinkProbe":
        for e in elems:
            self.expect_next(e, timeout)
        return self

    def expect_complete(self, timeout: float = 3.0) -> "SinkProbe":
        ev = self._next_event(timeout)
        if ev[0] != "complete":
            raise AssertionFailure(f"expected completion, got {ev}")
        return self

    def expect_error(self, timeout: float = 3.0) -> BaseException:
        ev = self._next_event(timeout)
        if ev[0] != "error":
            raise AssertionFailure(f"expected error, got {ev}")
        return ev[1]

    def expect_subscription_and_complete(self, timeout: float = 3.0
                                         ) -> "SinkProbe":
        return self.expect_complete(timeout)

    def expect_no_message(self, timeout: float = 0.2) -> "SinkProbe":
        try:
            ev = self._events.get(timeout=timeout)
        except _queue.Empty:
            return self
        raise AssertionFailure(f"expected silence, got {ev}")

    def cancel(self) -> "SinkProbe":
        with self._lock:
            cb = self._cb
        if cb is not None:
            cb.invoke("cancel")
        return self


_MISSING = object()


class _TestSinkStage(GraphStage):
    def __init__(self):
        self.name = "TestSink"
        self.in_ = Inlet("TestSink.in")
        self._shape = SinkShape(self.in_)

    @property
    def shape(self):
        return self._shape

    def create_logic_and_mat(self):
        in_ = self.in_
        probe = SinkProbe()
        state = {"demand": 0}

        class _L(GraphStageLogic):
            def pre_start(self):
                probe._bind(self.get_async_callback(self._on_request))

            def _on_request(self, n):
                if n == "cancel":
                    self.cancel(in_)
                    return
                state["demand"] += n
                if not self.has_been_pulled(in_) and not self.is_closed(in_) \
                        and state["demand"] > 0:
                    self.pull(in_)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            state["demand"] -= 1
            probe._event(("next", elem))
            if state["demand"] > 0:
                logic.pull(in_)

        def on_finish():
            probe._event(("complete", None))
            logic.complete_stage()

        def on_failure(ex):
            probe._event(("error", ex))
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic, probe


class TestSource:
    @staticmethod
    def probe():
        from .dsl import Source
        return Source.from_graph(_TestSourceStage)


class TestSink:
    @staticmethod
    def probe():
        from .dsl import Sink
        return Sink.from_graph(_TestSinkStage)
