"""Streams: backpressured processing pipelines (SURVEY.md §2.9).

Host path: the push/pull GraphInterpreter port-state machine hosted in one
actor per materialized graph (reference: impl/fusing/GraphInterpreter.scala
semantics), with the Source/Flow/Sink DSL and the core operator library.
TPU path: device pipelines that fuse a chain of tensor ops into a single
jitted step over chunked arrays (akka_tpu/stream/device.py) — the XLA-fusion
analogue of operator fusion in the reference materializer.
"""

from .stage import (FanInShape, FanOutShape, FlowShape, GraphStage,  # noqa: F401
                    GraphStageLogic, InHandler, Inlet, OutHandler, Outlet,
                    Shape, SinkShape, SourceShape, make_in_handler,
                    make_out_handler)
from .interpreter import (ActorGraphInterpreter, Connection,  # noqa: F401
                          GraphInterpreter, IllegalStateException)
from .dsl import (BidiFlow, Flow, GraphDSL, Keep, Materializer,  # noqa: F401
                  RunnableGraph, Sink, Source)
from .ops import (BufferOverflowException, NoSuchElementException,  # noqa: F401
                  SinkQueue, SourceQueue, TickCancellable)
from .killswitch import (KillSwitches, SharedKillSwitch,  # noqa: F401
                         UniqueKillSwitch)
from .hub import BroadcastHub, ConsumerInfo, MergeHub, PartitionHub  # noqa: F401
from .framing import Framing, FramingException, JsonFraming  # noqa: F401
from .retry import RetryFlow  # noqa: F401
from .device import DevicePipeline  # noqa: F401
from .streamref import SinkRef, SourceRef, StreamRefs  # noqa: F401
from .attributes import Attributes, Supervision  # noqa: F401
from .context import FlowWithContext, SourceWithContext  # noqa: F401
from .restart import (RestartFlow, RestartSettings, RestartSink,  # noqa: F401
                      RestartSource)
from .ops import _QUEUE_END as QUEUE_END  # noqa: F401

__all__ = [
    "Source", "Flow", "Sink", "Keep", "RunnableGraph", "Materializer",
    "BidiFlow", "GraphDSL",
    "GraphStage", "GraphStageLogic", "InHandler", "OutHandler",
    "Inlet", "Outlet", "Shape", "SourceShape", "SinkShape", "FlowShape",
    "FanInShape", "FanOutShape", "make_in_handler", "make_out_handler",
    "GraphInterpreter", "ActorGraphInterpreter", "Connection",
    "IllegalStateException",
    "SourceQueue", "SinkQueue", "QUEUE_END", "TickCancellable",
    "NoSuchElementException", "BufferOverflowException",
    "KillSwitches", "UniqueKillSwitch", "SharedKillSwitch",
    "MergeHub", "BroadcastHub", "PartitionHub", "ConsumerInfo",
    "DevicePipeline", "Framing", "FramingException", "JsonFraming",
    "RetryFlow",
    "StreamRefs", "SourceRef", "SinkRef",
    "Attributes", "Supervision",
    "RestartSource", "RestartFlow", "RestartSink", "RestartSettings",
    "SourceWithContext", "FlowWithContext",
]
