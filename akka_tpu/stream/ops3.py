"""Operator library, third tranche: the remaining Flow/Source families the
round-2 verdict named — divertTo, mergeSorted/mergePrioritized,
zipLatest/zipAll, foldAsync/scanAsync, onErrorComplete, lazy/never sources.

Reference parity: scaladsl/Flow.scala (divertTo :2061, mergeSorted,
mergePrioritized, zipLatest/zipLatestWith, zipAll, foldAsync, scanAsync,
onErrorComplete), scaladsl/Source.scala (lazySource/lazySingle, never),
impl/fusing/ZipLatestWith / MergeSorted / GraphStages.scala.
"""

from __future__ import annotations

import collections
from concurrent.futures import Future
from typing import Any, Callable, List, Optional

from .ops import _LinearStage, make_in_handler, make_out_handler
from .stage import (FanInShape, FanOutShape, GraphStage, GraphStageLogic,
                    Inlet, Outlet, SourceShape)


class DivertToStage(GraphStage):
    """1-in / 2-out: elements matching `when` leave via the divert outlet
    (wired to a Sink by the DSL), the rest continue downstream
    (scaladsl/Flow.scala divertTo)."""

    def __init__(self, when: Callable[[Any], bool]):
        self.name = "DivertTo"
        self.when = when
        self.in_ = Inlet("DivertTo.in")
        self.outs = [Outlet("DivertTo.main"), Outlet("DivertTo.divert")]
        self._shape = FanOutShape(self.in_, self.outs)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        in_, (main, divert), when = self.in_, self.outs, self.when
        logic = GraphStageLogic(self._shape)

        def _maybe_pull():
            # need demand on BOTH open outlets before pulling: the element's
            # route is unknown until it arrives
            if all(logic.is_available(o) or logic.is_closed(o)
                   for o in (main, divert)) \
                    and not (logic.is_closed(main) and logic.is_closed(divert)) \
                    and not logic.has_been_pulled(in_) \
                    and not logic.is_closed(in_):
                logic.pull(in_)

        def on_push():
            elem = logic.grab(in_)
            target = divert if when(elem) else main
            if logic.is_closed(target):
                # reference parity: divertTo is Partition(eagerCancel=true)
                # — losing either route cancels the whole stream, so no
                # element is ever silently dropped (ADVICE r3)
                logic.complete_stage()
            else:
                logic.push(target, elem)

        def on_downstream_finish(cause=None):
            # eagerCancel: either outlet closing tears the stage down
            logic.cancel_stage(cause)

        logic.set_handler(in_, make_in_handler(
            on_push, lambda: logic.complete_stage()))
        for o in (main, divert):
            logic.set_handler(o, make_out_handler(_maybe_pull,
                                                  on_downstream_finish))
        return logic


class MergeSortedStage(GraphStage):
    """Merge two ALREADY-SORTED inputs into one sorted output
    (scaladsl/Flow.scala mergeSorted; impl MergeSorted.scala)."""

    def __init__(self, key: Optional[Callable[[Any], Any]] = None):
        self.name = "MergeSorted"
        self.key = key or (lambda x: x)
        self.ins = [Inlet("MSort.in0"), Inlet("MSort.in1")]
        self.out = Outlet("MSort.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        i0, i1 = self.ins
        out, key = self.out, self.key
        # one-element lookahead per inlet
        head = {i0: None, i1: None}  # inlet -> [elem] | None
        logic = GraphStageLogic(self._shape)

        def _emit_if_ready():
            if not logic.is_available(out):
                return
            h0, h1 = head[i0], head[i1]
            c0, c1 = logic.is_closed(i0), logic.is_closed(i1)
            pick = None
            if h0 is not None and h1 is not None:
                pick = i0 if key(h0[0]) <= key(h1[0]) else i1
            elif h0 is not None and c1:
                pick = i0
            elif h1 is not None and c0:
                pick = i1
            elif h0 is None and h1 is None and c0 and c1:
                logic.complete(out)
                return
            if pick is None:
                for inlet in (i0, i1):
                    if head[inlet] is None and not logic.is_closed(inlet) \
                            and not logic.has_been_pulled(inlet):
                        logic.pull(inlet)
                return
            elem = head[pick][0]
            head[pick] = None
            logic.push(out, elem)
            if not logic.is_closed(pick):
                logic.pull(pick)
            elif head[i0] is None and head[i1] is None and \
                    logic.is_closed(i0) and logic.is_closed(i1):
                logic.complete(out)

        def mk_push(inlet):
            def on_push():
                head[inlet] = [logic.grab(inlet)]
                _emit_if_ready()
            return on_push

        def mk_finish(inlet):
            return _emit_if_ready

        for inlet in (i0, i1):
            logic.set_handler(inlet, make_in_handler(mk_push(inlet),
                                                     mk_finish(inlet)))
        logic.set_handler(out, make_out_handler(_emit_if_ready))
        return logic


class MergePrioritizedStage(GraphStage):
    """Merge n inputs; when several have an element buffered, the highest
    priority wins (deterministic form of scaladsl MergePrioritized — the
    reference randomizes proportionally to priorities; picking max keeps
    the test surface deterministic and the starvation-freedom property:
    a lone buffered element is always eligible)."""

    def __init__(self, priorities: List[int]):
        self.name = "MergePrioritized"
        if not priorities or any(p <= 0 for p in priorities):
            raise ValueError("priorities must be positive")
        self.priorities = list(priorities)
        self.ins = [Inlet(f"MPrio.in{i}") for i in range(len(priorities))]
        self.out = Outlet("MPrio.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        ins, out, prios = self.ins, self.out, self.priorities
        buf = {inlet: None for inlet in ins}
        logic = GraphStageLogic(self._shape)

        def _emit_if_ready():
            if not logic.is_available(out):
                return
            ready = [(prios[i], i) for i, inlet in enumerate(ins)
                     if buf[inlet] is not None]
            if not ready:
                if all(logic.is_closed(i) for i in ins):
                    logic.complete(out)
                else:
                    for inlet in ins:
                        if buf[inlet] is None and not logic.is_closed(inlet) \
                                and not logic.has_been_pulled(inlet):
                            logic.pull(inlet)
                return
            _, idx = max(ready)
            inlet = ins[idx]
            elem = buf[inlet][0]
            buf[inlet] = None
            logic.push(out, elem)
            if not logic.is_closed(inlet):
                logic.pull(inlet)
            elif all(buf[i] is None for i in ins) and \
                    all(logic.is_closed(i) for i in ins):
                logic.complete(out)

        def mk_push(inlet):
            def on_push():
                buf[inlet] = [logic.grab(inlet)]
                _emit_if_ready()
            return on_push

        for inlet in ins:
            logic.set_handler(inlet, make_in_handler(mk_push(inlet),
                                                     _emit_if_ready))
        logic.set_handler(out, make_out_handler(_emit_if_ready))
        return logic


class ZipLatestStage(GraphStage):
    """Combine the LATEST value of each input; emits whenever either side
    produces a new element once both have produced at least one
    (scaladsl zipLatest / zipLatestWith)."""

    def __init__(self, fn: Callable[[Any, Any], Any]):
        self.name = "ZipLatest"
        self.fn = fn
        self.ins = [Inlet("ZLatest.in0"), Inlet("ZLatest.in1")]
        self.out = Outlet("ZLatest.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        i0, i1 = self.ins
        out, fn = self.out, self.fn
        latest = {i0: None, i1: None}
        state = {"fresh": False}
        logic = GraphStageLogic(self._shape)

        def _emit_if_ready():
            if state["fresh"] and logic.is_available(out) and \
                    latest[i0] is not None and latest[i1] is not None:
                state["fresh"] = False
                logic.push(out, fn(latest[i0][0], latest[i1][0]))
            for inlet in (i0, i1):
                if not logic.is_closed(inlet) and \
                        not logic.has_been_pulled(inlet):
                    logic.pull(inlet)
            if all(logic.is_closed(i) for i in (i0, i1)) \
                    and not state["fresh"]:
                logic.complete(out)

        def mk_push(inlet):
            def on_push():
                latest[inlet] = [logic.grab(inlet)]
                state["fresh"] = True
                _emit_if_ready()
            return on_push

        def mk_finish(inlet):
            def on_finish():
                # a side that never produced ends the zip; otherwise defer
                # to _emit_if_ready, whose completion path is guarded on
                # `fresh` — completing here directly would drop a combined
                # element still waiting for downstream demand
                if latest[inlet] is None:
                    logic.complete_stage()
                else:
                    _emit_if_ready()
            return on_finish

        for inlet in (i0, i1):
            logic.set_handler(inlet, make_in_handler(mk_push(inlet),
                                                     mk_finish(inlet)))
        logic.set_handler(out, make_out_handler(_emit_if_ready))
        return logic


class ZipAllStage(GraphStage):
    """Zip two inputs, padding the exhausted side with its default until
    BOTH complete (scaladsl zipAll)."""

    def __init__(self, this_default: Any, that_default: Any):
        self.name = "ZipAll"
        self.d0 = this_default
        self.d1 = that_default
        self.ins = [Inlet("ZAll.in0"), Inlet("ZAll.in1")]
        self.out = Outlet("ZAll.out")
        self._shape = FanInShape(self.ins, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        i0, i1 = self.ins
        out, d0, d1 = self.out, self.d0, self.d1
        logic = GraphStageLogic(self._shape)

        def _emit_if_ready():
            a0, a1 = logic.is_available(i0), logic.is_available(i1)
            c0, c1 = logic.is_closed(i0), logic.is_closed(i1)
            if not logic.is_available(out):
                return
            if a0 and a1:
                logic.push(out, (logic.grab(i0), logic.grab(i1)))
            elif a0 and c1:
                logic.push(out, (logic.grab(i0), d1))
            elif a1 and c0:
                logic.push(out, (d0, logic.grab(i1)))
            elif c0 and c1:
                logic.complete(out)
                return
            else:
                for inlet in (i0, i1):
                    if not logic.is_closed(inlet) and \
                            not logic.has_been_pulled(inlet) and \
                            not logic.is_available(inlet):
                        logic.pull(inlet)
                return
            for inlet in (i0, i1):
                if not logic.is_closed(inlet) and \
                        not logic.has_been_pulled(inlet) and \
                        not logic.is_available(inlet):
                    logic.pull(inlet)
            if logic.is_closed(i0) and logic.is_closed(i1) and \
                    not logic.is_available(i0) and not logic.is_available(i1):
                logic.complete(out)

        for inlet in (i0, i1):
            logic.set_handler(inlet, make_in_handler(_emit_if_ready,
                                                     _emit_if_ready))
        logic.set_handler(out, make_out_handler(_emit_if_ready))
        return logic


class FoldAsync(_LinearStage):
    """fold whose aggregate fn returns a Future (scaladsl foldAsync);
    one aggregation in flight at a time, emits the final value at end."""

    def __init__(self, zero: Any, fn: Callable[[Any, Any], Any],
                 emit_each: bool = False):
        super().__init__("ScanAsync" if emit_each else "FoldAsync")
        self.zero = zero
        self.fn = fn
        self.emit_each = emit_each  # True = scanAsync semantics

    def create_logic(self):
        in_, out = self.in_, self.out
        zero, fn, emit_each = self.zero, self.fn, self.emit_each
        state = {"acc": zero, "busy": False, "finishing": False,
                 "emitted_zero": False, "pending_emit": False}

        logic = GraphStageLogic(self._shape)

        def _finish():
            if emit_each:
                if not state["emitted_zero"]:
                    # upstream finished before the first downstream pull:
                    # scan still owes the zero (reference Scan always
                    # emits it; ADVICE r3 — this was timing-dependent)
                    state["emitted_zero"] = True
                    logic.emit(out, state["acc"])
                logic.complete(out)
            elif logic.is_available(out):
                logic.push(out, state["acc"])
                logic.complete(out)
            else:
                state["pending_emit"] = True

        def _completed(res):
            ex, val = res
            state["busy"] = False
            if ex is not None:
                logic.fail_stage(ex)
                return
            state["acc"] = val
            if emit_each:
                if logic.is_available(out):
                    logic.push(out, val)
                else:
                    state["pending_emit"] = True
            if state["finishing"]:
                if not (emit_each and state["pending_emit"]):
                    _finish()
            elif not logic.has_been_pulled(in_) and not logic.is_closed(in_):
                logic.pull(in_)

        def on_push():
            elem = logic.grab(in_)
            state["busy"] = True
            cb = logic.get_async_callback(_completed)
            try:
                fut = fn(state["acc"], elem)
            except Exception as e:  # noqa: BLE001
                logic.fail_stage(e)
                return
            if isinstance(fut, Future):
                fut.add_done_callback(
                    lambda f: cb.invoke((f.exception(), None)
                                        if f.exception() is not None
                                        else (None, f.result())))
            else:
                _completed((None, fut))

        def on_finish():
            state["finishing"] = True
            if not state["busy"] and not state["pending_emit"]:
                _finish()

        logic.set_handler(in_, make_in_handler(on_push, on_finish))

        def on_pull():
            if emit_each and not state["emitted_zero"]:
                state["emitted_zero"] = True
                logic.push(out, state["acc"])  # scan emits zero first
                return
            if state["pending_emit"]:
                state["pending_emit"] = False
                if emit_each:
                    logic.push(out, state["acc"])
                    if state["finishing"] and not state["busy"]:
                        logic.complete(out)
                else:
                    logic.push(out, state["acc"])
                    logic.complete(out)
                return
            if not state["busy"] and not state["finishing"] and \
                    not logic.has_been_pulled(in_) and \
                    not logic.is_closed(in_):
                logic.pull(in_)
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class OnErrorComplete(_LinearStage):
    """Swallow a matching upstream failure and complete instead
    (scaladsl onErrorComplete)."""

    def __init__(self, pred: Optional[Callable[[BaseException], bool]] = None):
        super().__init__("OnErrorComplete")
        self.pred = pred or (lambda e: True)

    def create_logic(self):
        in_, out, pred = self.in_, self.out, self.pred
        logic = GraphStageLogic(self._shape)

        def on_fail(ex):
            if pred(ex):
                logic.complete(out)
            else:
                logic.fail_stage(ex)

        logic.set_handler(in_, make_in_handler(
            lambda: logic.push(out, logic.grab(in_)),
            lambda: logic.complete_stage(), on_fail))
        logic.set_handler(out, make_out_handler(lambda: logic.pull(in_)))
        return logic


class NeverSink(GraphStage):
    """Signals no demand, ever (scaladsl Sink.never)."""

    def __init__(self):
        self.name = "NeverSink"
        self.in_ = Inlet("NeverSink.in")
        from .stage import SinkShape
        self._shape = SinkShape(self.in_)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        logic = GraphStageLogic(self._shape)
        logic.set_handler(self.in_, make_in_handler(lambda: None))
        return logic


class NeverSource(GraphStage):
    """Emits nothing and never completes (scaladsl Source.never)."""

    def __init__(self):
        self.name = "NeverSource"
        self.out = Outlet("Never.out")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        logic = GraphStageLogic(self._shape)
        logic.set_handler(self.out, make_out_handler(lambda: None))
        return logic


class UnfoldResourceSource(GraphStage):
    """Source.unfoldResource as a real stage: the resource is opened at
    pre_start and closed in post_stop, which the interpreter runs on EVERY
    termination path — exhaustion, stage failure, and downstream cancel
    (reference: impl/UnfoldResourceSource.scala; the close must not wait
    for GC)."""

    def __init__(self, create: Callable[[], Any],
                 read: Callable[[Any], Optional[Any]],
                 close: Callable[[Any], None]):
        self.name = "UnfoldResourceSource"
        self.create = create
        self.read = read
        self.close = close
        self.out = Outlet("UnfoldResource.out")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        stage = self
        out = self.out
        state = {"resource": None, "open": False}

        class _L(GraphStageLogic):
            def pre_start(self):
                state["resource"] = stage.create()
                state["open"] = True

            def post_stop(self):
                if state["open"]:
                    state["open"] = False
                    stage.close(state["resource"])

        logic = _L(self._shape)

        def _reopen():
            # Supervision.restart: close the (possibly wedged) resource and
            # open a fresh one before the retried read (reference
            # UnfoldResourceSource restartState)
            if state["open"]:
                state["open"] = False
                stage.close(state["resource"])
            state["resource"] = stage.create()
            state["open"] = True
        logic.restart_state = _reopen

        def on_pull():
            v = stage.read(state["resource"])
            if v is None:
                logic.complete(out)
            else:
                logic.push(out, v)
        logic.set_handler(out, make_out_handler(on_pull))
        return logic
