"""StreamRefs: source/sink handles that cross the node boundary with
backpressure.

Reference parity: akka-stream/src/main/scala/akka/stream/impl/streamref/ —
SinkRefImpl.scala:42,152-161 / SourceRefImpl.scala / StreamRefs.scala and
the wire protocol (StreamRefsProtocol): OnSubscribeHandshake(targetRef),
CumulativeDemand(seqNr), SequencedOnNext(seqNr, payload),
RemoteStreamCompleted(seqNr), RemoteStreamFailure(msg). Demand is
cumulative (the highest seq nr the consumer is ready to receive); data is
at-most-once, a sequence gap fails the stream (InvalidSequenceNumberException
semantics).

Usage (mirrors the reference):
    # origin node: run a stream INTO a sink-ref; ship the SourceRef away
    source_ref = my_source.run_with(StreamRefs.source_ref(), system)
    other_node_actor.tell(("here", source_ref))
    # remote node: turn the handle back into a live Source
    SourceRef.source(source_ref).run_with(Sink.foreach(...), remote_system)

SinkRef is the dual: materialize `StreamRefs.sink_ref()` as a Source, ship
the SinkRef, and the remote runs a stream into it.

Refs serialize as actor paths (ActorRef payload serialization is already
wire-supported), so they work over any transport.
"""

from __future__ import annotations

import collections
import threading
from dataclasses import dataclass
from typing import Any, Optional

from ..actor.actor import Actor
from ..actor.props import Props
from .dsl import Sink, Source
from .stage import (GraphStage, GraphStageLogic, Inlet, Outlet, SinkShape,
                    SourceShape, make_in_handler, make_out_handler)


# -- wire protocol (reference: StreamRefsProtocol) ---------------------------

@dataclass(frozen=True)
class OnSubscribeHandshake:
    target_path: str   # consumer-side partner actor


@dataclass(frozen=True)
class CumulativeDemand:
    seq_nr: int        # consumer ready to receive up to this seq


@dataclass(frozen=True)
class SequencedOnNext:
    seq_nr: int
    payload: Any


@dataclass(frozen=True)
class RemoteStreamCompleted:
    seq_nr: int


@dataclass(frozen=True)
class RemoteStreamFailure:
    message: str


@dataclass(frozen=True)
class SourceRef:
    """Serializable handle to a stream running on the origin node."""
    origin_path: str

    @staticmethod
    def source(ref: "SourceRef") -> Source:
        return Source.from_graph(lambda: _SourceRefStage(ref.origin_path))


@dataclass(frozen=True)
class SinkRef:
    """Serializable handle accepting a stream from a remote node."""
    target_path: str

    @staticmethod
    def sink(ref: "SinkRef") -> Sink:
        return Sink.from_graph(lambda: _SinkRefStage(ref.target_path))


DEMAND_BATCH = 16  # demand window granularity (reference buffers ~32)


class _OriginActor(Actor):
    """Origin-side partner: forwards demand into the stream, relays elements
    out (reference: SinkRefImpl's stage-internal actor, here explicit)."""

    def __init__(self):
        super().__init__()
        self.stage_cb = None          # async callback into the origin stage
        self.early: list = []

    def receive(self, message: Any) -> Any:
        if message == "___bind___":
            pass
        elif isinstance(message, tuple) and message[0] == "___cb___":
            self.stage_cb = message[1]
            for m in self.early:
                self.stage_cb.invoke(m)
            self.early = []
        elif isinstance(message, (OnSubscribeHandshake, CumulativeDemand)):
            if self.stage_cb is None:
                self.early.append(message)
            else:
                self.stage_cb.invoke(message)
        else:
            return NotImplemented


class _SourceRefSinkStage(GraphStage):
    """The Sink materialized on the ORIGIN: its mat value is the SourceRef
    to ship away (reference: StreamRefs.sourceRef() -> Sink[T, SourceRef])."""

    def __init__(self):
        self.name = "SourceRefSink"
        self.in_ = Inlet("SourceRefSink.in")
        self._shape = SinkShape(self.in_)

    @property
    def shape(self):
        return self._shape

    def create_logic_and_mat(self):
        stage = self
        in_ = self.in_
        state = {"partner": None, "demand": 0, "seq": 0, "target": None,
                 "origin_ref": None, "ready": threading.Event()}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.set_keep_going(True)
                system = self.materializer.system
                cb = self.get_async_callback(self._on_remote)
                ref = system.actor_of(Props.create(_OriginActor))
                state["origin_ref"] = ref
                state["ready"].set()
                ref.tell(("___cb___", cb), None)

            def _on_remote(self, msg):
                system = self.materializer.system
                if isinstance(msg, OnSubscribeHandshake):
                    state["target"] = system.provider.resolve_actor_ref(
                        msg.target_path)
                elif isinstance(msg, CumulativeDemand):
                    state["demand"] = max(state["demand"], msg.seq_nr)
                    if not self.has_been_pulled(in_) and \
                            not self.is_closed(in_) and \
                            state["seq"] < state["demand"]:
                        self.pull(in_)
                    if self.is_closed(in_) and state.get("done") is not None:
                        self._flush_done()

            def _flush_done(self):
                if state["target"] is not None:
                    done = state["done"]
                    if done[0] == "complete":
                        state["target"].tell(
                            RemoteStreamCompleted(state["seq"]),
                            state["origin_ref"])
                    else:
                        state["target"].tell(RemoteStreamFailure(done[1]),
                                             state["origin_ref"])
                    self.set_keep_going(False)

            def post_stop(self):
                ref = state["origin_ref"]
                if ref is not None:
                    self.materializer.system.stop(ref)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            state["seq"] += 1
            if state["target"] is not None:
                state["target"].tell(SequencedOnNext(state["seq"], elem),
                                     state["origin_ref"])
            if state["seq"] < state["demand"] and not logic.is_closed(in_):
                logic.pull(in_)

        def on_finish():
            state["done"] = ("complete",)
            logic._flush_done() if state["target"] is not None else None

        def on_failure(ex):
            state["done"] = ("fail", str(ex))
            if state["target"] is not None:
                logic._flush_done()
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))

        # mat value needs the partner's FULL path (with address) so it
        # resolves from the remote side; computed lazily via a thunk-ref
        class _LazySourceRef:
            def _path(self):
                # the partner actor is spawned in pre_start on the stream's
                # actor thread; wait for materialization to reach it
                if not state["ready"].wait(10.0):
                    raise RuntimeError("stream ref not materialized")
                system = logic.materializer.system
                ref = state["origin_ref"]
                addr = getattr(system.provider, "default_address", None)
                rel = ref.path.to_string_without_address()
                return f"{addr}{rel}" if addr is not None else rel

            def __reduce__(self):
                return (SourceRef, (self._path(),))

            @property
            def origin_path(self):
                return self._path()
        return logic, _LazySourceRef()


class _ConsumerActor(Actor):
    """Consumer-side partner: receives sequenced elements, feeds the stage."""

    def __init__(self):
        super().__init__()
        self.stage_cb = None
        self.early: list = []

    def receive(self, message: Any) -> Any:
        if isinstance(message, tuple) and message[0] == "___cb___":
            self.stage_cb = message[1]
            for m in self.early:
                self.stage_cb.invoke(m)
            self.early = []
        elif isinstance(message, (SequencedOnNext, RemoteStreamCompleted,
                                  RemoteStreamFailure)):
            if self.stage_cb is None:
                self.early.append(message)
            else:
                self.stage_cb.invoke(message)
        else:
            return NotImplemented


class _SourceRefStage(GraphStage):
    """The Source materialized on the CONSUMER from a SourceRef."""

    def __init__(self, origin_path: str):
        self.name = "SourceRef"
        self.origin_path = origin_path
        self.out = Outlet("SourceRef.out")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        stage = self
        out = self.out
        buf: collections.deque = collections.deque()
        state = {"received": 0, "demanded": 0, "consumer_ref": None,
                 "origin": None, "done": None}

        class _L(GraphStageLogic):
            def pre_start(self):
                system = self.materializer.system
                cb = self.get_async_callback(self._on_remote)
                ref = system.actor_of(Props.create(_ConsumerActor))
                state["consumer_ref"] = ref
                ref.tell(("___cb___", cb), None)
                origin = system.provider.resolve_actor_ref(stage.origin_path)
                state["origin"] = origin
                addr = getattr(system.provider, "default_address", None)
                rel = ref.path.to_string_without_address()
                full = f"{addr}{rel}" if addr is not None else rel
                origin.tell(OnSubscribeHandshake(full), ref)
                self._demand_more()

            def _demand_more(self):
                want = state["received"] + DEMAND_BATCH - len(buf)
                if want > state["demanded"]:
                    state["demanded"] = want
                    state["origin"].tell(CumulativeDemand(want),
                                         state["consumer_ref"])

            def _on_remote(self, msg):
                if isinstance(msg, SequencedOnNext):
                    if msg.seq_nr != state["received"] + 1:
                        self.fail(out, RuntimeError(
                            f"invalid sequence nr {msg.seq_nr}, expected "
                            f"{state['received'] + 1} (at-most-once "
                            f"transport dropped a frame)"))
                        return
                    state["received"] = msg.seq_nr
                    if self.is_available(out) and not buf:
                        self.push(out, msg.payload)
                    else:
                        buf.append(msg.payload)
                    self._demand_more()
                elif isinstance(msg, RemoteStreamCompleted):
                    state["done"] = ("complete",)
                    if not buf:
                        self.complete(out)
                elif isinstance(msg, RemoteStreamFailure):
                    self.fail(out, RuntimeError(
                        f"remote stream failed: {msg.message}"))

            def post_stop(self):
                ref = state["consumer_ref"]
                if ref is not None:
                    self.materializer.system.stop(ref)
        logic = _L(self._shape)

        def on_pull():
            if buf:
                logic.push(out, buf.popleft())
                logic._demand_more()
            if state["done"] is not None and not buf:
                logic.complete(out)
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class _SinkRefSourceStage(GraphStage):
    """The Source materialized LOCALLY whose mat is a SinkRef for a remote
    producer (reference: StreamRefs.sinkRef() -> Source[T, SinkRef])."""

    def __init__(self):
        self.name = "SinkRefSource"
        self.out = Outlet("SinkRefSource.out")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic_and_mat(self):
        out = self.out
        buf: collections.deque = collections.deque()
        state = {"received": 0, "demanded": 0, "consumer_ref": None,
                 "producer": None, "done": None,
                 "ready": threading.Event()}

        class _L(GraphStageLogic):
            def pre_start(self):
                system = self.materializer.system
                cb = self.get_async_callback(self._on_remote)
                ref = system.actor_of(Props.create(_SinkTargetActor))
                state["consumer_ref"] = ref
                state["ready"].set()
                ref.tell(("___cb___", cb), None)

            def _demand_more(self):
                if state["producer"] is None:
                    return
                want = state["received"] + DEMAND_BATCH - len(buf)
                if want > state["demanded"]:
                    state["demanded"] = want
                    state["producer"].tell(CumulativeDemand(want),
                                           state["consumer_ref"])

            def _on_remote(self, msg):
                system = self.materializer.system
                if isinstance(msg, OnSubscribeHandshake):
                    state["producer"] = system.provider.resolve_actor_ref(
                        msg.target_path)
                    self._demand_more()
                elif isinstance(msg, SequencedOnNext):
                    if msg.seq_nr != state["received"] + 1:
                        self.fail(out, RuntimeError(
                            f"invalid sequence nr {msg.seq_nr}"))
                        return
                    state["received"] = msg.seq_nr
                    if self.is_available(out) and not buf:
                        self.push(out, msg.payload)
                    else:
                        buf.append(msg.payload)
                    self._demand_more()
                elif isinstance(msg, RemoteStreamCompleted):
                    state["done"] = ("complete",)
                    if not buf:
                        self.complete(out)
                elif isinstance(msg, RemoteStreamFailure):
                    self.fail(out, RuntimeError(msg.message))

            def post_stop(self):
                ref = state["consumer_ref"]
                if ref is not None:
                    self.materializer.system.stop(ref)
        logic = _L(self._shape)

        def on_pull():
            if buf:
                logic.push(out, buf.popleft())
                logic._demand_more()
            if state["done"] is not None and not buf:
                logic.complete(out)
        logic.set_handler(out, make_out_handler(on_pull))

        class _LazySinkRef:
            def _path(self):
                if not state["ready"].wait(10.0):
                    raise RuntimeError("stream ref not materialized")
                system = logic.materializer.system
                ref = state["consumer_ref"]
                addr = getattr(system.provider, "default_address", None)
                rel = ref.path.to_string_without_address()
                return f"{addr}{rel}" if addr is not None else rel

            def __reduce__(self):
                return (SinkRef, (self._path(),))

            @property
            def target_path(self):
                return self._path()
        return logic, _LazySinkRef()


class _SinkTargetActor(_ConsumerActor):
    """Also accepts the handshake (the remote producer initiates it)."""

    def receive(self, message: Any) -> Any:
        if isinstance(message, OnSubscribeHandshake):
            if self.stage_cb is None:
                self.early.append(message)
            else:
                self.stage_cb.invoke(message)
            return None
        return super().receive(message)


class _SinkRefStage(GraphStage):
    """The Sink materialized on the PRODUCER side from a shipped SinkRef:
    initiates the handshake then pushes on demand."""

    def __init__(self, target_path: str):
        self.name = "SinkRef"
        self.target_path = target_path
        self.in_ = Inlet("SinkRef.in")
        self._shape = SinkShape(self.in_)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        stage = self
        in_ = self.in_
        state = {"target": None, "demand": 0, "seq": 0, "origin_ref": None,
                 "done": None}

        class _L(GraphStageLogic):
            def pre_start(self):
                self.set_keep_going(True)
                system = self.materializer.system
                cb = self.get_async_callback(self._on_remote)
                ref = system.actor_of(Props.create(_OriginActor))
                state["origin_ref"] = ref
                ref.tell(("___cb___", cb), None)
                state["target"] = system.provider.resolve_actor_ref(
                    stage.target_path)
                addr = getattr(system.provider, "default_address", None)
                rel = ref.path.to_string_without_address()
                full = f"{addr}{rel}" if addr is not None else rel
                state["target"].tell(OnSubscribeHandshake(full), ref)

            def _on_remote(self, msg):
                if isinstance(msg, CumulativeDemand):
                    state["demand"] = max(state["demand"], msg.seq_nr)
                    if not self.has_been_pulled(in_) and \
                            not self.is_closed(in_) and \
                            state["seq"] < state["demand"]:
                        self.pull(in_)
                    if state["done"] is not None:
                        self._flush_done()

            def _flush_done(self):
                done = state["done"]
                if done[0] == "complete":
                    state["target"].tell(RemoteStreamCompleted(state["seq"]),
                                         state["origin_ref"])
                else:
                    state["target"].tell(RemoteStreamFailure(done[1]),
                                         state["origin_ref"])
                self.set_keep_going(False)

            def post_stop(self):
                ref = state["origin_ref"]
                if ref is not None:
                    self.materializer.system.stop(ref)
        logic = _L(self._shape)

        def on_push():
            elem = logic.grab(in_)
            state["seq"] += 1
            state["target"].tell(SequencedOnNext(state["seq"], elem),
                                 state["origin_ref"])
            if state["seq"] < state["demand"] and not logic.is_closed(in_):
                logic.pull(in_)

        def on_finish():
            state["done"] = ("complete",)
            logic._flush_done()

        def on_failure(ex):
            state["done"] = ("fail", str(ex))
            logic._flush_done()
            logic.fail_stage(ex)
        logic.set_handler(in_, make_in_handler(on_push, on_finish, on_failure))
        return logic


class StreamRefs:
    """(reference: stream/StreamRefs.scala)"""

    @staticmethod
    def source_ref() -> Sink:
        """A Sink whose mat value is a SourceRef (ship it; the remote side
        calls SourceRef.source(ref) to consume this stream)."""
        return Sink.from_graph(_SourceRefSinkStage)

    @staticmethod
    def sink_ref() -> Source:
        """A Source whose mat value is a SinkRef (ship it; the remote side
        calls SinkRef.sink(ref) to produce into this stream)."""
        return Source.from_graph(_SinkRefSourceStage)
