"""SourceWithContext / FlowWithContext: data with a carried context.

Reference parity: akka-stream scaladsl SourceWithContext.scala /
FlowWithContext.scala — a stream of (data, context) pairs where the
operator vocabulary applies to the DATA while the context follows each
element automatically (the pattern behind offset-committing Kafka
pipelines: the committable offset rides as context). Context rules match
the reference:

- map/mapAsync transform data, context unchanged
- filter/collect drop the pair together
- mapConcat duplicates the context onto every expanded element
- grouped emits (list of data, list of contexts)
- unsafe/arbitrary reordering ops are NOT exposed (the reference
  deliberately restricts the vocabulary so contexts can't be lost or
  reordered silently)

Internally a thin wrapper over a Source/Flow of (data, ctx) tuples —
`as_source()`/`as_flow()` unwraps, `via(...)` composes wrappers.
"""

from __future__ import annotations

from typing import Any, Callable

from .dsl import Flow, Keep, Sink, Source


def _pairify(fn):
    """Lift fn(data) -> data onto (data, ctx) pairs."""
    return lambda p: (fn(p[0]), p[1])


class FlowWithContext:
    """(reference: scaladsl/FlowWithContext.scala)"""

    def __init__(self, under: Flow):
        self._under = under  # Flow of (data, ctx) -> (data, ctx)

    # -- creation -------------------------------------------------------------
    @staticmethod
    def create() -> "FlowWithContext":
        return FlowWithContext(Flow())

    @staticmethod
    def from_tuples(flow: Flow) -> "FlowWithContext":
        """Wrap a Flow that already processes (data, ctx) tuples
        (reference: FlowWithContext.fromTuples)."""
        return FlowWithContext(flow)

    def as_flow(self) -> Flow:
        """The underlying Flow of (data, ctx) tuples (asFlow)."""
        return self._under

    # -- data ops (context follows) ------------------------------------------
    def map(self, fn) -> "FlowWithContext":
        return FlowWithContext(self._under.map(_pairify(fn)))

    def map_error(self, fn) -> "FlowWithContext":
        return FlowWithContext(self._under.map_error(fn))

    def map_async(self, parallelism: int, fn) -> "FlowWithContext":
        from concurrent.futures import Future

        def lifted(p):
            data, ctx = p
            fut = fn(data)
            if isinstance(fut, Future):
                out: Future = Future()

                def done(f):
                    if f.exception() is not None:
                        out.set_exception(f.exception())
                    else:
                        out.set_result((f.result(), ctx))
                fut.add_done_callback(done)
                return out
            return (fut, ctx)
        return FlowWithContext(self._under.map_async(parallelism, lifted))

    def filter(self, pred) -> "FlowWithContext":
        return FlowWithContext(self._under.filter(lambda p: pred(p[0])))

    def filter_not(self, pred) -> "FlowWithContext":
        return FlowWithContext(self._under.filter(lambda p: not pred(p[0])))

    def collect(self, fn) -> "FlowWithContext":
        """fn returns None to drop the pair (partial-function analogue)."""
        def lifted(p):
            v = fn(p[0])
            return None if v is None else (v, p[1])
        return FlowWithContext(self._under.collect(lifted))

    def map_concat(self, fn) -> "FlowWithContext":
        """Each output element carries the ORIGINAL element's context."""
        def lifted(p):
            data, ctx = p
            return [(v, ctx) for v in fn(data)]
        return FlowWithContext(self._under.map_concat(lifted))

    def grouped(self, n: int) -> "FlowWithContext":
        """Emits ([data...], [ctx...]) per group (reference grouped)."""
        def split(grp):
            return ([d for d, _c in grp], [c for _d, c in grp])
        return FlowWithContext(self._under.grouped(n).map(split))

    def sliding(self, n: int, step: int = 1) -> "FlowWithContext":
        def split(grp):
            return ([d for d, _c in grp], [c for _d, c in grp])
        return FlowWithContext(self._under.sliding(n, step).map(split))

    def map_context(self, fn) -> "FlowWithContext":
        """Transform the CONTEXT, data unchanged (mapContext)."""
        return FlowWithContext(self._under.map(lambda p: (p[0], fn(p[1]))))

    def log(self, name: str, extract=lambda x: x) -> "FlowWithContext":
        return FlowWithContext(self._under.log(name,
                                               lambda p: extract(p[0])))

    def throttle(self, elements: int, per_seconds: float,
                 **kw) -> "FlowWithContext":
        return FlowWithContext(self._under.throttle(elements, per_seconds,
                                                    **kw))

    # -- composition ----------------------------------------------------------
    def via(self, other: "FlowWithContext") -> "FlowWithContext":
        return FlowWithContext(self._under.via(other._under))

    def with_attributes(self, attrs) -> "FlowWithContext":
        return FlowWithContext(self._under.with_attributes(attrs))


class SourceWithContext:
    """(reference: scaladsl/SourceWithContext.scala)"""

    def __init__(self, under: Source):
        self._under = under  # Source of (data, ctx)

    @staticmethod
    def from_tuples(source: Source) -> "SourceWithContext":
        return SourceWithContext(source)

    def as_source(self) -> Source:
        return self._under

    def via(self, flow: FlowWithContext) -> "SourceWithContext":
        return SourceWithContext(self._under.via(flow.as_flow()))

    def with_attributes(self, attrs) -> "SourceWithContext":
        return SourceWithContext(self._under.with_attributes(attrs))

    # mirror the FlowWithContext vocabulary by delegation
    def _lift(self, name, *args, **kw) -> "SourceWithContext":
        fwc = getattr(FlowWithContext.create(), name)(*args, **kw)
        return self.via(fwc)

    def map(self, fn):
        return self._lift("map", fn)

    def map_error(self, fn):
        return self._lift("map_error", fn)

    def map_async(self, parallelism, fn):
        return self._lift("map_async", parallelism, fn)

    def filter(self, pred):
        return self._lift("filter", pred)

    def filter_not(self, pred):
        return self._lift("filter_not", pred)

    def collect(self, fn):
        return self._lift("collect", fn)

    def map_concat(self, fn):
        return self._lift("map_concat", fn)

    def grouped(self, n):
        return self._lift("grouped", n)

    def sliding(self, n, step=1):
        return self._lift("sliding", n, step)

    def map_context(self, fn):
        return self._lift("map_context", fn)

    def log(self, name, extract=lambda x: x):
        return self._lift("log", name, extract)

    def throttle(self, elements, per_seconds, **kw):
        return self._lift("throttle", elements, per_seconds, **kw)

    # -- run ------------------------------------------------------------------
    def to_mat(self, sink: Sink, combine=Keep.right):
        return self._under.to_mat(sink, combine)

    def run_with(self, sink: Sink, materializer_or_system):
        return self._under.run_with(sink, materializer_or_system)


def _source_as_source_with_context(self, extract_ctx: Callable[[Any], Any]
                                   ) -> SourceWithContext:
    """Source.as_source_with_context(f): pair every element with f(elem)
    as its carried context (reference: Source.asSourceWithContext)."""
    return SourceWithContext(self.map(lambda x: (x, extract_ctx(x))))


def _flow_as_flow_with_context(self, collapse: Callable[[Any, Any], Any],
                               extract_ctx: Callable[[Any], Any]
                               ) -> FlowWithContext:
    """Flow.as_flow_with_context(collapse, extract): adapt a plain Flow —
    incoming (data, ctx) pairs are collapsed into the Flow's input
    elements, contexts are re-extracted from its outputs (reference:
    Flow.asFlowWithContext)."""
    inner = self

    def build_pair_flow():
        return Flow().map(lambda p: collapse(p[0], p[1])).via(inner) \
            .map(lambda out: (out, extract_ctx(out)))
    return FlowWithContext(build_pair_flow())


Source.as_source_with_context = _source_as_source_with_context
Flow.as_flow_with_context = _flow_as_flow_with_context
