"""GraphInterpreter: the push/pull execution engine + its host actor.

Reference parity: akka-stream/src/main/scala/akka/stream/impl/fusing/
GraphInterpreter.scala — per-connection port-state machine (state docs
:154-198), bounded `execute(eventLimit)` event loop (:348), `processEvent`
dispatch to onPush/onPull/onUpstreamFinish/onDownstreamFinish (:485);
ActorGraphInterpreter.scala — the interpreter runs inside one actor per
fused island, external/async events arrive as actor messages.

Connection states here: "idle" → pull() → "pulled" → push() → "pushed" →
grab()+next pull → "idle"; closed flags per side with completion/failure/
cancellation propagation events. Failures tear the stream down along the
graph exactly like the reference (fail downstream, cancel upstream).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..actor.actor import Actor
from .stage import GraphStageLogic, Inlet, Outlet

# consecutive supervised on_pull failures on one connection before the
# resume/restart directive is escalated to a stage failure. The bound is a
# last-resort guard against a HOT livelock (a source whose on_pull throws
# deterministically forever under a resuming decider); it is set far above
# any plausible run of legitimately skipped bad records, and retries are
# rescheduled through the host actor mailbox (not the in-loop queue) so
# even a long run of failures stays fair to async events and cancellation
MAX_PULL_RETRIES = 10_000


class Connection:
    __slots__ = ("id", "out_logic", "outlet", "in_logic", "inlet", "state",
                 "element", "out_closed", "in_closed", "failure",
                 "pending_complete", "pending_fail", "pull_retries")

    def __init__(self, cid: int, out_logic: GraphStageLogic, outlet: Outlet,
                 in_logic: GraphStageLogic, inlet: Inlet):
        self.id = cid
        self.out_logic = out_logic
        self.outlet = outlet
        self.in_logic = in_logic
        self.inlet = inlet
        self.state = "idle"         # idle | pulled | pushed | grabbed
        self.element: Any = None
        self.out_closed = False
        self.in_closed = False
        self.failure: Optional[BaseException] = None
        self.pending_complete = False  # complete after in-flight push lands
        self.pending_fail: Optional[BaseException] = None
        self.pull_retries = 0  # consecutive supervised on_pull failures


@dataclass(frozen=True)
class _AsyncEvent:
    logic: Any
    handler: Callable[[Any], None]
    event: Any


@dataclass(frozen=True)
class _TimerEvent:
    logic: Any
    key: Any
    gen: int


class GraphInterpreter:
    """One per materialized (fused) graph."""

    def __init__(self, logics: List[GraphStageLogic],
                 connections: List[Connection], materializer=None,
                 on_shutdown: Optional[Callable[[], None]] = None):
        self.logics = logics
        self.connections = connections
        self.materializer = materializer
        self.on_shutdown = on_shutdown
        self.queue: List[Tuple[str, Connection]] = []
        self.by_inlet: Dict[int, Connection] = {}
        self.by_outlet: Dict[int, Connection] = {}
        self._running = False
        self._shutdown = False
        self._timer_gen: Dict[Tuple[int, Any], int] = {}
        self._timer_tasks: Dict[Tuple[int, Any], Any] = {}
        self._scheduler = None  # set by host (actor) for timers
        self._self_ref = None   # host actor ref for async events
        for c in connections:
            self.by_inlet[c.inlet.id] = c
            self.by_outlet[c.outlet.id] = c
        for lg in logics:
            lg.interpreter = self

    # -- startup --------------------------------------------------------------
    def init(self) -> None:
        for lg in self.logics:
            lg.pre_start()
        self.execute()

    # -- port ops (called from logics) ---------------------------------------
    def pull(self, logic: GraphStageLogic, inlet: Inlet) -> None:
        """tryPull semantics: a pull while already pulled, or while a push
        event is still in flight, is a no-op (the reference's strict pull
        throws there and operators call tryPull; ours only ever means try)."""
        c = self.by_inlet[inlet.id]
        if c.in_closed or (c.out_closed and c.state != "pushed"):
            return
        if c.state in ("pulled", "pushed"):
            return
        c.state = "pulled"
        c.element = None
        self.queue.append(("pull", c))

    def push(self, logic: GraphStageLogic, outlet: Outlet, elem: Any) -> None:
        c = self.by_outlet[outlet.id]
        if c.in_closed:
            return  # downstream cancelled: drop
        if c.out_closed:
            raise IllegalStateException(f"cannot push closed port {outlet}")
        if c.state != "pulled":
            raise IllegalStateException(
                f"cannot push port {outlet} that was not pulled "
                f"(state {c.state})")
        c.state = "pushed"
        c.element = elem
        self.queue.append(("push", c))

    def grab(self, logic: GraphStageLogic, inlet: Inlet) -> Any:
        c = self.by_inlet[inlet.id]
        if c.state != "pushed":
            raise IllegalStateException(
                f"cannot grab port {inlet} in state {c.state}")
        elem, c.element = c.element, None
        c.state = "grabbed"
        return elem

    def is_available(self, logic: GraphStageLogic, port) -> bool:
        if isinstance(port, Inlet):
            c = self.by_inlet.get(port.id)
            return c is not None and c.state == "pushed"
        c = self.by_outlet.get(port.id)
        return c is not None and c.state == "pulled" and not c.out_closed

    def has_been_pulled(self, logic: GraphStageLogic, inlet: Inlet) -> bool:
        c = self.by_inlet[inlet.id]
        return c.state == "pulled"

    def is_port_closed(self, logic: GraphStageLogic, port) -> bool:
        if isinstance(port, Inlet):
            c = self.by_inlet.get(port.id)
            return c is None or c.in_closed
        c = self.by_outlet.get(port.id)
        return c is None or c.out_closed

    def complete(self, logic: GraphStageLogic, outlet: Outlet) -> None:
        c = self.by_outlet[outlet.id]
        if c.out_closed:
            return
        if c.state == "pushed":
            # let the in-flight element land first (reference: Pushing|InClosed)
            c.pending_complete = True
            c.out_closed = True
            return
        c.out_closed = True
        self.queue.append(("complete", c))

    def fail(self, logic: GraphStageLogic, outlet: Outlet,
             ex: BaseException) -> None:
        c = self.by_outlet[outlet.id]
        if c.out_closed:
            return
        c.out_closed = True
        c.failure = ex
        self.queue.append(("fail", c))

    def cancel(self, logic: GraphStageLogic, inlet: Inlet,
               cause: Optional[BaseException] = None) -> None:
        c = self.by_inlet[inlet.id]
        if c.in_closed:
            return
        c.in_closed = True
        c.element = None
        self.queue.append(("cancel", c))

    # -- async/timers ---------------------------------------------------------
    def enqueue_async(self, logic, handler, event) -> None:
        """May be called from ANY thread: routes through the host actor's
        mailbox when hosted, else runs inline (unhosted/synchronous mode)."""
        if self._self_ref is not None:
            self._self_ref.tell(_AsyncEvent(logic, handler, event), None)
        else:
            self._dispatch_async(_AsyncEvent(logic, handler, event))

    def _dispatch_async(self, ev: _AsyncEvent) -> None:
        if self._shutdown:
            return
        try:
            ev.handler(ev.event)
        except Exception as e:  # noqa: BLE001
            ev.logic.fail_stage(e)
        self.execute()
        # a handler may have dropped the last keep-going flag with no new
        # events queued — re-check shutdown
        if not self.queue and not self._shutdown and self._all_closed():
            self._do_shutdown()

    def schedule_timer(self, logic, key, delay: float,
                       repeat: Optional[float]) -> None:
        if self._scheduler is None or self._self_ref is None:
            raise RuntimeError("timers need an actor-hosted stream")
        tk = (id(logic), key)
        gen = self._timer_gen.get(tk, 0) + 1
        self._timer_gen[tk] = gen
        old = self._timer_tasks.pop(tk, None)
        if old is not None:
            old.cancel()
        ev = _TimerEvent(logic, key, gen)
        if repeat is None:
            task = self._scheduler.schedule_tell_once(delay, self._self_ref, ev)
        else:
            task = self._scheduler.schedule_tell_with_fixed_delay(
                delay, repeat, self._self_ref, ev)
        self._timer_tasks[tk] = task

    def cancel_timer(self, logic, key) -> None:
        tk = (id(logic), key)
        self._timer_gen[tk] = self._timer_gen.get(tk, 0) + 1
        task = self._timer_tasks.pop(tk, None)
        if task is not None:
            task.cancel()

    def _dispatch_timer(self, ev: _TimerEvent) -> None:
        if self._shutdown:
            return
        if self._timer_gen.get((id(ev.logic), ev.key), 0) != ev.gen:
            return  # cancelled/superseded
        try:
            ev.logic.on_timer(ev.key)
        except Exception as e:  # noqa: BLE001
            ev.logic.fail_stage(e)
        self.execute()

    # -- the event loop (reference: execute :348 / processEvent :485) --------
    def execute(self, event_limit: int = 1_000_000) -> None:
        if self._running:
            return  # re-entrant calls drain via the outer loop
        self._running = True
        try:
            n = 0
            while self.queue and n < event_limit:
                kind, c = self.queue.pop(0)
                self._process(kind, c)
                n += 1
        finally:
            self._running = False
        if not self.queue and not self._shutdown and self._all_closed():
            self._do_shutdown()

    def _process(self, kind: str, c: Connection) -> None:  # noqa: C901
        try:
            if kind == "pull":
                if c.out_closed or c.state != "pulled":
                    return
                if c.out_logic._drain_emit(c.outlet):
                    return
                c.out_logic.out_handler(c.outlet).on_pull()
                c.pull_retries = 0
            elif kind == "push":
                if c.in_closed:
                    c.state = "idle"
                    c.element = None
                    return
                c.in_logic.in_handler(c.inlet).on_push()
                # element never grabbed + port now idle is fine: next pull
                # resets state
                if c.state == "grabbed":
                    c.state = "idle"
                if c.pending_complete and not c.in_closed:
                    c.pending_complete = False
                    self.queue.append(("complete", c))
            elif kind == "complete":
                if c.in_closed:
                    return
                if c.state == "pushed":
                    # element still in flight: retry after it lands
                    c.pending_complete = True
                    return
                c.in_closed = True
                c.in_logic.in_handler(c.inlet).on_upstream_finish()
            elif kind == "fail":
                if c.in_closed:
                    return
                c.in_closed = True
                c.in_logic.in_handler(c.inlet).on_upstream_failure(c.failure)
            elif kind == "cancel":
                if c.out_closed:
                    return
                c.out_closed = True
                c.out_logic.out_handler(c.outlet).on_downstream_finish(None)
        except Exception as e:  # noqa: BLE001 — operator threw
            # consult the stage's supervision decider (Attributes
            # supervisionStrategy; Supervision.scala). Element-processing
            # events (push = user fn on an element; pull = source producing
            # one) may resume/restart; lifecycle events always stop.
            failing = c.in_logic if kind in ("push", "complete", "fail") \
                else c.out_logic
            if kind in ("push", "pull") and self._supervise(kind, c, failing, e):
                return
            failing.fail_stage(e)

    def _supervise(self, kind: str, c: Connection, failing, ex) -> bool:
        """Apply the failing stage's supervision decider. Returns True if
        the failure was absorbed (element dropped, stream kept running)."""
        from .attributes import Supervision, effective_decider_of
        try:
            directive = effective_decider_of(failing)(ex)
        except Exception:  # noqa: BLE001 — a throwing decider means stop
            return False
        if directive not in (Supervision.resume, Supervision.restart):
            return False
        if directive == Supervision.restart and \
                failing.restart_state is not None:
            try:
                failing.restart_state()
            except Exception:  # noqa: BLE001 — reset failed: tear down
                return False
        if kind == "push":
            # drop the element; restore the port and the demand so the
            # stream keeps flowing (reference Ops.scala collectors pull
            # after a supervised drop)
            if c.state in ("pushed", "grabbed"):
                c.state = "idle"
                c.element = None
            if c.pending_complete and not c.in_closed:
                # the dropped element was the last one and upstream already
                # completed behind it: deliver the deferred completion (the
                # happy-path re-queue in _process was skipped by the throw)
                c.pending_complete = False
                self.queue.append(("complete", c))
            elif not c.in_closed and not c.out_closed:
                self.pull(failing, c.inlet)
            return True
        # pull: producing the element failed; leave the port pulled and
        # retry (unfoldResource-with-resume semantics: read() is retried).
        # Bounded + mailbox-rescheduled: a source whose on_pull throws
        # deterministically forever under a resuming decider would
        # otherwise spin the event loop hot (the reference cannot reach
        # this state; it does not supervise source pulls, so any bound is
        # stricter than parity requires)
        c.pull_retries += 1
        if c.pull_retries >= MAX_PULL_RETRIES:
            return False

        def requeue(_):
            if c.state == "pulled" and not c.out_closed:
                self.queue.append(("pull", c))
        if self._self_ref is not None:
            # hosted: bounce through the mailbox so async events, timers
            # and cancellations interleave with the retry storm
            self.enqueue_async(failing, requeue, None)
        elif c.state == "pulled" and not c.out_closed:
            self.queue.append(("pull", c))
        return True

    def _all_closed(self) -> bool:
        if any(lg._keep_going for lg in self.logics):
            return False  # setKeepGoing: stage alive past port closure
        return all(c.in_closed and c.out_closed for c in self.connections) \
            if self.connections else True

    def _do_shutdown(self) -> None:
        self._shutdown = True
        for task in self._timer_tasks.values():
            task.cancel()
        self._timer_tasks.clear()
        for lg in self.logics:
            try:
                lg.post_stop()
            except Exception:  # noqa: BLE001
                pass
        if self.on_shutdown is not None:
            self.on_shutdown()

    @property
    def is_completed(self) -> bool:
        return self._shutdown


class IllegalStateException(RuntimeError):
    pass


class ActorGraphInterpreter(Actor):
    """Hosts one interpreter inside an actor: async callbacks, timers, and
    external inputs arrive through the mailbox (reference:
    impl/fusing/ActorGraphInterpreter.scala)."""

    def __init__(self, interpreter: GraphInterpreter):
        super().__init__()
        self.interpreter = interpreter
        interpreter._scheduler = self.context.system.scheduler
        interpreter._self_ref = self.context.self_ref

    def pre_start(self) -> None:
        self.interpreter.init()
        self._maybe_stop()

    def receive(self, message: Any) -> Any:
        if isinstance(message, _AsyncEvent):
            self.interpreter._dispatch_async(message)
        elif isinstance(message, _TimerEvent):
            self.interpreter._dispatch_timer(message)
        else:
            return NotImplemented
        self._maybe_stop()

    def _maybe_stop(self) -> None:
        if self.interpreter.is_completed:
            self.context.stop(self.self_ref)
