"""Sub-stream operators: groupBy, splitWhen/splitAfter, flatMapMerge,
prefixAndTail.

Reference parity: akka-stream's stream-of-streams stages
(impl/fusing/StreamOfStreams.scala — GroupBy, Split, FlattenMerge;
scaladsl/Flow.scala groupBy/splitWhen/flatMapMerge/prefixAndTail). The
architecture differs TPU-host-style: each sub-stream is a queue-fed Source
the consumer materializes as its own interpreter actor (our hubs already
follow this shape), rather than a nested logic inside the parent
interpreter. Demand propagates through the bounded sub-queues.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional

from .ops import SourceQueue, _LinearStage, make_in_handler, make_out_handler
from .stage import (GraphStage, GraphStageLogic, Outlet, SourceShape)


class _PrefedQueueSource(GraphStage):
    """A QueueSource whose SourceQueue exists BEFORE materialization — the
    parent stage feeds it while the consumer decides when (whether) to run
    the sub-source. Offers before materialization buffer in the queue's
    early list."""

    def __init__(self, queue: SourceQueue, buffer_size: int = 1024):
        self.queue = queue
        self.buffer_size = buffer_size
        self.out = Outlet("PrefedQueueSource.out")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic_and_mat(self):
        stage = self
        buf: collections.deque = collections.deque()
        state = {"completing": False}
        size_box = getattr(stage.queue, "size_box", None)

        def dec():
            if size_box is not None:
                size_box[0] -= 1

        class _L(GraphStageLogic):
            def pre_start(self):
                stage.queue._bind(
                    self.get_async_callback(self._on_offer),
                    self.get_async_callback(self._on_done))

            def _on_offer(self, pair):
                elem, fut = pair
                if state["completing"]:
                    fut.set_result(False)
                    return
                if self.is_available(stage.out) and not buf:
                    self.push(stage.out, elem)
                    dec()
                    fut.set_result(True)
                else:
                    # NEVER silently drop a sub-stream element: the parent
                    # throttles its upstream pulls on size_box, so growth
                    # past buffer_size means the parent is mid-flight —
                    # bounded by its in-flight window, not by luck
                    buf.append(elem)
                    fut.set_result(True)

            def _on_done(self, item):
                if item[0] == "fail":
                    self.fail_stage(item[1])
                    return
                state["completing"] = True
                if not buf:
                    self.complete(stage.out)

            def post_stop(self):
                stage.queue._set_closed()

        logic = _L(self._shape)

        def on_pull():
            if buf:
                logic.push(stage.out, buf.popleft())
                dec()
            if state["completing"] and not buf:
                logic.complete(stage.out)

        logic.set_handler(stage.out, make_out_handler(on_pull))
        return logic, None


def _sub_source(queue: SourceQueue, buffer_size: int):
    from .dsl import Source
    return Source.from_graph(
        lambda: _PrefedQueueSource(queue, buffer_size))


def _new_queue() -> SourceQueue:
    q = SourceQueue()
    q.size_box = [0]  # in-flight elements; the parent throttles on this
    return q


def _offer(q: SourceQueue, elem) -> None:
    q.size_box[0] += 1
    q.offer(elem)


_RESUME_POLL = 0.005  # parent re-checks a throttled sub-queue at 200Hz


class GroupBy(_LinearStage):
    """Demultiplex by key: emits (key, Source) ONCE per distinct key; every
    element is offered to its key's sub-queue (StreamOfStreams.scala
    GroupBy). Exceeding max_substreams fails the stage, like the
    reference."""

    def __init__(self, max_substreams: int, key_fn: Callable[[Any], Any],
                 sub_buffer: int = 1024):
        super().__init__("GroupBy")
        self.max_substreams = max_substreams
        self.key_fn = key_fn
        self.sub_buffer = sub_buffer

    def create_logic(self):
        from .ops2 import _TimerLogic
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        stage = self
        queues: Dict[Any, SourceQueue] = {}

        def throttled() -> bool:
            return any(q.size_box[0] >= stage.sub_buffer
                       for q in queues.values())

        def maybe_pull():
            if logic.is_closed(in_) or logic.has_been_pulled(in_):
                return
            if throttled():
                logic.schedule_once("resume", _RESUME_POLL)
            else:
                logic.pull(in_)

        logic._on_timer_fn = lambda key: maybe_pull()

        def on_push():
            elem = logic.grab(in_)
            key = stage.key_fn(elem)
            q = queues.get(key)
            if q is None:
                if len(queues) >= stage.max_substreams:
                    logic.fail_stage(RuntimeError(
                        f"too many substreams (max {stage.max_substreams})"))
                    return
                q = queues[key] = _new_queue()
                _offer(q, elem)
                logic.push(out, (key, _sub_source(q, stage.sub_buffer)))
            else:
                _offer(q, elem)
                maybe_pull()

        def on_finish():
            for q in queues.values():
                q.complete()
            logic.complete_stage()

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(maybe_pull))
        return logic


class SplitWhen(_LinearStage):
    """Start a NEW sub-stream whenever the predicate fires (splitWhen; with
    after=True, the splitting element CLOSES the current sub-stream instead
    — splitAfter). Emits each sub-stream as a Source."""

    def __init__(self, predicate: Callable[[Any], bool], after: bool = False,
                 sub_buffer: int = 1024):
        super().__init__("SplitAfter" if after else "SplitWhen")
        self.predicate = predicate
        self.after = after
        self.sub_buffer = sub_buffer

    def create_logic(self):
        from .ops2 import _TimerLogic
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        stage = self
        current: List[Optional[SourceQueue]] = [None]
        # sub-sources born before downstream pulled again: the parent keeps
        # CONSUMING upstream while an emitted sub-stream is drained — the
        # demand link the reference wires through SubSource/SubSink pairs;
        # bounding pending emissions + sub-queue depth applies the
        # downstream backpressure
        pending: collections.deque = collections.deque()

        def open_sub(first_elem) -> None:
            q = _new_queue()
            current[0] = q
            _offer(q, first_elem)
            src = _sub_source(q, stage.sub_buffer)
            if logic.is_available(out):
                logic.push(out, src)
            else:
                pending.append(src)

        def maybe_pull():
            if logic.is_closed(in_) or logic.has_been_pulled(in_) or \
                    len(pending) > 1:
                return
            q = current[0]
            if q is not None and q.size_box[0] >= stage.sub_buffer:
                logic.schedule_once("resume", _RESUME_POLL)
            else:
                logic.pull(in_)

        logic._on_timer_fn = lambda key: maybe_pull()

        def on_push():
            elem = logic.grab(in_)
            if current[0] is None:
                open_sub(elem)
            elif stage.after:
                _offer(current[0], elem)
                if stage.predicate(elem):
                    current[0].complete()
                    current[0] = None
            elif stage.predicate(elem):
                current[0].complete()
                open_sub(elem)
            else:
                _offer(current[0], elem)
            maybe_pull()

        def on_finish():
            if current[0] is not None:
                current[0].complete()
            if pending:
                logic.emit_multiple(out, list(pending))
                pending.clear()
            logic.complete_stage()

        def on_pull():
            if pending:
                logic.push(out, pending.popleft())
            maybe_pull()

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class FlatMapMerge(_LinearStage):
    """Map each element to a Source and run up to `breadth` of them
    concurrently, merging their outputs as they arrive
    (StreamOfStreams.scala FlattenMerge). Sub-sources materialize as their
    own interpreter actors feeding this stage through async callbacks."""

    def __init__(self, breadth: int, fn: Callable[[Any], Any]):
        super().__init__("FlatMapMerge")
        self.breadth = breadth
        self.fn = fn

    def create_logic(self):
        logic, in_, out = self._logic(), self.in_, self.out
        stage = self
        buf: collections.deque = collections.deque()
        state = {"active": 0, "upstream_done": False}
        switches: set = set()  # live sub-stream kill switches

        def maybe_finish():
            if state["upstream_done"] and state["active"] == 0 and not buf:
                logic.complete_stage()

        def start_sub(src) -> None:
            from .dsl import Keep, Sink
            from .killswitch import KillSwitches
            state["active"] += 1
            on_elem = logic.get_async_callback(sub_elem)
            on_done = logic.get_async_callback(sub_done)
            # a kill switch rides every sub-stream so stage teardown (fail,
            # cancel, system stop) also stops still-running sub-interpreters
            sw, fut = (src.via_mat(KillSwitches.single(), Keep.right)
                       .to(Sink.foreach(lambda e: on_elem.invoke(e)), Keep.both)
                       .run(logic.materializer))
            switches.add(sw)
            fut.add_done_callback(lambda f: on_done.invoke((sw, f)))

        def sub_elem(elem):
            if logic.is_available(out) and not buf:
                logic.push(out, elem)
            else:
                buf.append(elem)

        def sub_done(sw_fut):
            sw, fut = sw_fut
            switches.discard(sw)
            state["active"] -= 1
            exc = fut.exception() if fut is not None else None
            if exc is not None:
                logic.fail_stage(exc)
                return
            if not state["upstream_done"] and state["active"] < stage.breadth \
                    and not logic.has_been_pulled(in_) \
                    and not logic.is_closed(in_):
                logic.pull(in_)
            maybe_finish()

        def on_push():
            src = stage.fn(logic.grab(in_))
            start_sub(src)
            if state["active"] < stage.breadth:
                logic.pull(in_)

        def on_finish():
            state["upstream_done"] = True
            maybe_finish()

        def on_pull():
            if buf:
                logic.push(out, buf.popleft())
                maybe_finish()
            elif not logic.has_been_pulled(in_) and not logic.is_closed(in_) \
                    and state["active"] < stage.breadth:
                logic.pull(in_)
            else:
                maybe_finish()

        def post_stop():
            # stage is going away for ANY reason — kill surviving sub-streams
            for sw in list(switches):
                try:
                    sw.shutdown()
                except Exception:  # noqa: BLE001
                    pass
            switches.clear()

        logic.post_stop = post_stop
        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(on_pull))
        return logic


class PrefixAndTail(_LinearStage):
    """Emit ([first n elements], Source-of-the-rest) once, then complete
    (scaladsl/Flow.scala prefixAndTail)."""

    def __init__(self, n: int, sub_buffer: int = 1024):
        super().__init__("PrefixAndTail")
        self.n = n
        self.sub_buffer = sub_buffer

    def create_logic(self):
        from .ops2 import _TimerLogic
        logic = _TimerLogic(self._shape)
        in_, out = self.in_, self.out
        stage = self
        prefix: List[Any] = []
        tail: List[Optional[SourceQueue]] = [None]

        def tail_pull():
            if logic.is_closed(in_) or logic.has_been_pulled(in_):
                return
            if tail[0] is not None and \
                    tail[0].size_box[0] >= stage.sub_buffer:
                logic.schedule_once("resume", _RESUME_POLL)
            else:
                logic.pull(in_)

        logic._on_timer_fn = lambda key: tail_pull()

        def on_push():
            elem = logic.grab(in_)
            if tail[0] is None:
                prefix.append(elem)
                if len(prefix) >= stage.n:
                    q = _new_queue()
                    tail[0] = q
                    logic.set_keep_going(True)  # outlive the outer cancel
                    logic.push(out, (list(prefix),
                                     _sub_source(q, stage.sub_buffer)))
                    tail_pull()  # tail drain is self-driven
                else:
                    logic.pull(in_)
            else:
                _offer(tail[0], elem)
                tail_pull()

        def on_finish():
            if tail[0] is None:
                # short stream: emit what we have + an empty tail
                q = _new_queue()
                q.complete()
                logic.emit(out, (list(prefix),
                                 _sub_source(q, stage.sub_buffer)))
                logic.complete_stage()
            else:
                tail[0].complete()
                logic.complete_stage()

        def on_downstream_finish(cause=None):
            # the outer stream (typically Sink.head) cancelling must NOT
            # cancel upstream while the tail sub-stream is still live —
            # the tail keeps draining through the queue
            if tail[0] is None:
                logic.cancel_stage(cause)

        logic.set_handler(in_, make_in_handler(on_push, on_finish))
        logic.set_handler(out, make_out_handler(
            lambda: logic.has_been_pulled(in_) or logic.is_closed(in_)
            or logic.pull(in_), on_downstream_finish))
        return logic
