"""TCP as stream stages: Tcp().outgoing_connection / Tcp().bind.

Reference parity: akka-stream/src/main/scala/akka/stream/scaladsl/Tcp.scala
(outgoingConnection :105, bind :210-245, IncomingConnection.handleWith) and
impl/io/TcpStages.scala — here the stages ride the actor-IO layer
(akka_tpu/io/tcp.py, the io/TcpConnection.scala analogue): an adapter actor
registers as the connection handler and feeds the GraphStage through async
callbacks, so the selector loop, write-ack flow control, and close protocol
are shared with the actor API rather than duplicated.

Backpressure: writes are ack-gated (one Write in flight — the stage pulls
upstream only after the connection acks, io/TcpConnection.scala ack
semantics); reads buffer in the stage and are pushed on demand.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from concurrent.futures import Future
from typing import Any, Optional, Tuple

from ..actor.actor import Actor
from ..actor.props import Props
from ..io import tcp as iotcp
from .dsl import Flow, Keep, Materializer, Sink, Source, _Builder
from .stage import (FlowShape, GraphStage, GraphStageLogic, Inlet, Outlet,
                    SourceShape, make_in_handler, make_out_handler)

_counter = itertools.count()

_ACK = object()  # write-ack token (ack-based write flow control)


class OutgoingConnection:
    """Mat value of outgoing_connection (scaladsl Tcp.OutgoingConnection)."""

    def __init__(self, remote_address, local_address):
        self.remote_address = remote_address
        self.local_address = local_address


class ServerBinding:
    """Mat value of bind (scaladsl Tcp.ServerBinding)."""

    def __init__(self, local_address, unbind_fn):
        self.local_address = local_address
        self._unbind = unbind_fn

    def unbind(self) -> None:
        self._unbind()


class IncomingConnection:
    """One accepted connection (scaladsl Tcp.IncomingConnection): carries
    the peer address and a Flow[bytes, bytes] joined to the socket."""

    def __init__(self, system, conn_ref, local_address, remote_address):
        self._system = system
        self._conn_ref = conn_ref
        self.local_address = local_address
        self.remote_address = remote_address

    @property
    def flow(self) -> Flow:
        """Flow whose input is bytes to SEND and output is bytes RECEIVED."""
        system, conn = self._system, self._conn_ref
        return Flow.from_graph(
            lambda: _TcpConnectionStage(system, existing=conn))

    def handle_with(self, handler_flow: Flow, system=None) -> Any:
        """Join the connection to a Flow[received -> to-send] (the
        reference's connection.handleWith): received bytes feed the handler,
        its output is written back. Returns the handler's mat value."""
        system = system or self._system
        conn = self._conn_ref

        def build(b: _Builder):
            logic, _ = b.add(_TcpConnectionStage(self._system, existing=conn))
            o2, m2 = handler_flow._build(b, logic.shape.outlets[0])
            b.connect(o2, logic.shape.inlets[0])
            return m2
        return Materializer(getattr(system, "classic", system)).materialize(build)


class _StreamTcpAdapter(Actor):
    """Forwards every connection message (and its sender) into the stage's
    async-callback queue — the Register handler the stage hides behind."""

    def __init__(self, invoke):
        super().__init__()
        self._invoke = invoke

    def receive(self, message: Any):
        self._invoke((message, self.sender))


class _TcpConnectionStage(GraphStage):
    """FlowShape stage bound to one TCP connection: IN = bytes to send,
    OUT = bytes received (impl/io/TcpStages.scala TcpStreamLogic).

    Two modes: `connect_to` dials a new connection through the Tcp manager;
    `existing` adopts an already-accepted connection ref (server side)."""

    def __init__(self, system, connect_to: Optional[Tuple[str, int]] = None,
                 existing=None, mat_future: Optional[Future] = None):
        self.name = "TcpConnection"
        self.system = system
        self.connect_to = connect_to
        self.existing = existing
        self.mat_future = mat_future
        self.in_ = Inlet("Tcp.in")
        self.out = Outlet("Tcp.out")
        self._shape = FlowShape(self.in_, self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        stage = self
        in_, out = self.in_, self.out
        system = getattr(self.system, "classic", self.system)
        recv: deque = deque()
        st = {"conn": self.existing, "connected": self.existing is not None,
              "await_ack": False, "up_done": False, "read_done": False,
              "closed": False, "adapter": None}

        logic = GraphStageLogic(self._shape)

        def _pump():
            while recv and logic.is_available(out):
                logic.push(out, recv.popleft())
            if st["read_done"] and not recv and not logic.is_closed(out):
                logic.complete(out)
            if st["closed"] and not recv:
                logic.complete_stage()
                return
            # write path: pull upstream once connected and no write pending
            if st["connected"] and not st["await_ack"] and \
                    not st["up_done"] and not logic.has_been_pulled(in_) \
                    and not logic.is_closed(in_):
                logic.pull(in_)

        def _on_event(msg_sender):
            msg, sender = msg_sender
            if isinstance(msg, iotcp.Connected):
                st["conn"] = sender
                st["connected"] = True
                sender.tell(iotcp.Register(st["adapter"],
                                           keep_open_on_peer_closed=True),
                            st["adapter"])
                if stage.mat_future is not None and \
                        not stage.mat_future.done():
                    stage.mat_future.set_result(OutgoingConnection(
                        msg.remote_address, msg.local_address))
                if st["up_done"]:  # upstream already finished pre-connect
                    st["conn"].tell(iotcp.ConfirmedClose(), st["adapter"])
                _pump()
            elif isinstance(msg, iotcp.Received):
                recv.append(msg.data)
                _pump()
            elif msg is _ACK:
                st["await_ack"] = False
                _pump()
            elif isinstance(msg, iotcp.CommandFailed):
                err = ConnectionError(
                    f"TCP command failed: {msg.cmd!r} {msg.cause}")
                if stage.mat_future is not None and \
                        not stage.mat_future.done():
                    stage.mat_future.set_exception(err)
                logic.fail_stage(err)
            elif isinstance(msg, iotcp.ErrorClosed):
                logic.fail_stage(ConnectionError(str(msg)))
            elif isinstance(msg, iotcp.PeerClosed):
                # half-close: the peer stopped WRITING; our write side stays
                # open (Register keep_open_on_peer_closed=True) — only the
                # read side completes after draining
                st["read_done"] = True
                _pump()
            elif isinstance(msg, (iotcp.Closed, iotcp.ConfirmedClosed,
                                  iotcp.Aborted)):
                st["read_done"] = True
                st["closed"] = True
                _pump()

        cb = logic.get_async_callback(_on_event)

        def pre_start():
            st["adapter"] = system.system_actor_of(
                Props.create(_StreamTcpAdapter, cb.invoke),
                f"stream-tcp-{next(_counter)}")
            if stage.existing is not None:
                # adopt the accepted connection: register as its handler
                stage.existing.tell(
                    iotcp.Register(st["adapter"],
                                   keep_open_on_peer_closed=True),
                    st["adapter"])
            else:
                iotcp.Tcp.get(system).manager.tell(
                    iotcp.Connect(stage.connect_to), st["adapter"])
        logic.pre_start = pre_start  # type: ignore[method-assign]

        def post_stop():
            # the stage can die by cancellation/failure, not only by clean
            # upstream finish: close the socket explicitly or the
            # connection actor + selector registration leak until the peer
            # closes (Close flushes pending writes first)
            if st["conn"] is not None and not st["closed"]:
                st["conn"].tell(iotcp.Close(), st["adapter"])
            if st["adapter"] is not None:
                system.stop(st["adapter"])
        logic.post_stop = post_stop  # type: ignore[method-assign]

        def on_push():
            data = logic.grab(in_)
            st["await_ack"] = True
            st["conn"].tell(iotcp.Write(bytes(data), ack=_ACK), st["adapter"])

        def on_up_finish():
            st["up_done"] = True
            if st["connected"]:
                # half-close: flush writes, FIN, keep reading
                # (io/TcpConnection.scala ConfirmedClose)
                st["conn"].tell(iotcp.ConfirmedClose(), st["adapter"])

        logic.set_handler(in_, make_in_handler(on_push, on_up_finish))
        logic.set_handler(out, make_out_handler(_pump))
        return logic


class _TcpBindSource(GraphStage):
    """SourceShape stage emitting IncomingConnection per accepted socket
    (impl/io/TcpStages.scala ConnectionSourceStage)."""

    def __init__(self, system, local_address: Tuple[str, int],
                 backlog: int, mat_future: Future):
        self.name = "TcpBind"
        self.system = system
        self.local_address = local_address
        self.backlog = backlog
        self.mat_future = mat_future
        self.out = Outlet("TcpBind.connections")
        self._shape = SourceShape(self.out)

    @property
    def shape(self):
        return self._shape

    def create_logic(self):
        stage = self
        out = self.out
        system = getattr(self.system, "classic", self.system)
        pending: deque = deque()
        st = {"adapter": None, "listener": None}

        logic = GraphStageLogic(self._shape)

        def _pump():
            while pending and logic.is_available(out):
                logic.push(out, pending.popleft())

        def _on_event(msg_sender):
            msg, sender = msg_sender
            if isinstance(msg, iotcp.Bound):
                st["listener"] = sender
                if not stage.mat_future.done():
                    def unbind():
                        if st["listener"] is not None:
                            st["listener"].tell(iotcp.Unbind(),
                                                st["adapter"])
                    stage.mat_future.set_result(ServerBinding(
                        msg.local_address, unbind))
            elif isinstance(msg, iotcp.Connected):
                pending.append(IncomingConnection(
                    system, sender, msg.local_address, msg.remote_address))
                _pump()
            elif isinstance(msg, iotcp.CommandFailed):
                err = ConnectionError(f"bind failed: {msg.cause}")
                if not stage.mat_future.done():
                    stage.mat_future.set_exception(err)
                logic.fail_stage(err)
            elif isinstance(msg, iotcp.Unbound):
                logic.complete(out)

        cb = logic.get_async_callback(_on_event)

        def pre_start():
            st["adapter"] = system.system_actor_of(
                Props.create(_StreamTcpAdapter, cb.invoke),
                f"stream-tcp-bind-{next(_counter)}")
            iotcp.Tcp.get(system).manager.tell(
                iotcp.Bind(st["adapter"], stage.local_address,
                           stage.backlog), st["adapter"])
        logic.pre_start = pre_start  # type: ignore[method-assign]

        def post_stop():
            if st["listener"] is not None:
                st["listener"].tell(iotcp.Unbind(), st["adapter"])
            if st["adapter"] is not None:
                system.stop(st["adapter"])
        logic.post_stop = post_stop  # type: ignore[method-assign]

        logic.set_handler(out, make_out_handler(_pump))
        return logic


class Tcp:
    """Stream-TCP entry point (scaladsl Tcp extension)."""

    def __init__(self, system):
        self.system = system

    @staticmethod
    def get(system) -> "Tcp":
        return Tcp(system)

    def outgoing_connection(self, host: str, port: int) -> Flow:
        """Flow[bytes -> bytes] over a new connection; mat value is a
        Future[OutgoingConnection] (scaladsl Tcp.outgoingConnection:105)."""
        system = self.system

        def build(b: _Builder, upstream):
            fut: Future = Future()
            logic, _ = b.add(_TcpConnectionStage(
                system, connect_to=(host, port), mat_future=fut))
            b.connect(upstream, logic.shape.inlets[0])
            return logic.shape.outlets[0], fut
        return Flow(build)

    def bind(self, host: str, port: int, backlog: int = 100) -> Source:
        """Source[IncomingConnection]; mat value is Future[ServerBinding]
        (scaladsl Tcp.bind:210-245)."""
        system = self.system

        def build(b: _Builder):
            fut: Future = Future()
            logic, _ = b.add(_TcpBindSource(system, (host, port), backlog,
                                            fut))
            return logic.shape.outlets[0], fut
        return Source(build)
