"""ActorSystem: bootstrap + lifecycle of the whole runtime.

Reference parity: akka-actor/src/main/scala/akka/actor/ActorSystem.scala —
ctor sequence eventStream → scheduler → provider → mailboxes → dispatchers
(:911-956), `_start` runs provider.init (:1013-1031), terminate (:1042),
Settings (:398), extensions loaded at start (:1027), CoordinatedShutdown
phase DAG (actor/CoordinatedShutdown.scala:189,297,366).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ..config import Config, reference_config
from ..dispatch.dispatcher import Dispatchers
from ..dispatch.mailbox import Mailboxes
from ..event.event_stream import EventStream
from ..event.logging import (DEBUG_LEVEL, LogEvent, LoggingAdapter, StdOutLogger,
                             level_for)
from .messages import DeadLetter
from .path import Address
from .props import Props
from .provider import LocalActorRefProvider
from .ref import ActorRef
from .scheduler import Scheduler


class Settings:
    """(reference: ActorSystem.Settings, actor/ActorSystem.scala:398)"""

    def __init__(self, config: Config):
        self.config = config
        self.loglevel = config.get_string("akka.loglevel", "INFO")
        self.stdout_loglevel = config.get_string("akka.stdout-loglevel", "WARNING")
        self.log_dead_letters = config.get_int("akka.log-dead-letters", 10)
        self.debug_receive = config.get_bool("akka.actor.debug.receive")
        self.debug_autoreceive = config.get_bool("akka.actor.debug.autoreceive")
        self.debug_lifecycle = config.get_bool("akka.actor.debug.lifecycle")
        self.debug_event_stream = config.get_bool("akka.actor.debug.event-stream")
        self.debug_unhandled = config.get_bool("akka.actor.debug.unhandled")
        self.serialize_messages = config.get_bool("akka.actor.serialize-messages")
        self.provider_kind = config.get_string("akka.actor.provider", "local")
        self.creation_timeout = config.get_duration("akka.actor.creation-timeout", "20s")


class ExtensionId:
    """Typed singleton plugin per system (reference: actor/Extension.scala)."""

    def create_extension(self, system: "ActorSystem") -> Any:
        raise NotImplementedError

    def apply(self, system: "ActorSystem") -> Any:
        return system.register_extension(self)

    __call__ = apply


class CoordinatedShutdown:
    """Ordered, config-defined phase DAG for graceful shutdown
    (reference: actor/CoordinatedShutdown.scala:189,297,366)."""

    PHASE_BEFORE_SERVICE_UNBIND = "before-service-unbind"
    PHASE_SERVICE_UNBIND = "service-unbind"
    PHASE_SERVICE_REQUESTS_DONE = "service-requests-done"
    PHASE_SERVICE_STOP = "service-stop"
    PHASE_BEFORE_CLUSTER_SHUTDOWN = "before-cluster-shutdown"
    PHASE_CLUSTER_SHARDING_SHUTDOWN_REGION = "cluster-sharding-shutdown-region"
    PHASE_CLUSTER_LEAVE = "cluster-leave"
    PHASE_CLUSTER_EXITING = "cluster-exiting"
    PHASE_CLUSTER_EXITING_DONE = "cluster-exiting-done"
    PHASE_CLUSTER_SHUTDOWN = "cluster-shutdown"
    PHASE_BEFORE_ACTOR_SYSTEM_TERMINATE = "before-actor-system-terminate"
    PHASE_ACTOR_SYSTEM_TERMINATE = "actor-system-terminate"

    def __init__(self, system: "ActorSystem"):
        self.system = system
        cfg = system.settings.config.get_config("akka.coordinated-shutdown")
        self.default_timeout = cfg.get_duration("default-phase-timeout", "5s")
        self._phases: Dict[str, list] = {name: [] for name in cfg.keys("phases")}
        self._order = self._topo_sort(cfg.get("phases", {}))
        self._run_started = threading.Event()
        self._lock = threading.Lock()

    @staticmethod
    def _topo_sort(phases: dict) -> list:
        order, seen = [], set()

        def visit(name: str, stack: tuple):
            if name in seen:
                return
            if name in stack:
                raise ValueError(f"cycle in coordinated-shutdown phases at {name}")
            for dep in phases.get(name, {}).get("depends-on", []):
                visit(dep, stack + (name,))
            seen.add(name)
            order.append(name)

        for name in phases:
            visit(name, ())
        return order

    def add_task(self, phase: str, name: str, task: Callable[[], Any]) -> None:
        with self._lock:
            self._phases.setdefault(phase, []).append((name, task))

    def run(self, reason: str = "unknown") -> None:
        if self._run_started.is_set():
            return
        self._run_started.set()
        for phase in self._order:
            for name, task in list(self._phases.get(phase, [])):
                try:
                    task()
                except Exception as e:  # noqa: BLE001
                    self.system.log.warning(
                        f"coordinated shutdown task [{name}] in phase [{phase}] failed: {e!r}")


class ActorSystem:
    """Create with `ActorSystem.create(name, config_overrides)`."""

    _global_count = 0

    def __init__(self, name: str, config: Optional[Config | dict] = None):
        if isinstance(config, dict):
            config = Config(config)
        self.name = name
        self.settings = Settings((config or Config()).with_fallback(reference_config()))
        cfg = self.settings.config

        self.event_stream = EventStream(debug=self.settings.debug_event_stream)
        self._stdout_logger = StdOutLogger(level_for(self.settings.stdout_loglevel))
        self.event_stream.attach_tap(self._stdout_filtered)

        # flight recorder: runtime-selected tracing SPI, noop by default
        # (JFRActorFlightRecorder selection parity, SURVEY.md §2.10 item 9)
        from ..event.flight_recorder import from_config as _fr_from_config
        self.flight_recorder = _fr_from_config(cfg)

        # metrics registry: the other half of the telemetry plane
        # (event/metrics.py) — None unless akka.metrics.enabled; the
        # tpu-batched dispatcher wires its device slab and stats
        # collectors into it (docs/OBSERVABILITY.md)
        from ..event.metrics import from_config as _metrics_from_config
        self.metrics_registry = _metrics_from_config(cfg)

        # causal tracing: sampled request->wave->step spans (event/
        # tracing.py) — None unless akka.tracing.enabled; the gateway
        # picks it up from the system and threads it through the serving
        # path (docs/OBSERVABILITY.md tracing section)
        from ..event.tracing import from_config as _tracer_from_config
        self.tracer = _tracer_from_config(cfg)
        if self.tracer is not None and self.metrics_registry is not None \
                and self.tracer.step_fn is None:
            # default step source: the registry's shared ATT_STEP axis
            self.tracer.step_fn = lambda: self.metrics_registry.step

        # multi-host data plane: opt-in jax.distributed bootstrap (DCN) so
        # device meshes span every process in the cluster (SURVEY.md §2.3
        # TPU-native equivalent; akka.jax-distributed.* config)
        if cfg.get_bool("akka.jax-distributed.enabled", False):
            from ..parallel.mesh import \
                maybe_initialize_distributed_from_config
            maybe_initialize_distributed_from_config(cfg)

        sched_impl = cfg.get_string("akka.scheduler.implementation", "default")
        self.scheduler = None
        if sched_impl == "native":
            # the C++ hashed-wheel (LightArrayRevolverScheduler parity);
            # silently falls back when no compiler is available
            try:
                from ..native.integration import NativeScheduler
                self.scheduler = NativeScheduler(
                    tick_duration=cfg.get_duration(
                        "akka.scheduler.tick-duration", "10ms"),
                    ticks_per_wheel=cfg.get_int(
                        "akka.scheduler.ticks-per-wheel", 512))
            except Exception:  # noqa: BLE001
                self.scheduler = None
        if self.scheduler is None:
            self.scheduler = Scheduler(
                tick_duration=cfg.get_duration("akka.scheduler.tick-duration", "10ms"),
                ticks_per_wheel=cfg.get_int("akka.scheduler.ticks-per-wheel", 512),
                name=f"akka-tpu-scheduler-{name}")

        self.dispatchers = Dispatchers(self.settings, self)
        # register the flagship TPU dispatcher type (extension seam per
        # BASELINE.json north star; reference: dispatch/Dispatchers.scala:235-259)
        try:
            from ..dispatch.batched import register_tpu_dispatcher_type
            register_tpu_dispatcher_type(self.dispatchers)
        except ImportError:  # jax unavailable in minimal envs; host path still works
            pass
        self.mailboxes = Mailboxes(self.settings, self.event_stream)
        if cfg.get_bool("akka.actor.native-mailboxes"):
            try:
                from ..native.integration import register_native_mailbox
                register_native_mailbox(self.mailboxes)
            except Exception:  # noqa: BLE001 — no compiler: python queues only
                pass

        provider_kind = self.settings.provider_kind
        if provider_kind in ("remote", "cluster"):
            from ..remote.provider import RemoteActorRefProvider
            self.provider = RemoteActorRefProvider(name, self.settings, self.event_stream)
        else:
            self.provider = LocalActorRefProvider(name, self.settings, self.event_stream)

        self.dead_letters = self.provider.dead_letters
        self.log = LoggingAdapter(self.event_stream, f"ActorSystem({name})",
                                  level=level_for(self.settings.loglevel))
        self._extensions: Dict[Any, Any] = {}
        self._ext_lock = threading.RLock()
        self._terminated = threading.Event()
        self._termination_callbacks: list[Callable[[], None]] = []
        self.start_time = time.time()

        self.provider.init(self)
        self.coordinated_shutdown = CoordinatedShutdown(self)
        self.coordinated_shutdown.add_task(
            CoordinatedShutdown.PHASE_ACTOR_SYSTEM_TERMINATE, "terminate-system",
            self._terminate_guardians)
        self._dead_letter_count = 0
        if self.settings.log_dead_letters:
            self.event_stream.subscribe(self._on_dead_letter, DeadLetter)

        if provider_kind in ("remote", "cluster"):
            self.provider.post_init(self)

    # -- factory -------------------------------------------------------------
    @staticmethod
    def create(name: str = "default", config: Optional[Config | dict] = None) -> "ActorSystem":
        return ActorSystem(name, config)

    # -- logging taps ---------------------------------------------------------
    def _stdout_filtered(self, event: Any) -> None:
        if isinstance(event, LogEvent):
            self._stdout_logger(event)

    def _on_dead_letter(self, event: DeadLetter) -> None:
        self._dead_letter_count += 1
        n = self.settings.log_dead_letters
        if self._dead_letter_count <= n:
            suffix = " (further dead letters will not be logged)" if self._dead_letter_count == n else ""
            self.log.info(
                f"Message [{type(event.message).__name__}] to {event.recipient} was not "
                f"delivered. [{self._dead_letter_count}] dead letters encountered{suffix}.")

    # -- actor factory surface (reference: ActorSystem.actorOf :886-887) ------
    def actor_of(self, props: Props, name: Optional[str] = None) -> ActorRef:
        return self.provider.guardian.cell.actor_of(props, name)

    spawn = actor_of

    def system_actor_of(self, props: Props, name: Optional[str] = None) -> ActorRef:
        return self.provider.system_guardian.cell.actor_of(props, name)

    def stop(self, ref: ActorRef) -> None:
        ref.stop()

    def actor_selection(self, path: str) -> ActorRef:
        return self.provider.resolve_actor_ref(path)

    @property
    def address(self) -> Address:
        return self.provider.default_address

    # -- extensions ------------------------------------------------------------
    def register_extension(self, ext_id: ExtensionId) -> Any:
        with self._ext_lock:
            key = type(ext_id) if not isinstance(ext_id, type) else ext_id
            if key not in self._extensions:
                self._extensions[key] = ext_id.create_extension(self)
            return self._extensions[key]

    def has_extension(self, ext_id: Any) -> bool:
        key = type(ext_id) if not isinstance(ext_id, type) else ext_id
        return key in self._extensions

    # -- termination ------------------------------------------------------------
    def terminate(self) -> None:
        threading.Thread(target=self.coordinated_shutdown.run,
                         args=("terminate",), daemon=True,
                         name=f"akka-tpu-shutdown-{self.name}").start()

    def _terminate_guardians(self) -> None:
        self.provider.guardian.stop()
        # root guardian stop cascades via provider.actor_terminated

    def _finish_terminate(self) -> None:
        self.dispatchers.shutdown()
        self.scheduler.shutdown()
        self.flight_recorder.close()
        if self.metrics_registry is not None:
            self.metrics_registry.close()
        if self.tracer is not None:
            self.tracer.close()
        self._terminated.set()
        for cb in self._termination_callbacks:
            try:
                cb()
            except Exception:  # noqa: BLE001
                pass

    def register_on_termination(self, cb: Callable[[], None]) -> None:
        if self._terminated.is_set():
            cb()
        else:
            self._termination_callbacks.append(cb)

    def await_termination(self, timeout: Optional[float] = None) -> bool:
        return self._terminated.wait(timeout)

    @property
    def when_terminated(self) -> threading.Event:
        return self._terminated

    @property
    def is_terminated(self) -> bool:
        return self._terminated.is_set()

    def __enter__(self) -> "ActorSystem":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
        self.await_termination(10.0)

    def __repr__(self) -> str:
        return f"ActorSystem({self.name})"
