"""ActorCell: per-actor execution context.

Reference parity: akka-actor/src/main/scala/akka/actor/ActorCell.scala —
`invoke` (:539-555), `systemInvoke` (:471-536), become/unbecome (:589-602),
`newActor` (:609-627) — plus the dungeon traits it mixes in:
Dispatch (actor/dungeon/Dispatch.scala: mailbox init :63-100, sendMessage :153-160),
FaultHandling (actor/dungeon/FaultHandling.scala), DeathWatch
(actor/dungeon/DeathWatch.scala:25,81), Children, ReceiveTimeout.

The cell doubles as the user-facing ActorContext (as in the reference, where
ActorCell extends ActorContext, actor/ActorCell.scala:49).
"""

from __future__ import annotations

import contextvars
import itertools
import threading
from typing import Any, Callable, Dict, Optional

from . import messages as msgs
from .messages import (ActorInitializationException, ActorKilledException,
                       DeathPactException, InvalidActorNameException, Terminated,
                       UnhandledMessage)
from .path import ActorPath, new_uid, validate_path_element
from .props import Props
from .ref import ActorRef, InternalActorRef, LocalActorRef, Nobody
from .supervision import ChildRestartStats, default_strategy
from ..dispatch import sysmsg
from ..dispatch.mailbox import Envelope

# the cell under construction, so Actor.__init__ can grab its context
# (reference: ActorCell.contextStack ThreadLocal)
_current_cell: contextvars.ContextVar = contextvars.ContextVar("akka_tpu_current_cell", default=None)


def current_cell():
    return _current_cell.get()


class ActorCell:
    _temp_counter = itertools.count()

    def __init__(self, system, self_ref: LocalActorRef, props: Props,
                 dispatcher_id: Optional[str], parent: Optional[InternalActorRef]):
        self.system = system
        self.self_ref = self_ref
        self.props = props
        self.parent = parent
        self.dispatcher = system.dispatchers.lookup(
            dispatcher_id or props.dispatcher or system.dispatchers.DEFAULT_DISPATCHER_ID)
        self.mailbox = None
        self.actor = None
        self._behavior_stack: list[Callable[[Any], Any]] = []
        self._children: Dict[str, InternalActorRef] = {}
        self._child_stats: Dict[str, ChildRestartStats] = {}
        # remote-deployed children: named like children (uniqueness, lookup,
        # stop-on-terminate) but NOT awaited during termination — their cell
        # lives on another node (remote/deploy.py daemon owns supervision)
        self._remote_children: Dict[str, InternalActorRef] = {}
        self._children_lock = threading.RLock()
        self.current_message: Optional[Envelope] = None
        self.sender: Optional[ActorRef] = None
        self._watching: Dict[ActorRef, Any] = {}     # ref -> custom Terminated-replacement or None
        self._watched_by: set = set()
        self._terminating = False
        self._terminated = False
        self._failed_perpetrator: Optional[ActorRef] = None
        self._failure_cause: Optional[BaseException] = None
        self._pending_recreate_cause: Optional[BaseException] = None
        self._pending_recreate_wait: set = set()
        self.uid = self_ref.path.uid
        self.receive_timeout: Optional[float] = None
        self._receive_timeout_task = None
        self.stash_capacity = -1

    # ------------------------------------------------------------------ init
    def init(self, send_supervise: bool, mailbox_type) -> None:
        """Create mailbox + enqueue Create (reference: dungeon/Dispatch.scala:63-100)."""
        self.mailbox = self.dispatcher.create_mailbox(self, mailbox_type)
        self.mailbox.actor = self
        self.mailbox.system_enqueue(self.self_ref, sysmsg.Create())
        if send_supervise and self.parent is not None:
            self.parent.send_system_message(sysmsg.Supervise(child=self.self_ref))

    def start(self) -> None:
        self.dispatcher.attach(self)

    def swap_mailbox(self, new):
        old = self.mailbox
        self.mailbox = new
        return old

    # ----------------------------------------------------------- ctx surface
    @property
    def context(self) -> "ActorCell":
        return self

    @property
    def self_(self) -> ActorRef:
        return self.self_ref

    @property
    def children(self):
        return list(self._children.values())

    def child(self, name: str) -> Optional[InternalActorRef]:
        c = self._children.get(name)
        return c if c is not None else self._remote_children.get(name)

    def get_single_child(self, name: str) -> Optional[InternalActorRef]:
        if "#" in name:
            name, uid_s = name.split("#", 1)
            child = self._children.get(name)
            if child is not None and child.path.uid == int(uid_s):
                return child
            # remote-deployed children's paths carry no uid; a selection to
            # the logical /user path must still resolve (the reference's
            # children container holds the RemoteActorRef, so getChild finds
            # it; skipping the uid check mirrors that)
            return self._remote_children.get(name)
        child = self._children.get(name)
        return child if child is not None else self._remote_children.get(name)

    def actor_of(self, props: Props, name: Optional[str] = None) -> ActorRef:
        """Spawn a child (reference: dungeon/Children.attachChild →
        provider.actorOf, actor/ActorRefProvider.scala:116)."""
        if self._terminating or self._terminated:
            raise msgs.IllegalActorStateException(f"cannot create children while terminating: {self.self_ref}")
        with self._children_lock:
            if name is None:
                name = f"$" + _base64(next(self._temp_counter))
            else:
                validate_path_element(name)
            if name in self._children or name in self._remote_children:
                raise InvalidActorNameException(
                    f"actor name [{name}] is not unique in {self.self_ref.path}")
            child = self.system.provider.actor_of(
                self.system, props, self.self_ref, self.self_ref.path.child(name).with_uid(new_uid()))
            if getattr(child, "is_local", True):
                self._children[name] = child
                self._child_stats[name] = ChildRestartStats(child)
            else:
                # remote-deployed — it lives under the remote daemon, which
                # watches this parent and stops the child when we die
                # (remote/deploy.py; no local sysmsg channel exists for it),
                # but it keeps its name here for uniqueness + child() lookup.
                # Watch it (internal, NOT via self._watching, so the user
                # never sees a Terminated they didn't ask for) so the entry
                # is pruned when the remote child dies — otherwise the name
                # stays reserved forever and the dict grows unboundedly
                # under routee churn.
                self._remote_children[name] = child
                child.send_system_message(
                    sysmsg.Watch(watchee=child, watcher=self.self_ref))
        child.start()
        return child

    spawn = actor_of

    def stop(self, ref: Optional[ActorRef] = None) -> None:
        """Stop self or a child (reference: ActorCell.stop)."""
        target = ref if ref is not None else self.self_ref
        if isinstance(target, InternalActorRef):
            target.send_system_message(sysmsg.Terminate())

    def become(self, behavior: Callable[[Any], Any], discard_old: bool = True) -> None:
        """(reference: ActorCell.become :589-602)"""
        if discard_old and self._behavior_stack:
            self._behavior_stack.pop()
        self._behavior_stack.append(behavior)

    def unbecome(self) -> None:
        if len(self._behavior_stack) > 1:
            self._behavior_stack.pop()

    def watch(self, ref: ActorRef, message: Any = None) -> ActorRef:
        """DeathWatch (reference: dungeon/DeathWatch.scala:25); `message`
        implements watchWith."""
        if ref != self.self_ref and ref not in self._watching:
            self._watching[ref] = message
            if isinstance(ref, InternalActorRef):
                ref.send_system_message(sysmsg.Watch(watchee=ref, watcher=self.self_ref))
        elif ref in self._watching:
            self._watching[ref] = message
        return ref

    def unwatch(self, ref: ActorRef) -> ActorRef:
        if ref in self._watching:
            del self._watching[ref]
            if isinstance(ref, InternalActorRef):
                ref.send_system_message(sysmsg.Unwatch(watchee=ref, watcher=self.self_ref))
        return ref

    def set_receive_timeout(self, timeout: Optional[float]) -> None:
        """(reference: dungeon/ReceiveTimeout.scala)"""
        self.receive_timeout = timeout if timeout and timeout > 0 else None
        self._reschedule_receive_timeout()

    def _reschedule_receive_timeout(self) -> None:
        if self._receive_timeout_task is not None:
            self._receive_timeout_task.cancel()
            self._receive_timeout_task = None
        if self.receive_timeout is not None and not self._terminated:
            self._receive_timeout_task = self.system.scheduler.schedule_once(
                self.receive_timeout,
                lambda: self.self_ref.tell(msgs.ReceiveTimeout, self.self_ref))

    # -------------------------------------------------------------- dispatch
    def send_message(self, envelope: Envelope) -> None:
        if self.mailbox is None or self._terminated:
            self.system.dead_letters.tell(
                msgs.DeadLetter(envelope.message, envelope.sender, self.self_ref), envelope.sender)
            return
        self.dispatcher.dispatch(self, envelope)

    def send_system_message(self, message: sysmsg.SystemMessage) -> None:
        if self.mailbox is None or self._terminated:
            self._system_message_post_mortem(message)
            return
        self.dispatcher.system_dispatch(self, message)

    def _system_message_post_mortem(self, message: sysmsg.SystemMessage) -> None:
        """System messages to an already-dead cell (reference: the
        deadLetterMailbox special-casing in dispatch/Mailbox.scala:445-465)."""
        if isinstance(message, sysmsg.Watch):
            if message.watcher is not None and message.watcher != self.self_ref:
                message.watcher.send_system_message(
                    sysmsg.DeathWatchNotification(self.self_ref, existence_confirmed=True))
        elif isinstance(message, (sysmsg.Unwatch, sysmsg.Terminate,
                                  sysmsg.DeathWatchNotification, sysmsg.Failed)):
            pass
        else:
            self.system.dead_letters.tell(
                msgs.DeadLetter(message, self.self_ref, self.self_ref), self.self_ref)

    @property
    def is_terminated(self) -> bool:
        return self._terminated

    @property
    def is_terminating(self) -> bool:
        return self._terminating

    # ------------------------------------------------------------ system path
    def system_invoke(self, message: sysmsg.SystemMessage) -> None:
        """(reference: ActorCell.systemInvoke :471-536)"""
        try:
            if isinstance(message, sysmsg.Create):
                self._create(message.failure)
            elif isinstance(message, sysmsg.Recreate):
                self._fault_recreate(message.cause)
            elif isinstance(message, sysmsg.Suspend):
                self._fault_suspend()
            elif isinstance(message, sysmsg.Resume):
                self._fault_resume(message.caused_by_failure)
            elif isinstance(message, sysmsg.Terminate):
                self._terminate()
            elif isinstance(message, sysmsg.Supervise):
                self._supervise(message.child)
            elif isinstance(message, sysmsg.Watch):
                self._add_watcher(message.watchee, message.watcher)
            elif isinstance(message, sysmsg.Unwatch):
                self._rem_watcher(message.watchee, message.watcher)
            elif isinstance(message, sysmsg.Failed):
                self._handle_failed(message)
            elif isinstance(message, sysmsg.DeathWatchNotification):
                self._watched_actor_terminated(message.actor, message.existence_confirmed,
                                               message.address_terminated, message.cause)
            elif isinstance(message, sysmsg.NoMessage):
                pass
        except Exception as e:  # noqa: BLE001 — supervision boundary
            self.handle_invoke_failure(e)

    def _create(self, failure: Optional[BaseException]) -> None:
        """(reference: ActorCell.create :629-664)"""
        if failure is not None:
            raise failure
        try:
            token = _current_cell.set(self)
            try:
                instance = self.props.new_actor()
            finally:
                _current_cell.reset(token)
            if instance is None:
                raise ActorInitializationException(self.self_ref, "Actor instance is None")
            self.actor = instance
            if not hasattr(instance, "_cell") or instance._cell is None:
                instance._cell = self
            if not self._behavior_stack:
                self._behavior_stack = [instance.receive]
            instance.pre_start()
            _fr = self.system.flight_recorder
            if _fr.enabled:
                _fr.actor_spawned(str(self.self_ref.path))
            if self.system.settings.debug_lifecycle:
                self._log_debug("started")
        except ActorInitializationException:
            raise
        except Exception as e:  # noqa: BLE001
            raise ActorInitializationException(
                self.self_ref, f"exception during creation: {e!r}", e) from e

    def _supervise(self, child: ActorRef) -> None:
        if not self._terminating and child.path.name not in self._children:
            # child created via provider directly (e.g. guardians)
            self._children[child.path.name] = child
            self._child_stats[child.path.name] = ChildRestartStats(child)

    # -- fault handling (reference: actor/dungeon/FaultHandling.scala) -------
    def handle_invoke_failure(self, cause: BaseException) -> None:
        if self._failed_perpetrator is not None:
            return
        self._failed_perpetrator = self.self_ref
        self._failure_cause = cause
        _fr = self.system.flight_recorder
        if _fr.enabled:
            _fr.actor_failed(str(self.self_ref.path), repr(cause))
        try:
            self.suspend_self_and_children()
            if self.parent is not None:
                self.parent.send_system_message(
                    sysmsg.Failed(child=self.self_ref, cause=cause, uid=self.uid))
            else:
                # root guardian failure: log + stop
                self._log_error(cause, "root-level failure; stopping")
                self.stop()
        except Exception:  # noqa: BLE001 pragma: no cover
            self.stop()

    def suspend_self_and_children(self) -> None:
        self.mailbox.suspend()
        for child in self.children:
            if isinstance(child, InternalActorRef):
                child.suspend()

    def suspend(self) -> None:
        self.send_system_message(sysmsg.Suspend())

    def resume(self, caused_by_failure: Optional[BaseException] = None) -> None:
        self.send_system_message(sysmsg.Resume(caused_by_failure=caused_by_failure))

    def restart(self, cause: Optional[BaseException] = None) -> None:
        self.send_system_message(sysmsg.Recreate(cause=cause))

    def _fault_suspend(self) -> None:
        self.mailbox.suspend()
        for child in self.children:
            if isinstance(child, InternalActorRef):
                child.suspend()

    def _fault_resume(self, caused_by_failure: Optional[BaseException]) -> None:
        if caused_by_failure is not None:
            self._failed_perpetrator = None
            self._failure_cause = None
        if self.mailbox.resume():
            for child in self.children:
                if isinstance(child, InternalActorRef):
                    child.resume(caused_by_failure=None)
        self.dispatcher.register_for_execution(self.mailbox, False, False)

    def _handle_failed(self, f: sysmsg.Failed) -> None:
        """Parent-side supervision decision (reference: FaultHandling.handleFailure)."""
        child = f.child
        stats = self._child_stats.get(child.path.name)
        if stats is None or stats.child != child:
            return  # stale
        strategy = self._strategy()
        handled = strategy.handle_failure(self, child, f.cause, stats,
                                          list(self._child_stats.values()))
        if not handled:
            # escalate: we fail ourselves with the child's cause
            raise f.cause if f.cause is not None else RuntimeError("escalated failure")

    def _strategy(self):
        if self.actor is not None:
            s = getattr(self.actor, "supervisor_strategy", None)
            if s is not None:
                return s
        return default_strategy()

    def _fault_recreate(self, cause: Optional[BaseException]) -> None:
        """(reference: FaultHandling.faultRecreate)"""
        if self.actor is None:
            self._create(None)
            self._fault_resume(cause)
            return
        if self._terminating:
            return
        failed_actor = self.actor
        try:
            failed_actor.pre_restart(cause, self.current_message.message if self.current_message else None)
        except Exception as e:  # noqa: BLE001
            self._log_error(e, "exception in pre_restart")
        # wait only for children that are actually terminating (the default
        # pre_restart stops them all, but a user pre_restart may keep children
        # alive — reference: faultRecreate waits for ChildrenContainer.Termination
        # entries only, not all children)
        stopping = {name for name, child in self._children.items()
                    if self._child_is_terminating(child)}
        if stopping:
            self._pending_recreate_cause = cause if cause is not None else RuntimeError("restart")
            self._pending_recreate_wait = stopping
        else:
            self._finish_recreate(cause)

    @staticmethod
    def _child_is_terminating(child) -> bool:
        cell = getattr(child, "cell", None)
        if cell is None:
            return False
        return cell._terminating or cell._terminated

    def _finish_recreate(self, cause: Optional[BaseException]) -> None:
        self._failed_perpetrator = None
        self._failure_cause = None
        self._pending_recreate_cause = None
        self._pending_recreate_wait = set()
        try:
            token = _current_cell.set(self)
            try:
                fresh = self.props.new_actor()
            finally:
                _current_cell.reset(token)
            self.actor = fresh
            fresh._cell = self
            self._behavior_stack = [fresh.receive]
            fresh.post_restart(cause)
            _fr = self.system.flight_recorder
            if _fr.enabled:
                _fr.actor_restarted(str(self.self_ref.path), repr(cause))
            if self.system.settings.debug_lifecycle:
                self._log_debug("restarted")
            if self.mailbox.resume():
                for child in self.children:
                    if isinstance(child, InternalActorRef):
                        child.resume(caused_by_failure=None)
            self.dispatcher.register_for_execution(self.mailbox, False, False)
        except Exception as e:  # noqa: BLE001
            self.actor = None
            self.handle_invoke_failure(
                msgs.PostRestartException(self.self_ref, f"exception post restart: {e!r}", e))

    # -- termination (reference: FaultHandling.terminate/finishTerminate) ----
    def _terminate(self) -> None:
        if self._terminated:
            return
        self.set_receive_timeout(None)
        if not self._terminating:
            self._terminating = True
            # remote-deployed children: fire-and-forget stop (their daemon
            # also watches us, so this is belt-and-braces, not awaited)
            for rc in list(self._remote_children.values()):
                rc.stop()
            self._remote_children.clear()
            children = self.children
            if children:
                for child in children:
                    if isinstance(child, InternalActorRef):
                        child.stop()
                # do not process user messages while waiting for children; the
                # reference suspends here (dungeon/FaultHandling.terminate) so
                # the children's DeathWatchNotifications can still arrive
                self.mailbox.suspend()
            else:
                self._finish_terminate()
        elif not self._children:
            self._finish_terminate()

    def _finish_terminate(self) -> None:
        if self._terminated:
            return
        self._terminated = True
        self._terminating = True
        _fr = self.system.flight_recorder
        if _fr.enabled:
            _fr.actor_stopped(str(self.self_ref.path))
        actor = self.actor
        try:
            if actor is not None:
                actor.post_stop()
        except Exception as e:  # noqa: BLE001
            self._log_error(e, "exception in post_stop")
        finally:
            self.mailbox.become_closed()
            self.mailbox.clean_up()
            self.dispatcher.detach(self)
            # unwatch everything we watch
            for ref in list(self._watching):
                if isinstance(ref, InternalActorRef):
                    ref.send_system_message(sysmsg.Unwatch(watchee=ref, watcher=self.self_ref))
            self._watching.clear()
            # notify watchers + parent (cause propagates failure deaths
            # into typed ChildFailed signals)
            for watcher in list(self._watched_by):
                watcher.send_system_message(
                    sysmsg.DeathWatchNotification(self.self_ref, existence_confirmed=True,
                                                  cause=self._failure_cause))
            self._watched_by.clear()
            if self.parent is not None:
                self.parent.send_system_message(
                    sysmsg.DeathWatchNotification(self.self_ref, existence_confirmed=True,
                                                  cause=self._failure_cause))
            self.actor = None
            if self.system.settings.debug_lifecycle:
                self._log_debug("stopped")
            self.system.provider.actor_terminated(self.self_ref)

    # -- deathwatch plumbing -------------------------------------------------
    def _add_watcher(self, watchee: ActorRef, watcher: ActorRef) -> None:
        if watchee == self.self_ref and watcher != self.self_ref:
            if self._terminated:
                watcher.send_system_message(
                    sysmsg.DeathWatchNotification(self.self_ref, existence_confirmed=True))
            else:
                self._watched_by.add(watcher)

    def _rem_watcher(self, watchee: ActorRef, watcher: ActorRef) -> None:
        if watchee == self.self_ref:
            self._watched_by.discard(watcher)

    def _watched_actor_terminated(self, actor: ActorRef, existence_confirmed: bool,
                                  address_terminated: bool,
                                  cause: Optional[BaseException] = None) -> None:
        """(reference: dungeon/DeathWatch.watchedActorTerminated :81)"""
        name = actor.path.name
        # remote-deployed child died: free its LOCAL name (the internal watch
        # placed at spawn; mirrors how local children leave _children). The
        # remote ref's path name is the daemon-side mangled name, so match by
        # path value, lenient on uid like _find_watched.
        from .path import undefined_uid
        for rname, rref in list(self._remote_children.items()):
            if rref.path == actor.path or (
                    rref.path.address == actor.path.address
                    and rref.path.elements == actor.path.elements
                    and (rref.path.uid == undefined_uid
                         or actor.path.uid == undefined_uid)):
                with self._children_lock:
                    self._remote_children.pop(rname, None)
                break
        is_child = self._children.get(name) == actor
        if is_child:
            with self._children_lock:
                self._children.pop(name, None)
                self._child_stats.pop(name, None)
            if self.actor is not None:
                self._strategy().handle_child_terminated(self, actor, self.children)
            self._pending_recreate_wait.discard(name)
            if self._pending_recreate_cause is not None and not self._pending_recreate_wait:
                self._finish_recreate(self._pending_recreate_cause)
            elif self._terminating and not self._children:
                self._finish_terminate()
        watched_key = self._find_watched(actor)
        if watched_key is not None:
            custom = self._watching.pop(watched_key)
            if not self._terminating and not self._terminated:
                message = custom if custom is not None else Terminated(
                    watched_key, existence_confirmed, address_terminated, cause)
                # delivered as a normal user message, bypassing the closed check
                self._invoke_terminated(Envelope(message, watched_key))

    def _find_watched(self, actor: ActorRef) -> Optional[ActorRef]:
        """Exact (path+uid) match first; else a path match where either side
        lacks a uid — a remote watch resolved without uid must still match the
        uid-carrying ref inside an inbound DeathWatchNotification."""
        if actor in self._watching:
            return actor
        from .path import undefined_uid
        for key in self._watching:
            if key.path == actor.path and (
                    key.path.uid == undefined_uid
                    or actor.path.uid == undefined_uid
                    or key.path.uid == actor.path.uid):
                return key
        return None

    def _invoke_terminated(self, envelope: Envelope) -> None:
        # Terminated must reach the actor even while mailbox is suspended;
        # enqueue through the dispatcher like any message.
        self.dispatcher.dispatch(self, envelope)

    # --------------------------------------------------------------- invoke
    def invoke(self, envelope: Envelope) -> None:
        """(reference: ActorCell.invoke :539-555)"""
        if self._terminated:
            self.system.dead_letters.tell(
                msgs.DeadLetter(envelope.message, envelope.sender, self.self_ref), envelope.sender)
            return
        self.current_message = envelope
        self.sender = envelope.sender if envelope.sender is not None else self.system.dead_letters
        msg = envelope.message
        try:
            # re-arm on every message, including ReceiveTimeout itself, so the
            # timeout keeps firing while the actor stays idle (reference:
            # dungeon/ReceiveTimeout re-arms after delivery)
            if self.receive_timeout is not None:
                self._reschedule_receive_timeout()
            if isinstance(msg, msgs.AutoReceivedMessage):
                self._auto_receive_message(envelope)
            else:
                self.receive_message(msg)
        except Exception as e:  # noqa: BLE001 — the supervision boundary
            self.handle_invoke_failure(e)
        finally:
            self.current_message = None

    def _auto_receive_message(self, envelope: Envelope) -> None:
        """(reference: ActorCell.autoReceiveMessage :557-568)"""
        msg = envelope.message
        if self.system.settings.debug_autoreceive:
            self._log_debug(f"received AutoReceiveMessage {msg!r}")
        if isinstance(msg, Terminated):
            self.receive_message(msg)
        elif msg is msgs.PoisonPill:
            self.stop()
        elif msg is msgs.Kill:
            raise ActorKilledException("Kill")
        elif isinstance(msg, msgs.Identify):
            sender = self.sender
            if sender is not None:
                sender.tell(msgs.ActorIdentity(msg.message_id, self.self_ref), self.self_ref)

    def receive_message(self, msg: Any) -> None:
        """(reference: ActorCell.receiveMessage :577 → Actor.aroundReceive)"""
        behavior = self._behavior_stack[-1] if self._behavior_stack else None
        if behavior is None:
            self.unhandled(msg)
            return
        if self.actor is not None:
            self.actor.around_receive(behavior, msg)
        else:
            behavior(msg)

    def unhandled(self, msg: Any) -> None:
        """(reference: Actor.unhandled — Terminated => DeathPactException)"""
        if isinstance(msg, Terminated):
            raise DeathPactException(msg.actor)
        self.system.event_stream.publish(UnhandledMessage(msg, self.sender, self.self_ref))

    # --------------------------------------------------------------- logging
    def _log_debug(self, text: str) -> None:
        from ..event.logging import Debug
        self.system.event_stream.publish(Debug(str(self.self_ref.path), type(self.actor).__name__
                                               if self.actor else "ActorCell", text))

    def _log_error(self, cause: BaseException, text: str) -> None:
        from ..event.logging import Error
        self.system.event_stream.publish(Error(str(self.self_ref.path), type(self.actor).__name__
                                               if self.actor else "ActorCell", text, cause=cause))


_B64 = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+~"


def _base64(n: int) -> str:
    s = ""
    while True:
        s += _B64[n & 63]
        n >>= 6
        if n == 0:
            return s
