"""Actor references: location-transparent handles with `tell`.

Reference parity: akka-actor/src/main/scala/akka/actor/ActorRef.scala —
`ActorRef.!` (:185), `LocalActorRef` delegating to its ActorCell (:412-413),
MinimalActorRef for synthetic refs, Nobody, DeadLetterActorRef
(akka/actor/ActorRefProvider.scala dead-letters), and FunctionRef
(actor/dungeon/Children FunctionRef) used for probes/adapters.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from .path import ActorPath, Address, undefined_uid
from .messages import DeadLetter, Terminated
from ..dispatch.mailbox import Envelope
from ..dispatch import sysmsg


class ActorRef:
    """The public handle. Ordered and hashed by path."""

    path: ActorPath

    def tell(self, message: Any, sender: "Optional[ActorRef]" = None) -> None:
        raise NotImplementedError

    # `ref << msg` sugar for tell with no sender
    def __lshift__(self, message: Any) -> None:
        self.tell(message, None)

    def forward(self, message: Any, context) -> None:
        self.tell(message, context.sender)

    @property
    def uid(self) -> int:
        return self.path.uid

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, ActorRef) and self.path == other.path
                and self.path.uid == other.path.uid)

    def __hash__(self) -> int:
        return hash((self.path, self.path.uid))

    def __lt__(self, other: "ActorRef") -> bool:
        return (str(self.path), self.path.uid) < (str(other.path), other.path.uid)

    def __repr__(self) -> str:
        return f"Actor[{self.path.to_serialization_format()}]"

    def __reduce__(self):
        # refs in message payloads cross the wire as full-address path strings
        # resolved against the receiving system's provider (reference:
        # Serialization.currentTransportInformation, Serialization.scala:93-136)
        from ..serialization.serialization import resolve_ref, serialized_ref_path
        return (resolve_ref, (serialized_ref_path(self),))


class InternalActorRef(ActorRef):
    """SPI shared by local/remote refs (reference: InternalActorRef in ActorRef.scala)."""

    def start(self) -> None: ...
    def suspend(self) -> None: ...
    def resume(self, caused_by_failure: Optional[BaseException] = None) -> None: ...
    def restart(self, cause: Optional[BaseException] = None) -> None: ...
    def stop(self) -> None: ...

    def send_system_message(self, message: sysmsg.SystemMessage) -> None: ...

    @property
    def is_local(self) -> bool:
        return True

    @property
    def is_terminated(self) -> bool:
        return False

    def get_child(self, names: list) -> "InternalActorRef":
        return Nobody


class LocalActorRef(InternalActorRef):
    """Delegates everything to its ActorCell (reference: ActorRef.scala:305-430)."""

    __slots__ = ("path", "cell", "_system")

    def __init__(self, system, props, dispatcher_id, parent, path: ActorPath):
        from .cell import ActorCell
        self.path = path
        self._system = system
        self.cell = ActorCell(system, self, props, dispatcher_id, parent)

    def initialize(self, send_supervise: bool, mailbox_type) -> "LocalActorRef":
        self.cell.init(send_supervise, mailbox_type)
        return self

    def tell(self, message: Any, sender: Optional[ActorRef] = None) -> None:
        if message is None:
            from .messages import InvalidMessageException
            raise InvalidMessageException("message must not be None")
        self.cell.send_message(Envelope(message, sender))

    def send_system_message(self, message: sysmsg.SystemMessage) -> None:
        self.cell.send_system_message(message)

    def start(self) -> None:
        self.cell.start()

    def suspend(self) -> None:
        self.cell.suspend()

    def resume(self, caused_by_failure: Optional[BaseException] = None) -> None:
        self.cell.resume(caused_by_failure)

    def restart(self, cause: Optional[BaseException] = None) -> None:
        self.cell.restart(cause)

    def stop(self) -> None:
        self.cell.stop()

    @property
    def is_terminated(self) -> bool:
        return self.cell.is_terminated

    @property
    def underlying(self):
        return self.cell

    def get_child(self, names: list) -> InternalActorRef:
        ref: InternalActorRef = self
        for name in names:
            if name in ("", "."):
                continue
            if name == "..":
                ref = ref.cell.parent if isinstance(ref, LocalActorRef) else Nobody
            elif isinstance(ref, LocalActorRef):
                child = ref.cell.get_single_child(name)
                if child is None:
                    return Nobody
                ref = child
            else:
                return Nobody
        return ref


class MinimalActorRef(InternalActorRef):
    """No cell, no mailbox — synthetic refs (reference: MinimalActorRef)."""

    def __init__(self, path: ActorPath, provider=None):
        self.path = path
        self.provider = provider

    def tell(self, message: Any, sender: Optional[ActorRef] = None) -> None:
        pass

    def send_system_message(self, message: sysmsg.SystemMessage) -> None:
        if isinstance(message, sysmsg.Watch):
            if message.watchee == self and message.watcher != self:
                message.watcher.send_system_message(
                    sysmsg.DeathWatchNotification(self, existence_confirmed=False))

    @property
    def is_terminated(self) -> bool:
        return True


class _Nobody(MinimalActorRef):
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __init__(self):
        super().__init__(ActorPath(Address("akka", "all-systems"), ("Nobody",)))

    def __repr__(self):
        return "Nobody"


Nobody = _Nobody()


class DeadLetterActorRef(MinimalActorRef):
    """Publishes DeadLetter to the event stream
    (reference: DeadLetterActorRef in ActorRefProvider.scala)."""

    def __init__(self, path: ActorPath, event_stream):
        super().__init__(path)
        self.event_stream = event_stream

    def tell(self, message: Any, sender: Optional[ActorRef] = None) -> None:
        if isinstance(message, DeadLetter):
            self.event_stream.publish(message)
        else:
            self.event_stream.publish(DeadLetter(message, sender if sender is not None else Nobody, self))


class FunctionRef(MinimalActorRef):
    """A ref backed by a plain function; supports being watched
    (reference: akka.actor.FunctionRef in actor/ActorCell.scala companion area)."""

    def __init__(self, path: ActorPath, provider, handler: Callable[[Any, Optional[ActorRef]], None]):
        super().__init__(path, provider)
        self.handler = handler
        self._watched_by: set = set()
        self._stopped = False
        self._lock = threading.Lock()

    def tell(self, message: Any, sender: Optional[ActorRef] = None) -> None:
        if not self._stopped:
            self.handler(message, sender)

    def send_system_message(self, message: sysmsg.SystemMessage) -> None:
        if isinstance(message, sysmsg.Watch):
            with self._lock:
                if self._stopped:
                    message.watcher.send_system_message(
                        sysmsg.DeathWatchNotification(self, existence_confirmed=True))
                else:
                    self._watched_by.add(message.watcher)
        elif isinstance(message, sysmsg.Unwatch):
            with self._lock:
                self._watched_by.discard(message.watcher)
        elif isinstance(message, sysmsg.DeathWatchNotification):
            self.tell(Terminated(message.actor, message.existence_confirmed,
                                 message.address_terminated), message.actor)

    @property
    def is_terminated(self) -> bool:
        return self._stopped

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            watchers = list(self._watched_by)
            self._watched_by.clear()
        for w in watchers:
            w.send_system_message(sysmsg.DeathWatchNotification(self, existence_confirmed=True))

    def watch(self, other: InternalActorRef) -> None:
        other.send_system_message(sysmsg.Watch(watchee=other, watcher=self))

    def unwatch(self, other: InternalActorRef) -> None:
        other.send_system_message(sysmsg.Unwatch(watchee=other, watcher=self))
