"""FSM DSL for classic actors.

Reference parity: akka-actor/src/main/scala/akka/actor/FSM.scala (:375) —
startWith/when (:310-315), goto/stay/using, onTransition, whenUnhandled,
state timeouts, named timers, stop with reason.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from .actor import Actor


@dataclass(frozen=True)
class Event:
    event: Any
    state_data: Any


class StateTimeout:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "StateTimeout"


STATE_TIMEOUT = StateTimeout()


@dataclass(frozen=True)
class CurrentState:
    fsm_ref: Any
    state: Any


@dataclass(frozen=True)
class Transition:
    fsm_ref: Any
    from_state: Any
    to_state: Any


class SubscribeTransitionCallBack:
    def __init__(self, ref):
        self.ref = ref


class _State:
    __slots__ = ("state_name", "state_data", "timeout", "stop_reason", "replies")

    def __init__(self, state_name, state_data, timeout=None, stop_reason=None,
                 replies=None):
        self.state_name = state_name
        self.state_data = state_data
        self.timeout = timeout
        self.stop_reason = stop_reason
        self.replies = replies or []

    def using(self, data) -> "_State":
        return _State(self.state_name, data, self.timeout, self.stop_reason,
                      list(self.replies))

    def for_max(self, timeout: float) -> "_State":
        return _State(self.state_name, self.state_data, timeout,
                      self.stop_reason, list(self.replies))

    def replying(self, msg) -> "_State":
        s = _State(self.state_name, self.state_data, self.timeout,
                   self.stop_reason, list(self.replies))
        s.replies.append(msg)
        return s


class FSM(Actor):
    """Subclass, then in __init__ call when(...) for each state and
    start_with(initial, data)."""

    def __init__(self):
        super().__init__()
        self._handlers: Dict[Any, Callable[[Event], _State]] = {}
        self._unhandled_handler: Optional[Callable[[Event], _State]] = None
        self._transition_handlers: List[Callable[[Any, Any], None]] = []
        self._transition_subscribers: List[Any] = []
        self._timers: Dict[str, Any] = {}
        self._state_timeout_task = None
        self.current_state: Optional[_State] = None
        self._state_timeouts: Dict[Any, Optional[float]] = {}

    # -- DSL -----------------------------------------------------------------
    def when(self, state_name: Any, handler: Callable[[Event], _State],
             state_timeout: Optional[float] = None) -> None:
        self._handlers[state_name] = handler
        self._state_timeouts[state_name] = state_timeout

    def when_unhandled(self, handler: Callable[[Event], _State]) -> None:
        self._unhandled_handler = handler

    def on_transition(self, handler: Callable[[Any, Any], None]) -> None:
        self._transition_handlers.append(handler)

    def start_with(self, state_name: Any, state_data: Any,
                   timeout: Optional[float] = None) -> None:
        self.current_state = _State(state_name, state_data,
                                    timeout or self._state_timeouts.get(state_name))

    def goto(self, state_name: Any) -> _State:
        return _State(state_name, self.current_state.state_data,
                      self._state_timeouts.get(state_name))

    def stay(self) -> _State:
        return _State(self.current_state.state_name, self.current_state.state_data)

    def stop(self, reason: Any = "normal") -> _State:
        s = self.stay()
        s.stop_reason = reason
        return s

    @property
    def state_name(self) -> Any:
        return self.current_state.state_name

    @property
    def state_data(self) -> Any:
        return self.current_state.state_data

    # -- timers (reference: FSM setTimer/cancelTimer) ------------------------
    def set_timer(self, name: str, msg: Any, delay: float, repeat: bool = False) -> None:
        self.cancel_timer(name)
        sched = self.context.system.scheduler
        if repeat:
            task = sched.schedule_tell_with_fixed_delay(delay, delay, self.self_ref,
                                                        msg, self.self_ref)
        else:
            task = sched.schedule_tell_once(delay, self.self_ref, msg, self.self_ref)
        self._timers[name] = task

    def cancel_timer(self, name: str) -> None:
        t = self._timers.pop(name, None)
        if t is not None:
            t.cancel()

    def is_timer_active(self, name: str) -> bool:
        t = self._timers.get(name)
        return t is not None and not t.is_cancelled

    # -- engine --------------------------------------------------------------
    def initialize(self) -> None:
        self._arm_state_timeout()

    def receive(self, message: Any):
        if isinstance(message, SubscribeTransitionCallBack):
            self._transition_subscribers.append(message.ref)
            message.ref.tell(CurrentState(self.self_ref, self.state_name), self.self_ref)
            return None
        handler = self._handlers.get(self.state_name)
        if handler is None:
            return NotImplemented
        event = Event(message, self.current_state.state_data)
        next_state = handler(event)
        if next_state is None and self._unhandled_handler is not None:
            next_state = self._unhandled_handler(event)
        if next_state is None:
            return NotImplemented
        self._apply_state(next_state)
        return None

    def _apply_state(self, next_state: _State) -> None:
        for reply in next_state.replies:
            self.sender.tell(reply, self.self_ref)
        if next_state.stop_reason is not None:
            self._cancel_state_timeout()
            self.on_termination(next_state.stop_reason)
            self.context.stop()
            return
        prev = self.current_state.state_name
        self.current_state = next_state
        if next_state.state_name != prev:
            for h in self._transition_handlers:
                h(prev, next_state.state_name)
            for sub in self._transition_subscribers:
                sub.tell(Transition(self.self_ref, prev, next_state.state_name),
                         self.self_ref)
        self._arm_state_timeout()

    def _arm_state_timeout(self) -> None:
        self._cancel_state_timeout()
        timeout = (self.current_state.timeout
                   if self.current_state.timeout is not None
                   else self._state_timeouts.get(self.state_name))
        if timeout:
            self._state_timeout_task = self.context.system.scheduler.schedule_tell_once(
                timeout, self.self_ref, STATE_TIMEOUT, self.self_ref)

    def _cancel_state_timeout(self) -> None:
        if self._state_timeout_task is not None:
            self._state_timeout_task.cancel()
            self._state_timeout_task = None

    def on_termination(self, reason: Any) -> None:
        pass

    def post_stop(self) -> None:
        self._cancel_state_timeout()
        for t in self._timers.values():
            t.cancel()
        self._timers.clear()
        super().post_stop()
