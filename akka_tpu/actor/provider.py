"""LocalActorRefProvider: creates/resolves refs, owns the guardian hierarchy.

Reference parity: akka-actor/src/main/scala/akka/actor/ActorRefProvider.scala —
LocalActorRefProvider (:370), rootGuardian (:513-514), actorOf (:116,215,231),
the /temp container for short-lived ask refs, and deadLetters.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Optional

from .actor import Actor
from .cell import _base64
from .messages import Terminated
from .path import ActorPath, Address, new_uid, parse_actor_path
from .props import Props
from .ref import (ActorRef, DeadLetterActorRef, FunctionRef, InternalActorRef,
                  LocalActorRef, MinimalActorRef, Nobody)
from .supervision import (OneForOneStrategy, Escalate, Restart, Stop,
                          default_decider)
from ..dispatch import sysmsg


class Guardian(Actor):
    """Root/user/system guardian behavior (reference: ActorRefProvider.scala
    guardianProps — default SupervisorStrategy, Terminated stops the system)."""

    def __init__(self, strategy=None):
        super().__init__()
        self._strategy = strategy

    @property
    def supervisor_strategy(self):
        return self._strategy

    def receive(self, message: Any):
        if isinstance(message, Terminated):
            self.context.stop()
            return None
        return NotImplemented


class LocalActorRefProvider:
    def __init__(self, system_name: str, settings, event_stream):
        from .deploy import Deployer
        self.system_name = system_name
        self.settings = settings
        self.event_stream = event_stream
        self.deployer = Deployer(settings)
        self.root_path = ActorPath(Address("akka", system_name))
        self.dead_letters = DeadLetterActorRef(self.root_path / "deadLetters", event_stream)
        self.ignore_ref = MinimalActorRef(self.root_path / "ignore")
        self.root_guardian: Optional[LocalActorRef] = None
        self.user_guardian: Optional[LocalActorRef] = None
        self.system_guardian: Optional[LocalActorRef] = None
        self.system = None
        self._temp: Dict[str, InternalActorRef] = {}
        self._temp_lock = threading.Lock()
        self._temp_counter = itertools.count()
        self._terminated_event = threading.Event()

    # -- init (reference: ActorRefProvider.init + rootGuardian creation) -----
    def init(self, system) -> None:
        self.system = system
        root_props = Props.create(Guardian, OneForOneStrategy(decider=default_decider))
        self.root_guardian = LocalActorRef(
            system, root_props, system.dispatchers.INTERNAL_DISPATCHER_ID, None,
            self.root_path.with_uid(new_uid()))
        mailboxes = system.mailboxes
        self.root_guardian.initialize(send_supervise=False,
                                      mailbox_type=mailboxes.default_mailbox())
        self.root_guardian.start()
        root_cell = self.root_guardian.cell
        self.system_guardian = root_cell.actor_of(
            Props.create(Guardian).with_dispatcher(system.dispatchers.INTERNAL_DISPATCHER_ID),
            "system")
        self.user_guardian = root_cell.actor_of(Props.create(Guardian), "user")

    @property
    def guardian(self) -> LocalActorRef:
        return self.user_guardian

    # -- deployment resolution (reference: Deployer.lookup consulted from
    # actorOf; the config entry wins over the programmatic Props.deploy) -----
    def effective_props(self, props: Props, path: ActorPath):
        """Merge `akka.actor.deployment` config with props.deploy; returns
        (props, deploy). Only /user-subtree actors are deployable."""
        from .deploy import NO_SCOPE, Deploy
        from dataclasses import replace as _replace
        elements = list(path.elements)
        cfg_deploy = (self.deployer.lookup(elements[1:])
                      if len(elements) > 1 and elements[0] == "user" else None)
        deploy = props.deploy
        if cfg_deploy is not None:
            deploy = cfg_deploy.with_fallback(deploy) if deploy is not None \
                else cfg_deploy
        if deploy is None:
            return props, None
        if props.router_config is None and deploy.router_config is not None:
            props = _replace(props, router_config=deploy.router_config)
        if props.dispatcher is None and deploy.dispatcher is not None:
            props = props.with_dispatcher(deploy.dispatcher)
        if props.mailbox is None and deploy.mailbox is not None:
            props = props.with_mailbox(deploy.mailbox)
        return props, deploy

    # -- actorOf (reference: ActorRefProvider.actorOf :116) ------------------
    def actor_of(self, system, props: Props, supervisor: InternalActorRef,
                 path: ActorPath, _resolved: bool = False) -> InternalActorRef:
        if not _resolved:
            props, _deploy = self.effective_props(props, path)
        if props.device is not None:
            # device-resident actor: rows in the tpu-batched runtime behind
            # an ordinary ref — no cell, no host mailbox (the Dispatchers
            # seam selects the backend, dispatch/Dispatchers.scala:121-259)
            from ..batched.bridge import (DeviceActorRef, DeviceBlockRef,
                                          get_handle)
            spec = props.device
            handle = get_handle(system, props.dispatcher)
            rows = handle.spawn(spec.behavior, spec.n, spec.init_state)
            if spec.n == 1:
                return DeviceActorRef(system, handle, int(rows[0]), path,
                                      spec.codec)
            return DeviceBlockRef(system, handle, rows, path, spec.codec)
        if props.router_config is not None:
            from ..routing.routed_cell import RoutedActorRef
            ref = RoutedActorRef(system, props, props.dispatcher, supervisor, path)
        else:
            ref = LocalActorRef(system, props, props.dispatcher, supervisor, path)
        mailbox_type = system.mailboxes.for_props(props)
        ref.initialize(send_supervise=True, mailbox_type=mailbox_type)
        return ref

    # -- temp refs for ask (reference: ActorRefProvider tempContainer) -------
    def temp_path(self) -> ActorPath:
        return (self.root_path / "temp").child("$" + _base64(next(self._temp_counter)))

    def register_temp_actor(self, ref: InternalActorRef, path: ActorPath) -> None:
        with self._temp_lock:
            self._temp[path.name] = ref

    def unregister_temp_actor(self, path: ActorPath) -> None:
        with self._temp_lock:
            self._temp.pop(path.name, None)

    def create_function_ref(self, handler) -> FunctionRef:
        path = self.temp_path()
        ref = FunctionRef(path, self, handler)
        self.register_temp_actor(ref, path)
        return ref

    def stop_function_ref(self, ref: FunctionRef) -> None:
        ref.stop()
        self.unregister_temp_actor(ref.path)

    # -- resolution ----------------------------------------------------------
    def resolve_actor_ref(self, path: Any) -> ActorRef:
        if isinstance(path, str):
            try:
                path = parse_actor_path(path)
            except ValueError:
                return self.dead_letters
        if path.address != self.root_path.address:
            return self.dead_letters
        return self.resolve_local(path)

    def resolve_local(self, path: ActorPath) -> ActorRef:
        elements = list(path.elements)
        if not elements:
            return self.root_guardian
        if elements[0] == "temp":
            with self._temp_lock:
                ref = self._temp.get(elements[1]) if len(elements) > 1 else None
            return ref if ref is not None else self.dead_letters
        if elements == ["deadLetters"]:
            return self.dead_letters
        ref = self.root_guardian.get_child(elements)
        return ref if ref is not Nobody else self.dead_letters

    # -- termination bookkeeping --------------------------------------------
    def actor_terminated(self, ref: ActorRef) -> None:
        if self.system is None:
            return
        if ref == self.user_guardian:
            if self.system_guardian is not None:
                self.system_guardian.stop()
        elif ref == self.system_guardian:
            if self.root_guardian is not None:
                self.root_guardian.stop()
        elif ref == self.root_guardian:
            self._terminated_event.set()
            self.system._finish_terminate()

    @property
    def terminated_event(self) -> threading.Event:
        return self._terminated_event

    def get_external_address_for(self, remote_address) -> Optional[Address]:
        return None

    @property
    def default_address(self) -> Address:
        return self.root_path.address
