"""Actor addresses and hierarchical paths.

Reference parity: akka-actor/src/main/scala/akka/actor/Address.scala and
ActorPath.scala — location-transparent names `akka://system@host:port/user/a/b`
with a per-incarnation uid appended as `#uid` (uid-in-path evidence:
actor/ActorCell.scala:382-388).
"""

from __future__ import annotations

import itertools
import re
from dataclasses import dataclass
from typing import Iterable, Optional, Tuple

_VALID_ELEMENT = re.compile(r"^[-\w:@&=+,.!~*'_;()]+$")

undefined_uid = 0
_uid_counter = itertools.count(1)


def new_uid() -> int:
    return next(_uid_counter)


@dataclass(frozen=True)
class Address:
    """Network location of an actor system. `host`/`port` are None for a
    purely local address (reference: actor/Address.scala:24-53)."""

    protocol: str
    system: str
    host: Optional[str] = None
    port: Optional[int] = None

    @property
    def has_local_scope(self) -> bool:
        return self.host is None

    @property
    def has_global_scope(self) -> bool:
        return self.host is not None

    def __str__(self) -> str:
        if self.host is None:
            return f"{self.protocol}://{self.system}"
        return f"{self.protocol}://{self.system}@{self.host}:{self.port}"

    @property
    def host_port(self) -> str:
        return str(self).split("://", 1)[1]

    @staticmethod
    def parse(s: str) -> "Address":
        m = re.match(r"^(\w[\w+.-]*)://([^@/]+)(?:@([^:/]+):(\d+))?$", s)
        if not m:
            raise ValueError(f"malformed address: {s!r}")
        proto, system, host, port = m.groups()
        return Address(proto, system, host, int(port) if port else None)


class ActorPath:
    """Immutable hierarchical path. Child construction via `path / name`."""

    __slots__ = ("address", "elements", "uid", "_str")

    def __init__(self, address: Address, elements: Tuple[str, ...] = (), uid: int = undefined_uid):
        self.address = address
        self.elements = elements
        self.uid = uid
        self._str: Optional[str] = None

    # -- construction ------------------------------------------------------
    def __truediv__(self, child: str) -> "ActorPath":
        return self.child(child)

    def child(self, name: str) -> "ActorPath":
        if not name or ("/" in name and not name.startswith("$")):
            raise ValueError(f"illegal actor name: {name!r}")
        return ActorPath(self.address, self.elements + (name,))

    def descendant(self, names: Iterable[str]) -> "ActorPath":
        p = self
        for n in names:
            p = p.child(n)
        return p

    def with_uid(self, uid: int) -> "ActorPath":
        return ActorPath(self.address, self.elements, uid)

    def with_address(self, address: Address) -> "ActorPath":
        return ActorPath(address, self.elements, self.uid)

    # -- views -------------------------------------------------------------
    @property
    def name(self) -> str:
        return self.elements[-1] if self.elements else "/"

    @property
    def parent(self) -> "ActorPath":
        if not self.elements:
            return self
        return ActorPath(self.address, self.elements[:-1])

    @property
    def root(self) -> "ActorPath":
        return ActorPath(self.address)

    def is_ancestor_of(self, other: "ActorPath") -> bool:
        return (other.address == self.address
                and len(other.elements) >= len(self.elements)
                and other.elements[: len(self.elements)] == self.elements)

    def to_string_without_address(self) -> str:
        return "/" + "/".join(self.elements)

    def to_serialization_format(self) -> str:
        s = f"{self.address}{self.to_string_without_address()}"
        return f"{s}#{self.uid}" if self.uid != undefined_uid else s

    def __str__(self) -> str:
        if self._str is None:
            self._str = f"{self.address}{self.to_string_without_address()}"
        return self._str

    def __repr__(self) -> str:
        return str(self)

    def __hash__(self) -> int:
        # uid excluded to match __eq__ (uid is ActorRef identity, not path identity)
        return hash((self.address, self.elements))

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, ActorPath)
                and self.address == other.address
                and self.elements == other.elements)

    def __lt__(self, other: "ActorPath") -> bool:
        return str(self) < str(other)


def validate_path_element(name: str) -> str:
    if not _VALID_ELEMENT.match(name):
        raise ValueError(
            f"invalid actor name [{name}]: must match {_VALID_ELEMENT.pattern}")
    return name


def parse_actor_path(s: str) -> ActorPath:
    """Parse `proto://system@host:port/a/b#uid` back into an ActorPath
    (reference: RootActorPath/ActorPath.fromString)."""
    uid = undefined_uid
    if "#" in s:
        s, uid_s = s.rsplit("#", 1)
        uid = int(uid_s)
    if "://" not in s:
        raise ValueError(f"malformed actor path: {s!r}")
    addr_part, _, path_part = s.partition("://")
    rest = path_part.split("/")
    addr = Address.parse(f"{addr_part}://{rest[0]}")
    elements = tuple(e for e in rest[1:] if e)
    return ActorPath(addr, elements, uid)
