"""Props: immutable recipe for creating an actor.

Reference parity: akka-actor/src/main/scala/akka/actor/Props.scala — class +
constructor args + deploy info (dispatcher/mailbox/router selection, reference:
actor/Deployer.scala).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Optional


@dataclass(frozen=True)
class Props:
    factory: Callable[[], Any]                 # () -> Actor
    cls: Optional[type] = None
    dispatcher: Optional[str] = None           # dispatcher config id
    mailbox: Optional[Any] = None              # mailbox name or MailboxType
    router_config: Optional[Any] = None        # RouterConfig (akka_tpu.routing)
    device: Optional[Any] = None               # DeviceSpec: rows in the
                                               # tpu-batched runtime instead
                                               # of a host cell (bridge.py)

    @staticmethod
    def create(cls: type, *args, **kwargs) -> "Props":
        return Props(factory=lambda: cls(*args, **kwargs), cls=cls)

    @staticmethod
    def from_factory(factory: Callable[[], Any], cls: Optional[type] = None) -> "Props":
        return Props(factory=factory, cls=cls)

    @staticmethod
    def from_receive(receive: Callable[[Any, Any], None]) -> "Props":
        """Props from a plain function receive(context, message)."""
        from .actor import FunctionActor
        return Props(factory=lambda: FunctionActor(receive), cls=FunctionActor)

    def with_dispatcher(self, dispatcher_id: str) -> "Props":
        return replace(self, dispatcher=dispatcher_id)

    def with_mailbox(self, mailbox: Any) -> "Props":
        return replace(self, mailbox=mailbox)

    def with_router(self, router_config: Any) -> "Props":
        return replace(self, router_config=router_config)

    def new_actor(self) -> Any:
        return self.factory()

    def actor_class(self) -> Optional[type]:
        return self.cls
