"""Props: immutable recipe for creating an actor.

Reference parity: akka-actor/src/main/scala/akka/actor/Props.scala — class +
constructor args + deploy info (dispatcher/mailbox/router/scope selection,
reference: actor/Deployer.scala, actor/Deploy.scala). `Props.create` keeps the
(cls, args, kwargs) triple so a Props can travel to another node for remote
deployment (remote/RemoteDeployer.scala; DaemonMsgCreate carries the recipe,
not a closure).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Optional, Tuple


@dataclass(frozen=True)
class Props:
    factory: Callable[[], Any]                 # () -> Actor
    cls: Optional[type] = None
    args: Tuple[Any, ...] = ()                 # ctor args (wire-able recipe)
    kwargs: Tuple[Tuple[str, Any], ...] = ()   # ctor kwargs as sorted items
    dispatcher: Optional[str] = None           # dispatcher config id
    mailbox: Optional[Any] = None              # mailbox name or MailboxType
    router_config: Optional[Any] = None        # RouterConfig (akka_tpu.routing)
    deploy: Optional[Any] = None               # Deploy (akka_tpu.actor.deploy)
    device: Optional[Any] = None               # DeviceSpec: rows in the
                                               # tpu-batched runtime instead
                                               # of a host cell (bridge.py)
    recipe: bool = False                       # built via Props.create, so
                                               # (cls, args, kwargs) is complete

    @staticmethod
    def create(cls: type, *args, **kwargs) -> "Props":
        return Props(factory=lambda: cls(*args, **kwargs), cls=cls,
                     args=tuple(args), kwargs=tuple(sorted(kwargs.items())),
                     recipe=True)

    @staticmethod
    def from_factory(factory: Callable[[], Any], cls: Optional[type] = None) -> "Props":
        return Props(factory=factory, cls=cls)

    @staticmethod
    def from_receive(receive: Callable[[Any, Any], None]) -> "Props":
        """Props from a plain function receive(context, message)."""
        from .actor import FunctionActor
        return Props(factory=lambda: FunctionActor(receive), cls=FunctionActor)

    def with_dispatcher(self, dispatcher_id: str) -> "Props":
        return replace(self, dispatcher=dispatcher_id)

    def with_mailbox(self, mailbox: Any) -> "Props":
        return replace(self, mailbox=mailbox)

    def with_router(self, router_config: Any) -> "Props":
        return replace(self, router_config=router_config)

    def with_deploy(self, deploy: Any) -> "Props":
        """Attach a Deploy (e.g. Deploy(scope=RemoteScope(addr)))."""
        return replace(self, deploy=deploy)

    def new_actor(self) -> Any:
        return self.factory()

    def actor_class(self) -> Optional[type]:
        return self.cls

    @property
    def has_recipe(self) -> bool:
        """True when (cls, args, kwargs) fully describes construction — the
        precondition for shipping this Props to another node."""
        return self.recipe and self.cls is not None
