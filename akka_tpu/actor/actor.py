"""Classic Actor base class + Stash + FunctionActor.

Reference parity: akka-actor/src/main/scala/akka/actor/Actor.scala (lifecycle
hooks: preStart/postStop/preRestart/postRestart, aroundReceive, unhandled) and
actor/Stash.scala (:61,172,216 — stash into a deque-based mailbox).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .cell import ActorCell, current_cell
from .messages import Terminated
from .ref import ActorRef
from ..dispatch.mailbox import DequeBasedMessageQueue, Envelope


class Actor:
    """Subclass and implement `receive(message)`. The context is available
    as `self.context` already inside __init__ (grabbed from the construction
    contextvar, mirroring the reference's contextStack ThreadLocal)."""

    # optional mailbox requirement marker (see Mailboxes.for_props)
    mailbox_requirement: Optional[type] = None

    def __init__(self) -> None:
        self._cell: Optional[ActorCell] = current_cell()

    # -- context accessors ---------------------------------------------------
    @property
    def context(self) -> ActorCell:
        if self._cell is None:
            raise RuntimeError("actor has no context (not created via actor_of?)")
        return self._cell

    @property
    def self_ref(self) -> ActorRef:
        return self.context.self_ref

    @property
    def sender(self) -> ActorRef:
        return self.context.sender

    @property
    def supervisor_strategy(self):
        return None  # None -> cell uses default_strategy()

    # -- lifecycle (reference: Actor.scala preStart/postStop/pre/postRestart) --
    def pre_start(self) -> None:
        pass

    def post_stop(self) -> None:
        pass

    def pre_restart(self, reason: Optional[BaseException], message: Any) -> None:
        """Default: unwatch+stop all children, then post_stop."""
        ctx = self.context
        for child in ctx.children:
            ctx.unwatch(child)
            ctx.stop(child)
        self.post_stop()

    def post_restart(self, reason: Optional[BaseException]) -> None:
        self.pre_start()

    # -- message handling ----------------------------------------------------
    def around_receive(self, receive: Callable[[Any], Any], msg: Any) -> None:
        handled = receive(msg)
        if handled is NotImplemented:
            self.unhandled(msg)

    def receive(self, message: Any) -> Any:
        """Return NotImplemented to signal 'unhandled' (maps the reference's
        partial-function miss to a sentinel)."""
        return NotImplemented

    def unhandled(self, message: Any) -> None:
        self.context.unhandled(message)


class FunctionActor(Actor):
    """Actor from a plain function receive(context, message)."""

    def __init__(self, fn: Callable[[ActorCell, Any], Any]):
        super().__init__()
        self._fn = fn

    def receive(self, message: Any) -> Any:
        return self._fn(self.context, message)


class Stash(Actor):
    """Mixin: stash() the current message, unstash_all() to re-prepend them
    (reference: actor/Stash.scala; requires a deque-based mailbox)."""

    mailbox_requirement = DequeBasedMessageQueue

    def __init__(self) -> None:
        super().__init__()
        self._theStash: list[Envelope] = []

    def stash(self) -> None:
        env = self.context.current_message
        if env is None:
            raise RuntimeError("no current message to stash")
        if self._theStash and self._theStash[-1] is env:
            raise RuntimeError("cannot stash the same message twice")
        cap = self.context.stash_capacity
        if 0 <= cap <= len(self._theStash):
            raise RuntimeError(f"stash capacity {cap} exceeded")
        self._theStash.append(env)

    def unstash_all(self, predicate: Callable[[Any], bool] = lambda _: True) -> None:
        mq = self.context.mailbox.message_queue
        if not isinstance(mq, DequeBasedMessageQueue):
            raise RuntimeError("unstash_all requires a deque-based mailbox")
        try:
            for env in reversed(self._theStash):
                if predicate(env.message):
                    mq.enqueue_first(self.context.self_ref, env)
        finally:
            self._theStash = []

    def unstash(self) -> None:
        """Prepend the OLDEST stashed message (reference: Stash.unstash)."""
        if self._theStash:
            mq = self.context.mailbox.message_queue
            mq.enqueue_first(self.context.self_ref, self._theStash.pop(0))

    def post_stop(self) -> None:
        # dead-letter remaining stash (reference: Stash.scala:216)
        from .messages import DeadLetter
        for env in self._theStash:
            self.context.system.dead_letters.tell(
                DeadLetter(env.message, env.sender, self.context.self_ref), env.sender)
        self._theStash = []
        super().post_stop()
