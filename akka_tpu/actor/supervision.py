"""Supervision strategies: 'let it crash' fault handling.

Reference parity: akka-actor/src/main/scala/akka/actor/FaultHandling.scala —
SupervisorStrategy with directives Resume/Restart/Stop/Escalate, the default
decider, OneForOneStrategy / AllForOneStrategy with maxNrOfRetries inside
withinTimeRange, and StoppingSupervisorStrategy. Applied from the cell's
failure path (actor/dungeon/FaultHandling.scala via ActorCell.systemInvoke:511-519).
"""

from __future__ import annotations

import time
from enum import Enum
from typing import Any, Callable, Optional

from .messages import (ActorInitializationException, ActorKilledException,
                       DeathPactException)


class Directive(Enum):
    """Resume/Restart/Stop/Escalate (FaultHandling.scala). Shared with the
    batched device runtime: a BatchedBehavior's LaneSupervisor
    (batched/supervision.py) maps each Directive to a lane code and applies
    it as masked column ops inside the jitted step — same semantics,
    step-count time base instead of wall clock (docs/SUPERVISION.md)."""

    RESUME = "resume"
    RESTART = "restart"
    STOP = "stop"
    ESCALATE = "escalate"


Resume = Directive.RESUME
Restart = Directive.RESTART
Stop = Directive.STOP
Escalate = Directive.ESCALATE

Decider = Callable[[BaseException], Directive]


def default_decider(cause: BaseException) -> Directive:
    """Reference: SupervisorStrategy.defaultDecider — init/kill/deathpact stop,
    any other Exception restarts; Errors escalate."""
    if isinstance(cause, (ActorInitializationException, ActorKilledException, DeathPactException)):
        return Stop
    if isinstance(cause, Exception):
        return Restart
    return Escalate


def stopping_decider(cause: BaseException) -> Directive:
    return Stop if isinstance(cause, Exception) else Escalate


class ChildRestartStats:
    """Per-child restart-frequency window (reference: actor/FaultHandling.scala
    ChildRestartStats.requestRestartPermission)."""

    __slots__ = ("child", "max_retries", "within", "_restarts")

    def __init__(self, child):
        self.child = child
        self._restarts: list[float] = []

    def request_restart_permission(self, max_retries: int, within: float) -> bool:
        if max_retries == 0:
            return False
        now = time.monotonic()
        if within > 0:
            self._restarts = [t for t in self._restarts if now - t < within]
        if max_retries < 0 or len(self._restarts) < max_retries:
            self._restarts.append(now)
            return True
        return False


class SupervisorStrategy:
    def __init__(self, max_nr_of_retries: int = -1, within_time_range: float = float("inf"),
                 decider: Decider = default_decider, logging_enabled: bool = True):
        self.max_nr_of_retries = max_nr_of_retries
        self.within_time_range = within_time_range
        self.decider = decider
        self.logging_enabled = logging_enabled

    # -- template methods ---------------------------------------------------
    def handle_failure(self, cell, child, cause: BaseException, stats: ChildRestartStats,
                       all_stats: list) -> bool:
        """Returns False if the failure should escalate to our own supervisor
        (reference: SupervisorStrategy.handleFailure)."""
        directive = self.decider(cause)
        if directive is Resume:
            self.log_failure(cell, child, cause, directive)
            self.resume_child(child, cause)
            return True
        if directive is Restart:
            self.log_failure(cell, child, cause, directive)
            self.process_failure(cell, restart=True, child=child, cause=cause,
                                 stats=stats, all_stats=all_stats)
            return True
        if directive is Stop:
            self.log_failure(cell, child, cause, directive)
            self.process_failure(cell, restart=False, child=child, cause=cause,
                                 stats=stats, all_stats=all_stats)
            return True
        return False  # Escalate

    def process_failure(self, cell, restart: bool, child, cause, stats, all_stats) -> None:
        raise NotImplementedError

    def handle_child_terminated(self, cell, child, children) -> None:
        pass

    def resume_child(self, child, cause) -> None:
        child.resume(caused_by_failure=cause)

    def restart_child(self, child, cause, suspend_first: bool) -> None:
        if suspend_first:
            child.suspend()
        child.restart(cause)

    def log_failure(self, cell, child, cause, directive: Directive) -> None:
        if self.logging_enabled:
            from ..event.logging import Error, Warning as LogWarning
            if directive is Resume:
                cell.system.event_stream.publish(
                    LogWarning(str(child.path), type(cause).__name__, str(cause)))
            else:
                cell.system.event_stream.publish(
                    Error(str(child.path), type(cause).__name__,
                          f"{cause!r} -> {directive.value}", cause=cause))


class OneForOneStrategy(SupervisorStrategy):
    """Apply the directive to the failing child only."""

    def process_failure(self, cell, restart, child, cause, stats, all_stats) -> None:
        if restart and stats.request_restart_permission(self.max_nr_of_retries, self.within_time_range):
            self.restart_child(child, cause, suspend_first=False)
        else:
            child.stop()


class AllForOneStrategy(SupervisorStrategy):
    """Apply the directive to all children (reference: AllForOneStrategy)."""

    def process_failure(self, cell, restart, child, cause, stats, all_stats) -> None:
        if all_stats:
            if restart and all(s.request_restart_permission(self.max_nr_of_retries, self.within_time_range)
                               for s in all_stats):
                for s in all_stats:
                    self.restart_child(s.child, cause, suspend_first=(s.child != child))
            else:
                for s in all_stats:
                    s.child.stop()


def default_strategy() -> SupervisorStrategy:
    return OneForOneStrategy(decider=default_decider)


def stopping_strategy() -> SupervisorStrategy:
    return OneForOneStrategy(decider=stopping_decider)
