"""Built-in user-level message types auto-handled by the actor cell.

Reference parity: akka-actor/src/main/scala/akka/actor/Actor.scala
(PoisonPill, Kill, ReceiveTimeout, Terminated, Identify/ActorIdentity,
Status) and event/DeadLetter types (event/EventStream-published).
AutoReceive handling lives in ActorCell.invoke (actor/ActorCell.scala:557-568).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


class AutoReceivedMessage:
    """Marker: handled by the cell itself, not the user receive."""
    __slots__ = ()


class PossiblyHarmful:
    __slots__ = ()


class _PoisonPill(AutoReceivedMessage, PossiblyHarmful):
    _instance: "Optional[_PoisonPill]" = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "PoisonPill"


class _Kill(AutoReceivedMessage, PossiblyHarmful):
    _instance: "Optional[_Kill]" = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Kill"


class _ReceiveTimeout(PossiblyHarmful):
    _instance: "Optional[_ReceiveTimeout]" = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "ReceiveTimeout"


PoisonPill = _PoisonPill()
Kill = _Kill()
ReceiveTimeout = _ReceiveTimeout()


class ActorKilledException(Exception):
    pass


class ActorInitializationException(Exception):
    def __init__(self, actor: Any, message: str, cause: Optional[BaseException] = None):
        super().__init__(message)
        self.actor = actor
        self.cause = cause


class PreRestartException(ActorInitializationException):
    pass


class PostRestartException(ActorInitializationException):
    pass


class DeathPactException(Exception):
    """Terminated received but not handled (reference: actor/Actor.scala DeathPactException)."""

    def __init__(self, dead: Any):
        super().__init__(f"monitored actor {dead} terminated")
        self.dead = dead


class IllegalActorStateException(Exception):
    pass


class InvalidActorNameException(Exception):
    pass


class InvalidMessageException(Exception):
    pass


@dataclass(frozen=True)
class Terminated(AutoReceivedMessage):
    """DeathWatch notification delivered to watchers
    (reference: actor/dungeon/DeathWatch.scala:81). `cause` is non-None when
    the watched actor died from a failure (feeds typed ChildFailed)."""
    actor: Any
    existence_confirmed: bool = True
    address_terminated: bool = False
    cause: Optional[BaseException] = None


@dataclass(frozen=True)
class Identify(AutoReceivedMessage):
    message_id: Any = None


@dataclass(frozen=True)
class ActorIdentity:
    correlation_id: Any
    ref: Any  # Optional[ActorRef]


@dataclass(frozen=True)
class DeadLetter:
    """Published to the EventStream for messages to dead/nonexistent actors
    (reference: actor/DeadLetter in actor/Actor.scala; event/DeadLetterListener.scala)."""
    message: Any
    sender: Any
    recipient: Any


@dataclass(frozen=True)
class SuppressedDeadLetter:
    message: Any
    sender: Any
    recipient: Any


@dataclass(frozen=True)
class Dropped:
    """Envelope dropped due to overflow/invalid state (reference: actor/Dropped)."""
    message: Any
    reason: str
    sender: Any
    recipient: Any


@dataclass(frozen=True)
class UnhandledMessage:
    message: Any
    sender: Any
    recipient: Any


class Status:
    @dataclass(frozen=True)
    class Success:
        status: Any = None

    @dataclass(frozen=True)
    class Failure:
        cause: BaseException = None  # type: ignore[assignment]
