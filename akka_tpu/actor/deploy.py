"""Deploy: where/how an actor is instantiated — dispatcher, mailbox, router,
and (with the remote provider) the node it lives on.

Reference parity: akka-actor/src/main/scala/akka/actor/Deployer.scala —
config-driven per-path deployment (`akka.actor.deployment` section, wildcard
path patterns, router/dispatcher/mailbox selection) — and the Scope model
(LocalScope / RemoteScope, the latter from akka-remote/src/main/scala/akka/
remote/RemoteDeployer.scala). Props.deploy and the deployer's config entry are
merged at spawn time with the config entry winning (Deployer.scala lookup +
ActorRefProvider.actorOf deployment resolution).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple


class Scope:
    """Where the actor is created (reference: actor/Deploy.scala Scope)."""
    __slots__ = ()

    def with_fallback(self, other: "Scope") -> "Scope":
        return self


class LocalScope(Scope):
    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - repr only
        return "LocalScope"


class NoScopeGiven(Scope):
    __slots__ = ()

    def with_fallback(self, other: Scope) -> Scope:
        return other

    def __repr__(self) -> str:  # pragma: no cover - repr only
        return "NoScopeGiven"


@dataclass(frozen=True)
class RemoteScope(Scope):
    """Deploy onto the node at `address` ("akka://sys@host:port").
    Reference: remote/RemoteDeployer.scala RemoteScope."""
    address: str


NO_SCOPE = NoScopeGiven()
LOCAL_SCOPE = LocalScope()


@dataclass(frozen=True)
class Deploy:
    """(reference: actor/Deploy.scala — path/config/routerConfig/scope/
    dispatcher/mailbox with with_fallback merge)"""
    path: str = ""
    scope: Scope = NO_SCOPE
    dispatcher: Optional[str] = None
    mailbox: Optional[Any] = None
    router_config: Optional[Any] = None
    tags: Tuple[str, ...] = ()

    def with_fallback(self, other: "Deploy") -> "Deploy":
        return Deploy(
            path=self.path or other.path,
            scope=self.scope.with_fallback(other.scope),
            dispatcher=self.dispatcher if self.dispatcher is not None else other.dispatcher,
            mailbox=self.mailbox if self.mailbox is not None else other.mailbox,
            router_config=(self.router_config if self.router_config is not None
                           else other.router_config),
            tags=self.tags or other.tags)


def _router_from_config(kind: str, entry) -> Any:
    """Build a RouterConfig from a deployment entry's `router = <kind>`
    (reference: Deployer.scala createRouterConfig's type registry)."""
    from ..routing import router as r
    n = entry.get_int("nr-of-instances", 1)
    paths = tuple(entry.get("routees", {}).get("paths", ()) or ())
    table = {
        "round-robin-pool": lambda: r.RoundRobinPool(n),
        "random-pool": lambda: r.RandomPool(n),
        "broadcast-pool": lambda: r.BroadcastPool(n),
        "smallest-mailbox-pool": lambda: r.SmallestMailboxPool(n),
        "consistent-hashing-pool": lambda: r.ConsistentHashingPool(n),
        "scatter-gather-pool": lambda: r.ScatterGatherFirstCompletedPool(n),
        "tail-chopping-pool": lambda: r.TailChoppingPool(n),
        "round-robin-group": lambda: r.RoundRobinGroup(paths),
        "random-group": lambda: r.RandomGroup(paths),
        "broadcast-group": lambda: r.BroadcastGroup(paths),
        "consistent-hashing-group": lambda: r.ConsistentHashingGroup(paths),
    }
    factory = table.get(kind)
    if factory is None:
        raise ValueError(f"unknown router type in deployment config: {kind!r}")
    return factory()


class Deployer:
    """Parses `akka.actor.deployment` into Deploy entries and answers
    lookups by /user-relative path, most-specific match first, with `*`
    wildcard elements (reference: actor/Deployer.scala:156-178 lookup on a
    WildcardTree)."""

    def __init__(self, settings):
        self._entries: List[Tuple[Tuple[str, ...], Deploy]] = []
        section = settings.config.get("akka.actor.deployment", {}) or {}
        cfg = settings.config.get_config("akka.actor.deployment")
        for raw_path in section:
            if raw_path == "default":
                continue
            entry = cfg.get_config(raw_path)
            elements = tuple(e for e in raw_path.split("/") if e)
            router_kind = entry.get_string("router", "")
            deploy = Deploy(
                path=raw_path,
                scope=(RemoteScope(entry.get_string("remote"))
                       if entry.get_string("remote", "") else NO_SCOPE),
                dispatcher=entry.get_string("dispatcher", "") or None,
                mailbox=entry.get_string("mailbox", "") or None,
                router_config=(_router_from_config(router_kind, entry)
                               if router_kind and router_kind != "from-code"
                               else None))
            self._entries.append((elements, deploy))
        # longest (most specific) patterns first; literals beat wildcards
        self._entries.sort(key=lambda kv: (-len(kv[0]), kv[0].count("*")))

    @staticmethod
    def _matches(pattern: Tuple[str, ...], elements: Sequence[str]) -> bool:
        if pattern and pattern[-1] == "**":
            # trailing "**" matches ANY suffix, including a single element
            # (Deployer wildcard-tree parity)
            head = pattern[:-1]
            return (len(elements) >= len(head)
                    and all(p == "*" or p == e
                            for p, e in zip(head, elements)))
        if len(pattern) != len(elements):
            return False
        return all(p == "*" or p == e for p, e in zip(pattern, elements))

    def lookup(self, elements: Sequence[str]) -> Optional[Deploy]:
        """`elements` is the /user-relative path (e.g. ["service", "worker"])."""
        elements = list(elements)
        for pattern, deploy in self._entries:
            if self._matches(pattern, elements):
                return deploy
        return None
