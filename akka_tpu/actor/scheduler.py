"""Hashed-wheel timer scheduler.

Reference parity: akka-actor/src/main/scala/akka/actor/LightArrayRevolverScheduler.scala
(:40) — a wheel of `ticks-per-wheel` buckets revolved every `tick-duration`
(:47-51); `schedule` (:102) quantizes timers to ticks. Timers drive receive
timeouts, ask timeouts, cluster ticks and user schedules.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional


class Cancellable:
    __slots__ = ("_cancelled", "_lock")

    def __init__(self) -> None:
        self._cancelled = False
        self._lock = threading.Lock()

    def cancel(self) -> bool:
        with self._lock:
            if self._cancelled:
                return False
            self._cancelled = True
            return True

    @property
    def is_cancelled(self) -> bool:
        return self._cancelled


class _TimerTask(Cancellable):
    __slots__ = ("fn", "rounds", "repeat_delay", "fixed_rate", "period_start")

    def __init__(self, fn: Callable[[], None], rounds: int,
                 repeat_delay: float = 0.0, fixed_rate: bool = False):
        super().__init__()
        self.fn = fn
        self.rounds = rounds
        self.repeat_delay = repeat_delay
        self.fixed_rate = fixed_rate


class Scheduler:
    """Wheel-based scheduler on a daemon thread."""

    def __init__(self, tick_duration: float = 0.01, ticks_per_wheel: int = 512,
                 name: str = "akka-tpu-scheduler"):
        self.tick_duration = max(tick_duration, 0.001)
        self.wheel_size = self._next_pow2(ticks_per_wheel)
        self._wheel: list[list[_TimerTask]] = [[] for _ in range(self.wheel_size)]
        self._lock = threading.Lock()
        self._tick = 0
        self._stopped = threading.Event()
        self._start_time = time.monotonic()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    @staticmethod
    def _next_pow2(n: int) -> int:
        p = 1
        while p < n:
            p <<= 1
        return p

    # -- public API ---------------------------------------------------------
    def schedule_once(self, delay: float, fn: Callable[[], None]) -> Cancellable:
        return self._schedule(delay, fn, repeat_delay=0.0)

    def schedule_with_fixed_delay(self, initial_delay: float, delay: float,
                                  fn: Callable[[], None]) -> Cancellable:
        return self._schedule(initial_delay, fn, repeat_delay=delay, fixed_rate=False)

    def schedule_at_fixed_rate(self, initial_delay: float, interval: float,
                               fn: Callable[[], None]) -> Cancellable:
        return self._schedule(initial_delay, fn, repeat_delay=interval, fixed_rate=True)

    def schedule_tell_once(self, delay: float, receiver, message: Any, sender=None) -> Cancellable:
        return self.schedule_once(delay, lambda: receiver.tell(message, sender))

    def schedule_tell_with_fixed_delay(self, initial_delay: float, delay: float,
                                       receiver, message: Any, sender=None) -> Cancellable:
        return self.schedule_with_fixed_delay(
            initial_delay, delay, lambda: receiver.tell(message, sender))

    # -- internals ----------------------------------------------------------
    def _schedule(self, delay: float, fn, repeat_delay: float, fixed_rate: bool = False) -> Cancellable:
        if self._stopped.is_set():
            raise RuntimeError("scheduler has been shut down")
        delay = max(delay, 0.0)
        task = _TimerTask(fn, 0, repeat_delay, fixed_rate)
        self._place(task, delay)
        return task

    def _place(self, task: _TimerTask, delay: float) -> None:
        ticks = max(int(delay / self.tick_duration + 0.999999), 1)
        with self._lock:
            slot = (self._tick + ticks) & (self.wheel_size - 1)
            # the slot is first reached after ((ticks-1) % wheel)+1 ticks, so a
            # delay of exactly one wheel period needs 0 extra revolutions
            task.rounds = (ticks - 1) // self.wheel_size
            self._wheel[slot].append(task)

    def _run(self) -> None:
        next_deadline = time.monotonic() + self.tick_duration
        while not self._stopped.is_set():
            now = time.monotonic()
            sleep = next_deadline - now
            if sleep > 0:
                self._stopped.wait(sleep)
                if self._stopped.is_set():
                    break
            next_deadline += self.tick_duration
            self._advance()

    def _advance(self) -> None:
        with self._lock:
            self._tick = (self._tick + 1) & (self.wheel_size - 1)
            bucket = self._wheel[self._tick]
            due, remaining = [], []
            for task in bucket:
                if task.is_cancelled:
                    continue
                if task.rounds > 0:
                    task.rounds -= 1
                    remaining.append(task)
                else:
                    due.append(task)
            self._wheel[self._tick] = remaining
        for task in due:
            try:
                task.fn()
            except Exception:  # noqa: BLE001 — scheduler must keep ticking
                pass
            if task.repeat_delay > 0 and not task.is_cancelled:
                self._place(task, task.repeat_delay)

    def shutdown(self) -> None:
        self._stopped.set()


class ExplicitlyTriggeredScheduler(Scheduler):
    """Virtual-time scheduler for tests — advances only via time_passes()
    (reference: akka-testkit ExplicitlyTriggeredScheduler.scala; typed
    ManualTime)."""

    def __init__(self, tick_duration: float = 0.01, ticks_per_wheel: int = 512):
        self._entries: list[tuple[float, _TimerTask]] = []
        self._now = 0.0
        self._elock = threading.Lock()
        self.tick_duration = tick_duration
        self._stopped = threading.Event()

    def _schedule(self, delay: float, fn, repeat_delay: float, fixed_rate: bool = False) -> Cancellable:
        task = _TimerTask(fn, 0, repeat_delay, fixed_rate)
        with self._elock:
            self._entries.append((self._now + max(delay, 0.0), task))
        return task

    def time_passes(self, amount: float) -> None:
        target = self._now + amount
        while True:
            with self._elock:
                due = sorted((t, task) for t, task in self._entries
                             if t <= target and not task.is_cancelled)
                if not due:
                    self._now = target
                    self._entries = [(t, task) for t, task in self._entries
                                     if not task.is_cancelled]
                    return
                t, task = due[0]
                self._entries.remove((t, task))
                self._now = max(self._now, t)
            try:
                task.fn()
            except Exception:  # noqa: BLE001
                pass
            if task.repeat_delay > 0 and not task.is_cancelled:
                with self._elock:
                    self._entries.append((self._now + task.repeat_delay, task))

    @property
    def current_time(self) -> float:
        return self._now

    def shutdown(self) -> None:
        self._stopped.set()
