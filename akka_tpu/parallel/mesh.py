"""Device mesh helpers: the ICI/DCN communication substrate.

This replaces the reference's Artery transport stack (remote/artery/
ArteryTransport.scala:328 — Aeron UDP lanes between JVMs) with XLA collectives
over the TPU interconnect: cross-shard tells ride `all_to_all`/`ppermute`
inside the jitted step (ICI), and multi-host control goes through
jax.distributed (DCN). See SURVEY.md §2.3 "TPU-native equivalent".
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None, axis_name: str = "shards",
              devices: Optional[Sequence] = None) -> Mesh:
    """1D mesh over the actor-shard axis."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"need {n_devices} devices, have {len(devices)} "
                    f"(hint: XLA_FLAGS=--xla_force_host_platform_device_count=N)")
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def make_mesh_2d(dp: int, tp: int, axis_names=("dp", "tp"),
                 devices: Optional[Sequence] = None) -> Mesh:
    """2D mesh for layered parallelism (shard axis x replication axis)."""
    if devices is None:
        devices = jax.devices()[: dp * tp]
    return Mesh(np.asarray(devices).reshape(dp, tp), axis_names)


def shard_spec(mesh: Mesh, axis_name: str = "shards") -> NamedSharding:
    """Rows sharded over the mesh axis (actor axis / shard axis)."""
    return NamedSharding(mesh, P(axis_name))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def host_device_count() -> int:
    return jax.device_count()


_distributed_initialized = False
_distributed_lock = __import__("threading").Lock()


def initialize_distributed(coordinator_address: str,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Bring up the MULTI-HOST jax runtime (DCN): after this,
    jax.devices() spans every process's chips and a Mesh built from them
    crosses hosts — the data plane's equivalent of Artery binding its
    transport (ArteryTransport.scala:328-470). Idempotent; returns whether
    this call performed the initialization."""
    global _distributed_initialized
    with _distributed_lock:
        if _distributed_initialized:
            return False
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        _distributed_initialized = True
        return True


def maybe_initialize_distributed_from_config(config) -> bool:
    """ActorSystem bootstrap hook: `akka.jax-distributed.enabled = true`
    plus coordinator-address/num-processes/process-id (process-id defaults
    from the standard env vars jax honors). The control plane (membership
    gossip over TCP) and the data plane (collectives over DCN) then share
    one process topology."""
    if config is None or not config.get_bool("akka.jax-distributed.enabled",
                                             False):
        return False
    addr = config.get_string("akka.jax-distributed.coordinator-address", "")
    n = config.get_int("akka.jax-distributed.num-processes", 0) or None
    pid = config.get_int("akka.jax-distributed.process-id", -1)
    return initialize_distributed(addr or None, n,
                                  pid if pid >= 0 else None)
