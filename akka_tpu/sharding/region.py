"""ShardRegion + Shard: the per-node entry point of cluster sharding.

Reference parity: akka-cluster-sharding/src/main/scala/akka/cluster/sharding/
ShardRegion.scala (:522 region actor; deliverMessage :1046-1089 — resolve
shard home, forward or buffer; ShardHome handling :712; buffering +
GetShardHome :968,1056) and Shard.scala (entity hosting, Passivate buffering,
remember-entities restart).

Regions address each other and the coordinator by path string; refs resolve
through the provider so the same code runs local or cross-node.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..actor.actor import Actor
from ..actor.messages import PoisonPill, Terminated
from ..actor.props import Props
from .messages import (BeginHandOff, BeginHandOffAck, ClusterShardingStats,
                       CurrentShardRegionState, GetClusterShardingStats,
                       GetShardHome, GetShardRegionState, GracefulShutdownReq,
                       HandOff, HostShard, Passivate, Register, RegisterAck,
                       RegisterProxy, ShardHome, ShardingEnvelope, ShardState,
                       ShardStarted, ShardStopped, StartEntity, StartEntityAck)


@dataclass(frozen=True)
class ClusterShardingSettings:
    """(reference: ClusterShardingSettings.scala) — tuned-down intervals for
    the host control plane."""
    number_of_shards: int = 32
    buffer_size: int = 10_000
    retry_interval: float = 0.2
    rebalance_interval: float = 1.0
    passivate_idle_after: Optional[float] = None  # seconds; None = off
    remember_entities: bool = False
    # which RememberEntitiesStore backs remember_entities (reference:
    # akka.cluster.sharding.remember-entities-store): "inproc" (tests),
    # "journal" (file-backed record log, needs remember_entities_dir), or
    # "ddata" (ORSet of ids per shard riding the op-delta replicator)
    remember_entities_store: str = "inproc"
    remember_entities_dir: Optional[str] = None
    role: Optional[str] = None


def default_extract_entity_id(message: Any) -> Optional[Tuple[str, Any]]:
    """(reference: ShardRegion.scala:42 ExtractEntityId) — understands
    ShardingEnvelope and StartEntity."""
    if isinstance(message, ShardingEnvelope):
        return message.entity_id, message.message
    if isinstance(message, StartEntity):
        return message.entity_id, message
    return None


def make_default_extract_shard_id(number_of_shards: int) -> Callable[[Any], Optional[str]]:
    from ..utils.hashing import stable_hash_str

    def extract(message: Any) -> Optional[str]:
        eid = None
        if isinstance(message, ShardingEnvelope):
            eid = message.entity_id
        elif isinstance(message, StartEntity):
            eid = message.entity_id
        if eid is None:
            return None
        # stable across processes: every node must agree on entity->shard
        return str(stable_hash_str(eid) % number_of_shards)
    return extract


# -- remember-entities store (reference: RememberEntitiesProvider) -----------

class RememberEntitiesStore:
    def remembered(self, type_name: str, shard_id: str) -> Set[str]:
        raise NotImplementedError

    def add(self, type_name: str, shard_id: str, entity_id: str) -> None:
        raise NotImplementedError

    def remove(self, type_name: str, shard_id: str, entity_id: str) -> None:
        raise NotImplementedError


class InProcRememberEntitiesStore(RememberEntitiesStore):
    """Process-global store: survives shard moves between in-proc 'nodes'
    (the ddata/eventsourced-store analogue for tests; a persistence-backed
    store plugs in via the same interface)."""

    _data: Dict[Tuple[str, str], Set[str]] = {}
    _lock = threading.Lock()

    def remembered(self, type_name, shard_id):
        with self._lock:
            return set(self._data.get((type_name, shard_id), set()))

    def add(self, type_name, shard_id, entity_id):
        with self._lock:
            self._data.setdefault((type_name, shard_id), set()).add(entity_id)

    def remove(self, type_name, shard_id, entity_id):
        with self._lock:
            self._data.get((type_name, shard_id), set()).discard(entity_id)

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._data.clear()


class JournalRememberEntitiesStore(RememberEntitiesStore):
    """Durable file-backed store: add/remove ops append to a
    length-prefixed record log (the FileJournal/TellJournal format, torn
    tails truncated on open), folded into memory at open so remembered()
    never touches the disk. A restarted region reads back exactly the
    ids whose add() was flushed — the eventsourced remember-entities
    provider (reference: EventSourcedRememberEntitiesShardStore.scala)
    at record-log simplicity.

    Appends are idempotence-elided (re-adding a present id writes
    nothing), flushed per record (kill -9 safe) and fsync'd every
    `fsync_every_n` appends; `compact()` rewrites the log as one
    snapshot record per non-empty (type, shard)."""

    def __init__(self, path: str, flight_recorder: Any = None,
                 fsync_every_n: int = 1):
        import os
        from ..persistence.journal import (repair_record_log,
                                           scan_record_log)
        self.path = path
        self.fsync_every_n = max(1, int(fsync_every_n))
        self._since_fsync = 0
        self._lock = threading.Lock()
        self._data: Dict[Tuple[str, str], Set[str]] = {}
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self.truncated_bytes = repair_record_log(path, flight_recorder)
        for _end, rec in scan_record_log(path):
            self._apply(rec)
        self._fh = open(path, "ab")

    def _apply(self, rec: Dict[str, Any]) -> None:
        op = rec.get("op")
        if op == "snap":
            for type_name, shard_id, ids in rec.get("data", ()):
                self._data[(type_name, shard_id)] = set(ids)
            return
        key = (rec["type"], rec["shard"])
        if op == "add":
            self._data.setdefault(key, set()).add(rec["eid"])
        elif op == "remove":
            self._data.get(key, set()).discard(rec["eid"])

    def _append_locked(self, rec: Dict[str, Any]) -> None:
        import os
        import pickle
        if self._fh is None:
            raise ValueError("JournalRememberEntitiesStore is closed")
        blob = pickle.dumps(rec, protocol=4)
        self._fh.write(len(blob).to_bytes(8, "little"))
        self._fh.write(blob)
        self._fh.flush()
        self._since_fsync += 1
        if self._since_fsync >= self.fsync_every_n:
            os.fsync(self._fh.fileno())
            self._since_fsync = 0

    def remembered(self, type_name, shard_id):
        with self._lock:
            return set(self._data.get((type_name, shard_id), set()))

    def add(self, type_name, shard_id, entity_id):
        with self._lock:
            ids = self._data.setdefault((type_name, shard_id), set())
            if entity_id in ids:
                return
            ids.add(entity_id)
            self._append_locked({"op": "add", "type": type_name,
                                 "shard": shard_id, "eid": entity_id})

    def remove(self, type_name, shard_id, entity_id):
        with self._lock:
            ids = self._data.get((type_name, shard_id), set())
            if entity_id not in ids:
                return
            ids.discard(entity_id)
            self._append_locked({"op": "remove", "type": type_name,
                                 "shard": shard_id, "eid": entity_id})

    def compact(self) -> int:
        """Atomic log rewrite: one snapshot record covering the live
        fold. Returns the number of remembered ids retained."""
        import os
        import pickle
        with self._lock:
            if self._fh is None:
                raise ValueError("JournalRememberEntitiesStore is closed")
            data = [(t, s, sorted(ids))
                    for (t, s), ids in self._data.items() if ids]
            blob = pickle.dumps({"op": "snap", "data": data}, protocol=4)
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(len(blob).to_bytes(8, "little"))
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "ab")
            self._since_fsync = 0
            return sum(len(ids) for _t, _s, ids in data)

    def close(self) -> None:
        import os
        with self._lock:
            if self._fh is not None:
                if self._since_fsync:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                    self._since_fsync = 0
                self._fh.close()
                self._fh = None


class DDataRememberEntitiesStore(RememberEntitiesStore):
    """Replicated store: one ORSet of entity ids per (type, shard) key in
    the ddata Replicator — adds/removes travel as the op-based deltas of
    PR 14 (an add to a 10k-id set gossips O(1 id), not the set), and a
    region restarted on ANY node of the cluster reads back the ids with
    one local Get (reference: DDataRememberEntitiesShardStore.scala).

    Local-first semantics: updates are WriteLocal (the shard's add must
    never block on a quorum — the reference uses majority writes but
    batches behind the shard's message stash; here gossip + delta ticks
    converge the set) and reads are ReadLocal."""

    def __init__(self, system, key_prefix: str = "sharding-remember",
                 timeout: float = 5.0):
        from ..ddata import DistributedData
        dd = DistributedData.get(system)
        self.system = system
        self.replicator = dd.replicator
        self.node = dd.self_unique_address
        self.key_prefix = key_prefix
        self.timeout = float(timeout)

    def _key(self, type_name: str, shard_id: str):
        from ..ddata import Key
        return Key(f"{self.key_prefix}-{type_name}-{shard_id}")

    def remembered(self, type_name, shard_id):
        from ..ddata import Get, GetSuccess, ReadLocal
        from ..pattern.ask import ask_sync
        rep = ask_sync(self.replicator,
                       Get(self._key(type_name, shard_id), ReadLocal()),
                       timeout=self.timeout, system=self.system)
        if isinstance(rep, GetSuccess):
            return set(rep.data.elements)
        return set()  # NotFound: nothing remembered yet

    def _update(self, type_name, shard_id, modify) -> None:
        from ..ddata import ORSet, Update, UpdateSuccess, WriteLocal
        from ..pattern.ask import ask_sync
        rep = ask_sync(self.replicator,
                       Update(self._key(type_name, shard_id),
                              ORSet.empty(), WriteLocal(), modify=modify),
                       timeout=self.timeout, system=self.system)
        if not isinstance(rep, UpdateSuccess):
            raise RuntimeError(
                f"remember-entities ddata update failed: {rep!r}")

    def add(self, type_name, shard_id, entity_id):
        self._update(type_name, shard_id,
                     lambda s: s.add(self.node, entity_id))

    def remove(self, type_name, shard_id, entity_id):
        self._update(type_name, shard_id,
                     lambda s: s.remove(self.node, entity_id))


def make_remember_entities_store(
        settings: "ClusterShardingSettings", system=None,
        flight_recorder: Any = None) -> Optional[RememberEntitiesStore]:
    """Resolve `settings.remember_entities_store` to an impl (None when
    remember_entities is off). "journal" needs remember_entities_dir;
    "ddata" needs the ActorSystem hosting the replicator."""
    if not settings.remember_entities:
        return None
    kind = settings.remember_entities_store or "inproc"
    if kind == "inproc":
        return InProcRememberEntitiesStore()
    if kind == "journal":
        import os
        if not settings.remember_entities_dir:
            raise ValueError(
                "remember_entities_store='journal' needs "
                "remember_entities_dir")
        return JournalRememberEntitiesStore(
            os.path.join(settings.remember_entities_dir,
                         "remember_entities.journal"),
            flight_recorder=flight_recorder)
    if kind == "ddata":
        if system is None:
            raise ValueError(
                "remember_entities_store='ddata' needs the ActorSystem")
        return DDataRememberEntitiesStore(system)
    raise ValueError(f"unknown remember_entities_store {kind!r}")


@dataclass(frozen=True)
class _RetryTick:
    pass


@dataclass(frozen=True)
class _PassivateIdleTick:
    pass


@dataclass(frozen=True)
class _StateQueryTimeout:
    qid: int


@dataclass(frozen=True)
class _ShardStateQuery:
    """Region->shard leg of a GetShardRegionState aggregation, tagged so a
    LATE reply from a timed-out query can never satisfy a newer one."""
    qid: int


@dataclass(frozen=True)
class _ShardStateReply:
    qid: int
    state: Any  # ShardState


# per-shard state aggregation deadline (reference: the 5s default ask
# timeout of ShardRegion.GetShardRegionState queries); a partial snapshot
# is sent if a shard does not answer in time
STATE_QUERY_TIMEOUT = 2.0


class Shard(Actor):
    """Hosts the entities of one shard as child actors (reference:
    sharding/Shard.scala)."""

    def __init__(self, type_name: str, shard_id: str, entity_props_factory,
                 settings: ClusterShardingSettings,
                 store: Optional[RememberEntitiesStore]):
        super().__init__()
        self.type_name = type_name
        self.shard_id = shard_id
        self.entity_props_factory = entity_props_factory
        self.settings = settings
        self.store = store if settings.remember_entities else None
        self.entities: Dict[str, Any] = {}          # id -> ref
        self.by_ref: Dict[Any, str] = {}            # ref -> id
        self.passivating: Set[str] = set()
        self.msg_buffer: Dict[str, List[tuple]] = {}  # passivating id -> msgs
        self.last_msg: Dict[str, float] = {}
        self.handoff_requester = None
        self._idle_task = None

    def pre_start(self) -> None:
        if self.store is not None:
            for eid in sorted(self.store.remembered(self.type_name, self.shard_id)):
                self._get_or_create(eid)
        if self.settings.passivate_idle_after:
            t = self.settings.passivate_idle_after / 2
            self._idle_task = self.context.system.scheduler \
                .schedule_tell_with_fixed_delay(t, t, self.self_ref,
                                                _PassivateIdleTick())

    def post_stop(self) -> None:
        if self._idle_task:
            self._idle_task.cancel()

    def _get_or_create(self, entity_id: str):
        ref = self.entities.get(entity_id)
        if ref is None:
            props = self.entity_props_factory(entity_id)
            ref = self.context.actor_of(props, entity_id)
            self.context.watch(ref)
            self.entities[entity_id] = ref
            self.by_ref[ref] = entity_id
            if self.store is not None:
                self.store.add(self.type_name, self.shard_id, entity_id)
        return ref

    def receive(self, message: Any) -> Any:  # noqa: C901
        if isinstance(message, tuple) and len(message) == 2 \
                and message[0] == "deliver":
            entity_id, payload = message[1]
            self._deliver(entity_id, payload)
        elif isinstance(message, StartEntity):
            self._get_or_create(message.entity_id)
            self.sender.tell(StartEntityAck(message.entity_id, self.shard_id),
                             self.self_ref)
        elif isinstance(message, Passivate):
            ref = self.sender
            eid = self.by_ref.get(ref)
            if eid is not None and eid not in self.passivating:
                self.passivating.add(eid)
                self.msg_buffer.setdefault(eid, [])
                if self.store is not None:
                    self.store.remove(self.type_name, self.shard_id, eid)
                if message.stop_message == "poison-pill":
                    ref.tell(PoisonPill)
                else:
                    ref.tell(message.stop_message, self.self_ref)
        elif isinstance(message, Terminated):
            self._entity_terminated(message.actor)
        elif isinstance(message, HandOff):
            self.handoff_requester = self.sender
            if not self.entities:
                self.sender.tell(ShardStopped(self.shard_id), self.self_ref)
                self.context.stop(self.context.self_ref)
            else:
                for ref in list(self.entities.values()):
                    ref.tell(PoisonPill)
        elif isinstance(message, _PassivateIdleTick):
            deadline = time.monotonic() - (self.settings.passivate_idle_after or 0)
            for eid, last in list(self.last_msg.items()):
                if last < deadline and eid in self.entities \
                        and eid not in self.passivating:
                    self.passivating.add(eid)
                    self.msg_buffer.setdefault(eid, [])
                    if self.store is not None:
                        self.store.remove(self.type_name, self.shard_id, eid)
                    self.entities[eid].tell(PoisonPill)
        elif isinstance(message, GetShardRegionState):
            self.sender.tell(ShardState(self.shard_id,
                                        tuple(sorted(self.entities))),
                             self.self_ref)
        elif isinstance(message, _ShardStateQuery):
            self.sender.tell(_ShardStateReply(
                message.qid, ShardState(self.shard_id,
                                        tuple(sorted(self.entities)))),
                self.self_ref)
        else:
            return NotImplemented

    def _deliver(self, entity_id: str, payload: Any) -> None:
        self.last_msg[entity_id] = time.monotonic()
        if entity_id in self.passivating:
            buf = self.msg_buffer.setdefault(entity_id, [])
            if len(buf) < self.settings.buffer_size:
                buf.append((payload, self.sender))
            return
        if isinstance(payload, StartEntity):
            self._get_or_create(entity_id)
            self.sender.tell(StartEntityAck(entity_id, self.shard_id),
                             self.self_ref)
            return
        self._get_or_create(entity_id).tell(payload, self.sender)

    def _entity_terminated(self, ref: Any) -> None:
        eid = self.by_ref.pop(ref, None)
        if eid is None:
            return
        self.entities.pop(eid, None)
        self.last_msg.pop(eid, None)
        was_passivating = eid in self.passivating
        self.passivating.discard(eid)
        buffered = self.msg_buffer.pop(eid, [])
        if self.handoff_requester is not None:
            if not self.entities:
                self.handoff_requester.tell(ShardStopped(self.shard_id),
                                            self.self_ref)
                self.context.stop(self.context.self_ref)
            return
        if buffered:
            # restart after passivation: redeliver buffered messages
            for payload, snd in buffered:
                self.last_msg[eid] = time.monotonic()
                self._get_or_create(eid).tell(payload, snd)
        elif not was_passivating and self.store is not None:
            # crashed / stopped on its own: remember-entities restarts it
            self._get_or_create(eid)


class ShardRegion(Actor):
    """(reference: ShardRegion.scala:522). host mode (entity_props_factory
    set) or proxy mode (None)."""

    def __init__(self, type_name: str, entity_props_factory,
                 extract_entity_id, extract_shard_id,
                 settings: ClusterShardingSettings,
                 coordinator_manager_path: str,
                 store: Optional[RememberEntitiesStore] = None):
        super().__init__()
        self.type_name = type_name
        self.entity_props_factory = entity_props_factory
        self.extract_entity_id = extract_entity_id or default_extract_entity_id
        self.extract_shard_id = extract_shard_id or \
            make_default_extract_shard_id(settings.number_of_shards)
        self.settings = settings
        self.manager_path = coordinator_manager_path
        # "ddata" needs the replicator's ActorSystem, which only exists
        # once the actor starts — defer that kind to pre_start
        self.store = store if store is not None else (
            make_remember_entities_store(settings)
            if settings.remember_entities and
            settings.remember_entities_store != "ddata" else None)
        self.coordinator = None               # direct ref once registered
        self.shard_homes: Dict[str, str] = {}  # shard -> region path
        self.shards: Dict[str, Any] = {}       # local shard id -> shard ref
        self.buffers: Dict[str, List[tuple]] = {}
        self._watched_regions: Dict[Any, str] = {}  # peer region ref -> path
        self._state_queries: Dict[int, dict] = {}   # qid -> pending agg
        self._state_query_seq = 0
        self._task = None
        from ..cluster.cluster import Cluster
        self.cluster = Cluster.get(self.context.system)

    # -- plumbing ------------------------------------------------------------
    def _self_path(self) -> str:
        addr = self.context.system.provider.default_address
        return f"{addr}{self.self_ref.path.to_string_without_address()}"

    def _ref(self, path: str):
        return self.context.system.provider.resolve_actor_ref(path)

    def _coordinator_ref(self):
        """Resolve the singleton coordinator on the current oldest node."""
        from ..cluster.member import MemberStatus
        ms = [m for m in self.cluster.state.members
              if m.status is MemberStatus.UP and
              (self.settings.role is None or self.settings.role in m.roles)]
        if not ms:
            return None
        oldest = min(ms, key=lambda m: (m.up_number, m.unique_address))
        return self._ref(f"{oldest.unique_address.address_str}"
                         f"{self.manager_path}/coordinator")

    def pre_start(self) -> None:
        if self.store is None and self.settings.remember_entities:
            self.store = make_remember_entities_store(
                self.settings, system=self.context.system)
        self._task = self.context.system.scheduler.schedule_tell_with_fixed_delay(
            0.05, self.settings.retry_interval, self.self_ref, _RetryTick())

    def post_stop(self) -> None:
        if self._task:
            self._task.cancel()

    def _register(self) -> None:
        ref = self._coordinator_ref()
        if ref is None:
            return
        msg = (Register(self._self_path()) if self.entity_props_factory
               else RegisterProxy(self._self_path()))
        ref.tell(msg, self.self_ref)

    # -- receive -------------------------------------------------------------
    def receive(self, message: Any) -> Any:  # noqa: C901
        if isinstance(message, _RetryTick):
            if self.coordinator is None:
                self._register()
            for shard_id in list(self.buffers):
                self._ask_home(shard_id)
        elif isinstance(message, RegisterAck):
            self.coordinator = self.sender
            self.context.watch(self.sender)
            for shard_id in list(self.buffers):
                self._ask_home(shard_id)
        elif isinstance(message, ShardHome):
            self.shard_homes[message.shard_id] = message.region_path
            self._watch_home(message.region_path)
            self._drain(message.shard_id)
        elif isinstance(message, HostShard):
            self._get_or_create_shard(message.shard_id)
            self.shard_homes[message.shard_id] = self._self_path()
            self.sender.tell(ShardStarted(message.shard_id), self.self_ref)
            self._drain(message.shard_id)
        elif isinstance(message, BeginHandOff):
            self.shard_homes.pop(message.shard_id, None)
            self.sender.tell(BeginHandOffAck(message.shard_id), self.self_ref)
        elif isinstance(message, HandOff):
            shard = self.shards.get(message.shard_id)
            if shard is None:
                self.sender.tell(ShardStopped(message.shard_id), self.self_ref)
            else:
                shard.tell(message, self.sender)  # shard replies ShardStopped
                self.shards.pop(message.shard_id, None)
        elif isinstance(message, Terminated):
            if self.coordinator is not None and message.actor == self.coordinator:
                self.coordinator = None
            else:
                # a peer region died: forget its shard homes so the next
                # message re-resolves via the coordinator
                path = self._watched_regions.pop(message.actor, None)
                if path is not None:
                    for sid in [s for s, h in self.shard_homes.items()
                                if h == path]:
                        del self.shard_homes[sid]
        elif isinstance(message, GetShardRegionState):
            # aggregate per-shard entity lists asynchronously (reference:
            # ShardRegion.scala replyToRegionStateQuery — ask each shard,
            # aggregate with a timeout, never block the region)
            if not self.shards:
                self.sender.tell(CurrentShardRegionState(()), self.self_ref)
            else:
                self._state_query_seq += 1
                qid = self._state_query_seq
                self._state_queries[qid] = {
                    "waiting": set(self.shards), "acc": [],
                    "reply_to": self.sender}
                for shard in self.shards.values():
                    shard.tell(_ShardStateQuery(qid), self.self_ref)
                self.context.system.scheduler.schedule_tell_once(
                    STATE_QUERY_TIMEOUT, self.self_ref,
                    _StateQueryTimeout(qid))
        elif isinstance(message, _ShardStateReply):
            # qid-tagged: a late reply from a timed-out query finds its
            # query gone and is dropped instead of satisfying a newer one
            q = self._state_queries.get(message.qid)
            if q is not None and message.state.shard_id in q["waiting"]:
                q["waiting"].discard(message.state.shard_id)
                q["acc"].append(message.state)
                if not q["waiting"]:
                    del self._state_queries[message.qid]
                    q["reply_to"].tell(
                        CurrentShardRegionState(tuple(q["acc"])),
                        self.self_ref)
        elif isinstance(message, _StateQueryTimeout):
            q = self._state_queries.pop(message.qid, None)
            if q is not None:  # partial beats nothing (reference timeout)
                q["reply_to"].tell(CurrentShardRegionState(tuple(q["acc"])),
                                   self.self_ref)
        elif isinstance(message, ShardStopped):
            pass  # late ack from a shard we already dropped
        else:
            env = self.extract_entity_id(message)
            shard_id = self.extract_shard_id(message)
            if env is None or shard_id is None:
                return NotImplemented
            self._deliver(shard_id, env[0], env[1], message)

    # -- delivery (reference: deliverMessage ShardRegion.scala:1046-1089) ----
    def _deliver(self, shard_id: str, entity_id: str, payload: Any,
                 original: Any) -> None:
        home = self.shard_homes.get(shard_id)
        if home is None:
            buf = self.buffers.setdefault(shard_id, [])
            if len(buf) >= self.settings.buffer_size:
                from ..actor.messages import DeadLetter
                self.context.system.event_stream.publish(
                    DeadLetter(payload, self.sender, self.self_ref))
                return
            buf.append((entity_id, payload, original, self.sender))
            self._ask_home(shard_id)
        elif home == self._self_path():
            shard = self._get_or_create_shard(shard_id)
            shard.tell(("deliver", (entity_id, payload)), self.sender)
        else:
            # forward the ORIGINAL message: the remote region re-extracts with
            # its own (identical) extractors (reference forwards msg verbatim)
            self._ref(home).tell(original, self.sender)

    def _watch_home(self, region_path: str) -> None:
        if region_path == self._self_path():
            return
        if region_path not in self._watched_regions.values():
            ref = self._ref(region_path)
            self.context.watch(ref)
            self._watched_regions[ref] = region_path

    def _ask_home(self, shard_id: str) -> None:
        if self.coordinator is not None:
            self.coordinator.tell(GetShardHome(shard_id), self.self_ref)

    def _drain(self, shard_id: str) -> None:
        buffered = self.buffers.pop(shard_id, [])
        for entity_id, payload, original, snd in buffered:
            home = self.shard_homes.get(shard_id)
            if home == self._self_path():
                self._get_or_create_shard(shard_id).tell(
                    ("deliver", (entity_id, payload)), snd)
            elif home is not None:
                self._ref(home).tell(original, snd)

    def _get_or_create_shard(self, shard_id: str):
        shard = self.shards.get(shard_id)
        if shard is None:
            if self.entity_props_factory is None:
                raise RuntimeError("proxy region cannot host shards")
            shard = self.context.actor_of(
                Props.create(Shard, self.type_name, shard_id,
                             self.entity_props_factory, self.settings,
                             self.store),
                f"shard-{shard_id}")
            self.shards[shard_id] = shard
        return shard
