"""ShardCoordinator: singleton allocator of shards to regions + rebalance.

Reference parity: akka-cluster-sharding/src/main/scala/akka/cluster/sharding/
ShardCoordinator.scala — allocation-strategy interface (:90-160),
LeastShardAllocationStrategy (:201 — allocate to the region with fewest
shards; rebalance from most- to least-loaded until within threshold), and the
coordinator protocol (Register/GetShardHome/ShardHome/BeginHandOff/HandOff).

Runs as the child of a ClusterSingletonManager (one live coordinator
cluster-wide, on the oldest node). Region refs are carried as path strings so
the protocol serializes across nodes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set

from ..actor.actor import Actor
from ..actor.messages import Terminated
from .messages import (BeginHandOff, BeginHandOffAck, GetShardHome,
                       GracefulShutdownReq, HandOff, HostShard, Register,
                       RegisterAck, RegisterProxy, ShardHome, ShardStopped)


class ShardAllocationStrategy:
    """(reference: ShardCoordinator.scala:90-160)"""

    def allocate_shard(self, requester: str, shard_id: str,
                       current: Dict[str, List[str]]) -> str:
        """Pick the region (path) to host shard_id. `current` maps
        region-path -> shard ids it hosts."""
        raise NotImplementedError

    def rebalance(self, current: Dict[str, List[str]],
                  in_progress: Set[str]) -> Set[str]:
        """Return shard ids to hand off this round."""
        raise NotImplementedError


class LeastShardAllocationStrategy(ShardAllocationStrategy):
    """(reference: ShardCoordinator.scala:201) — allocate to the least-loaded
    region; rebalance when max-min exceeds `rebalance_threshold`, at most
    `max_simultaneous_rebalance` in flight."""

    def __init__(self, rebalance_threshold: int = 1,
                 max_simultaneous_rebalance: int = 3):
        self.rebalance_threshold = rebalance_threshold
        self.max_simultaneous_rebalance = max_simultaneous_rebalance

    def allocate_shard(self, requester, shard_id, current):
        return min(current.items(), key=lambda kv: (len(kv[1]), kv[0]))[0]

    def rebalance(self, current, in_progress):
        if len(in_progress) >= self.max_simultaneous_rebalance or not current:
            return set()
        # consider only shards not already moving
        loads = {r: [s for s in shards if s not in in_progress]
                 for r, shards in current.items()}
        out: Set[str] = set()
        budget = self.max_simultaneous_rebalance - len(in_progress)
        while budget > 0:
            most = max(loads.items(), key=lambda kv: (len(kv[1]), kv[0]))
            least = min(loads.items(), key=lambda kv: (len(kv[1]), kv[0]))
            if len(most[1]) - len(least[1]) <= self.rebalance_threshold:
                break
            shard = sorted(most[1])[0]
            out.add(shard)
            most[1].remove(shard)
            budget -= 1
        return out


@dataclass(frozen=True)
class _RebalanceTick:
    pass


class ShardCoordinator(Actor):
    """State: regions (path -> hosted shards), shards (id -> region path),
    unallocated GetShardHome requests wait until a region registers."""

    def __init__(self, type_name: str,
                 allocation_strategy: Optional[ShardAllocationStrategy] = None,
                 rebalance_interval: float = 1.0):
        super().__init__()
        self.type_name = type_name
        self.strategy = allocation_strategy or LeastShardAllocationStrategy()
        self.rebalance_interval = rebalance_interval
        self.regions: Dict[str, List[str]] = {}   # region path -> shard ids
        self.proxies: Set[str] = set()
        self.shards: Dict[str, str] = {}          # shard id -> region path
        # rebalance bookkeeping: shard -> waiting-for BeginHandOffAck sources
        self.rebalance_ack_wait: Dict[str, Set[str]] = {}
        self.rebalance_in_progress: Set[str] = set()
        self.graceful_shutdown: Set[str] = set()
        self._pending_get_home: List[tuple] = []  # (shard_id, reply_to_path)
        self._watched: Dict[Any, str] = {}        # region ref -> path
        self._task = None

    def pre_start(self) -> None:
        self._task = self.context.system.scheduler.schedule_tell_with_fixed_delay(
            self.rebalance_interval, self.rebalance_interval, self.self_ref,
            _RebalanceTick())

    def post_stop(self) -> None:
        if self._task:
            self._task.cancel()

    # -- helpers -------------------------------------------------------------
    def _ref(self, path: str):
        return self.context.system.provider.resolve_actor_ref(path)

    def _self_path(self) -> str:
        ref = self.self_ref
        addr = self.context.system.provider.default_address
        return f"{addr}{ref.path.to_string_without_address()}"

    def _active_regions(self) -> Dict[str, List[str]]:
        return {r: s for r, s in self.regions.items()
                if r not in self.graceful_shutdown}

    def _allocate(self, shard_id: str, requester_path: str) -> None:
        active = self._active_regions()
        if not active:
            self._pending_get_home.append((shard_id, requester_path))
            return
        region = self.strategy.allocate_shard(requester_path, shard_id, active)
        self.shards[shard_id] = region
        self.regions[region].append(shard_id)
        self._ref(region).tell(HostShard(shard_id), self.self_ref)
        home = ShardHome(shard_id, region)
        for r in set(self.regions) | self.proxies:
            self._ref(r).tell(home, self.self_ref)

    # -- receive -------------------------------------------------------------
    def receive(self, message: Any) -> Any:  # noqa: C901
        if isinstance(message, Register):
            region_ref = self._ref(message.region_path)
            if message.region_path not in self.regions:
                self.context.watch(region_ref)
                self._watched[region_ref] = message.region_path
            self.regions.setdefault(message.region_path, [])
            self.graceful_shutdown.discard(message.region_path)
            region_ref.tell(RegisterAck(self._self_path()), self.self_ref)
            # region can now host: drain deferred allocations
            pending, self._pending_get_home = self._pending_get_home, []
            for shard_id, requester in pending:
                if shard_id not in self.shards:
                    self._allocate(shard_id, requester)
                else:
                    self._ref(requester).tell(
                        ShardHome(shard_id, self.shards[shard_id]), self.self_ref)
        elif isinstance(message, RegisterProxy):
            proxy_ref = self._ref(message.region_path)
            if message.region_path not in self.proxies:
                self.context.watch(proxy_ref)
                self._watched[proxy_ref] = message.region_path
            self.proxies.add(message.region_path)
            proxy_ref.tell(RegisterAck(self._self_path()), self.self_ref)
        elif isinstance(message, GetShardHome):
            shard_id = message.shard_id
            requester = self._sender_path()
            if shard_id in self.rebalance_in_progress:
                pass  # home is in flux; region retries
            elif shard_id in self.shards:
                self.sender.tell(ShardHome(shard_id, self.shards[shard_id]),
                                 self.self_ref)
            else:
                self._allocate(shard_id, requester)
        elif isinstance(message, BeginHandOffAck):
            self._on_begin_handoff_ack(message.shard_id)
        elif isinstance(message, ShardStopped):
            shard_id = message.shard_id
            if shard_id in self.rebalance_in_progress:
                self.rebalance_in_progress.discard(shard_id)
                region = self.shards.pop(shard_id, None)
                if region and shard_id in self.regions.get(region, []):
                    self.regions[region].remove(shard_id)
        elif isinstance(message, GracefulShutdownReq):
            region = message.region_path
            if region in self.regions:
                self.graceful_shutdown.add(region)
                for shard_id in list(self.regions[region]):
                    self._start_rebalance(shard_id)
        elif isinstance(message, _RebalanceTick):
            for shard_id in self.strategy.rebalance(self._active_regions(),
                                                    self.rebalance_in_progress):
                self._start_rebalance(shard_id)
        elif isinstance(message, Terminated):
            self._region_terminated(message.actor)
        else:
            return NotImplemented

    def _region_terminated(self, ref: Any) -> None:
        """Free a dead region's shards so they reallocate on next demand, and
        unwedge any rebalance waiting on its acks (reference:
        ShardCoordinator regionTerminated)."""
        path = self._watched.pop(ref, None)
        if path is None:
            return
        self.proxies.discard(path)
        self.graceful_shutdown.discard(path)
        for shard_id in self.regions.pop(path, []):
            self.shards.pop(shard_id, None)
            self.rebalance_in_progress.discard(shard_id)
            self.rebalance_ack_wait.pop(shard_id, None)
        for shard_id in list(self.rebalance_ack_wait):
            waiting = self.rebalance_ack_wait[shard_id]
            waiting.discard(path)
            if not waiting:
                del self.rebalance_ack_wait[shard_id]
                region = self.shards.get(shard_id)
                if region is not None:
                    self._ref(region).tell(HandOff(shard_id), self.self_ref)
                else:
                    self.rebalance_in_progress.discard(shard_id)

    def _sender_path(self) -> str:
        s = self.sender
        path = s.path
        addr = path.address
        if not addr.has_global_scope:
            addr = self.context.system.provider.default_address
        return f"{addr}{path.to_string_without_address()}"

    # -- rebalance (reference: RebalanceWorker in ShardCoordinator.scala) ----
    def _start_rebalance(self, shard_id: str) -> None:
        if shard_id in self.rebalance_in_progress or shard_id not in self.shards:
            return
        self.rebalance_in_progress.add(shard_id)
        targets = set(self.regions) | self.proxies
        self.rebalance_ack_wait[shard_id] = set(targets)
        msg = BeginHandOff(shard_id)
        for r in targets:
            self._ref(r).tell(msg, self.self_ref)

    def _on_begin_handoff_ack(self, shard_id: str) -> None:
        waiting = self.rebalance_ack_wait.get(shard_id)
        if waiting is None:
            return
        waiting.discard(self._sender_path())
        if not waiting:
            del self.rebalance_ack_wait[shard_id]
            region = self.shards.get(shard_id)
            if region is not None:
                self._ref(region).tell(HandOff(shard_id), self.self_ref)
            else:
                self.rebalance_in_progress.discard(shard_id)
