"""Device-backed cluster sharding: entities as rows on the mesh.

This closes the loop VERDICT r1 flagged between the host sharding API
(akka_tpu/sharding/) and the device runtime (akka_tpu/batched/sharded.py):
`ClusterSharding.init` with a BatchedBehavior entity type lays entities out
as rows in a ShardedBatchedSystem, a coordinator-owned placement table maps
logical shards onto physical row blocks (and therefore devices), rebalance
is a slab copy that rides XLA's cross-device transfers, and cross-shard
tells are the existing all_to_all exchange.

Reference parity:
- entities→shards→regions resolution: sharding/ShardRegion.scala:1046
  deliverMessage (extractShardId → GetShardHome → forward); here the
  "home" lookup is the `shard_block` table — one int32 per logical shard.
- ShardCoordinator least-shard allocation + rebalance:
  sharding/ShardCoordinator.scala:90-201; here allocation assigns logical
  shards round-robin over physical blocks and rebalance(shard, to_block)
  slab-copies state between blocks and rewrites in-flight message
  destinations.
- remember-entities: sharding/Shard.scala — entity ids allocate rows on
  first use and survive in the host-side registry.

Layout: logical shard s occupies ONE physical block of `entities_per_shard`
contiguous rows; physical block b lives on device b // blocks_per_device.
The placement table `shard_block: int32[n_shards]` is replicated on device
(ctx.tables["shard_row_base"]) so entity behaviors can address any entity
as `tables["shard_row_base"][shard] + index` — placement changes never
recompile behaviors.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..batched.behavior import BatchedBehavior
from ..batched.sharded import ShardedBatchedSystem


@dataclass
class DeviceEntity:
    """Spec for a device-resident sharded entity type (the typed
    Entity(...) analogue, sharding-typed ClusterSharding.scala:178)."""

    type_name: str
    behavior: BatchedBehavior
    n_shards: int = 256
    entities_per_shard: int = 4096
    n_devices: Optional[int] = None
    spare_blocks: Optional[int] = None   # default: one per device
    payload_width: int = 4
    out_degree: int = 1
    mailbox_slots: int = 0
    host_inbox_per_shard: int = 256
    extra_behaviors: Sequence[BatchedBehavior] = field(default_factory=tuple)
    # forwarded to ShardedBatchedSystem: pin the delivery kernel family
    # (None = auto). The batched-ask tests pin both backends to prove the
    # conserved-value invariant is bit-identical across them.
    delivery_backend: Optional[str] = None
    # optional coordination lease (cluster_tools.lease.Lease): when set,
    # rebalance() must ACQUIRE it first — the reference guards shard
    # hand-off with a lease so two coordinators can't move shards
    # concurrently (SplitBrainResolver.scala:45-55 lease plumbing /
    # ShardCoordinator lease usage)
    lease: Optional[Any] = None
    # optional durable remember-entities store (sharding/region.py SPI):
    # first-touch allocations are add()ed, and restore() respawns every
    # remembered id BEFORE replay — a restarted region re-hosts its
    # entities with zero client traffic (Shard.scala remember-entities)
    remember_store: Optional[Any] = None


class DeviceEntityRef:
    """Host handle to one device entity (EntityRef analogue)."""

    __slots__ = ("region", "shard", "index", "entity_id")

    def __init__(self, region: "DeviceShardRegion", shard: int, index: int,
                 entity_id: str):
        self.region = region
        self.shard = shard
        self.index = index
        self.entity_id = entity_id

    @property
    def row(self) -> int:
        return self.region.row_of(self.shard, self.index)

    def tell(self, payload, mtype: int = 0) -> None:
        self.region.system.tell(self.row, payload, mtype)

    def read_state(self, col: str):
        return self.region.system.read_state(col, np.asarray([self.row]))[0]

    def __repr__(self):
        return (f"DeviceEntityRef({self.region.type_name}/"
                f"{self.entity_id} shard={self.shard} row={self.row})")


class DeviceShardRegion:
    """Owns the ShardedBatchedSystem + the logical→physical placement.

    The region IS the data plane; the (host) ShardCoordinator role — who
    owns which shard, when to rebalance — is the placement table here,
    driven by least-loaded allocation and explicit/auto rebalance."""

    def __init__(self, spec: DeviceEntity, mesh=None):
        import jax
        self.type_name = spec.type_name
        self.spec = spec
        n_devices = spec.n_devices or len(jax.devices())
        blocks_per_device = -(-spec.n_shards // n_devices)  # ceil
        spare = spec.spare_blocks if spec.spare_blocks is not None \
            else n_devices
        # pad spares so every device hosts the same number of blocks
        # (the mesh shards the row space evenly). The ask promise rows
        # (bridge reply-row protocol) are carved out of one spare/padding
        # block so capacity does not grow for regions that never ask; only
        # a region with NO free block at all pays for an extra stripe
        total_blocks = spec.n_shards + spare
        if total_blocks % n_devices:
            total_blocks += n_devices - total_blocks % n_devices
        if total_blocks == spec.n_shards:  # zero spares and no padding
            total_blocks += n_devices
        self.n_devices = n_devices
        self.blocks_per_device = total_blocks // n_devices
        self.total_blocks = total_blocks
        self.eps = spec.entities_per_shard
        capacity = total_blocks * self.eps

        self.system = ShardedBatchedSystem(
            capacity=capacity,
            behaviors=[spec.behavior, *spec.extra_behaviors,
                       self._promise_behavior(spec)],
            mesh=mesh, n_devices=n_devices,
            payload_width=spec.payload_width, out_degree=spec.out_degree,
            host_inbox_per_shard=spec.host_inbox_per_shard,
            mailbox_slots=spec.mailbox_slots,
            reroute_strays=True,  # messages follow rebalanced shards
            delivery_backend=spec.delivery_backend,
            # raise ATT_LATCH_BIT while any promise latch is high: the
            # batched ask engine polls "anyone replied?" off the tiny
            # attention word instead of a wide per-round state read
            attention_latch_col="__promise_replied")
        self._ask_latch_wired = True

        # initial allocation: shard s -> block s striped over devices
        # round-robin (LeastShardAllocation on an empty cluster assigns
        # evenly, ShardCoordinator.scala:201)
        order = np.arange(spec.n_shards, dtype=np.int32)
        stripe = (order % n_devices) * self.blocks_per_device + \
            (order // n_devices)
        self._shard_block = stripe.astype(np.int32)
        used = set(int(b) for b in self._shard_block)
        free = sorted(set(range(total_blocks)) - used)
        # the last free block becomes the promise block (never a shard
        # home, never a rebalance target); its rows resolve asks
        self._promise_block = free.pop()
        self._free_blocks: List[int] = free
        self._promise_free: List[int] = list(range(self.eps))
        # slots whose ask timed out with the reply still in flight: parked
        # here until the row's `__promise_replied` latch is observed True
        # (the late reply landed), then returned to the free list
        self._promise_retired: List[int] = []
        self._promise_spawned = False
        self._stat_ask_exhausted = 0  # typed AskPoolExhausted fast-fails
        # causal tracing (event/tracing.py): the ask engine reads these —
        # None tracer keeps the engine on its one-predicate quiet path;
        # _wave_seq numbers every execute_ask_batch invocation
        self.tracer = None
        self._wave_seq = 0
        self._lock = threading.Lock()
        # asks AND maintenance ops (checkpoint/rebalance/failover/restore)
        # serialize: all of them step or swap the shared runtime. Reentrant
        # because rebalance checkpoints under its own hold.
        self._ask_lock = threading.RLock()
        self._stray_steps_left = 0         # hand-off drain window
        # durability (attach_journal): WAL + slab snapshots + the placement
        # sidecar make the region restorable in a fresh process and
        # rebuildable on a survivor mesh (failover)
        self.checkpoint_dir: Optional[str] = None
        self._journal = None
        self._ents_fh = None
        # durable entity layer (attach_entity_journal): per-entity event
        # log group-committed at the ask-wave boundary; restore replays
        # snapshot + event tail back into the durable state column
        self._entity_journal = None
        self._durable_col = "total"
        self._per_event_fsync = False
        self._durable_replayed_totals: Optional[Dict[str, float]] = None

        # entity registry: per-shard entity_id -> index (remember-entities)
        self._entities: List[Dict[str, int]] = [dict()
                                                for _ in range(spec.n_shards)]
        # reverse view (index -> entity_id) so the wave-boundary event
        # emitter can name the entities a resolved ask touched without an
        # O(entities) scan per wave
        self._rev: List[Dict[int, str]] = [dict()
                                           for _ in range(spec.n_shards)]
        self._spawned = np.zeros((spec.n_shards,), np.int32)

        self._sync_tables()

    # ----------------------------------------------------------------- ask
    @staticmethod
    def _promise_behavior(spec: DeviceEntity) -> BatchedBehavior:
        """Promise rows (batched/bridge.py protocol on the mesh): a reply
        emitted by a remote-shard entity crosses the all_to_all exchange
        into this row; the host polls the replied latch."""
        from ..batched import Emit, behavior
        P, k = spec.payload_width, spec.out_degree

        if spec.mailbox_slots > 0:
            @behavior("__shard_promise",
                      {"__promise_reply": ((P,), jnp.float32),
                       "__promise_replied": ((), jnp.bool_)}, inbox="slots")
            def promise(state, mailbox, ctx):
                inbox = mailbox.reduce()
                got = inbox.count > 0
                return ({"__promise_reply": jnp.where(
                             got, inbox.sum, state["__promise_reply"]),
                         "__promise_replied":
                             state["__promise_replied"] | got},
                        Emit.none(k, P))
        else:
            @behavior("__shard_promise",
                      {"__promise_reply": ((P,), jnp.float32),
                       "__promise_replied": ((), jnp.bool_)})
            def promise(state, inbox, ctx):
                got = inbox.count > 0
                return ({"__promise_reply": jnp.where(
                             got, inbox.sum, state["__promise_reply"]),
                         "__promise_replied":
                             state["__promise_replied"] | got},
                        Emit.none(k, P))
        return promise

    def _ensure_promise_rows(self) -> None:
        with self._lock:
            if self._promise_spawned:
                return
            self._promise_spawned = True
        sys = self.system
        base = self._promise_block * self.eps
        rows = jnp.arange(base, base + self.eps, dtype=jnp.int32)
        bid = len(sys.behaviors) - 1  # promise behavior registered last
        sys.behavior_id = sys.behavior_id.at[rows].set(bid)
        sys.alive = sys.alive.at[rows].set(True)

    def ask(self, shard: int, index: int, message, steps: int = 2,
            max_extra_steps: int = 8):
        """Request/response to entity (shard, index) across the mesh: the
        reply-to promise row rides the payload's LAST column (the batched
        bridge's ask convention — the entity behavior answers with
        `Emit.single(reply_dst(payload), ...)`); returns the reply payload.

        Runs `steps` steps (request out + reply back), then single steps up
        to `max_extra_steps` more before declaring the ask unanswered.
        A timed-out ask's slot is retired, not reused — a late reply
        landing in a recycled row would otherwise answer the wrong ask.
        Retirement is not permanent: once the late reply is observed to
        have landed (`__promise_replied` True) the slot is reclaimed.

        Implemented as a batch of one through the ask micro-batching
        engine (ask_batch.py) — a solo batch runs the exact step schedule
        this method always ran, so results are bit-identical."""
        out = self.ask_many([(shard, index, message)], steps=steps,
                            max_extra_steps=max_extra_steps)[0]
        if isinstance(out, BaseException):
            raise out
        return out

    def attach_tracer(self, tracer) -> None:
        """Wire the causal tracer (event/tracing.py) into the ask engine:
        wave/member spans are emitted for sampled asks, and the tracer's
        step source becomes this region's runtime — the authoritative
        ATT_STEP axis for the spans describing its waves. Failover swaps
        `self.system`; the lambda reads it dynamically, so spans keep
        stamping the LIVE step axis across rebuilds."""
        self.tracer = tracer
        if tracer is not None:
            tracer.step_fn = lambda: self.system._host_step

    def ask_many(self, requests: Sequence[Any], steps: int = 2,
                 max_extra_steps: int = 8,
                 ctxs: Optional[Sequence[Any]] = None) -> List[Any]:
        """Coalesced asks: `requests` is a sequence of
        `(shard, index, message)`; every member gets its own promise row,
        all the tells go out in ONE flush, and the whole batch shares one
        step budget instead of paying N serialized device rounds
        (gateway concurrency rides this via AskBatcher).

        Returns a list aligned with `requests`: the reply payload
        (np.ndarray), or the per-ask exception INSTANCE (AskPoolExhausted
        / TimeoutError / ValueError) — one member's failure never fails
        its batch-mates. Per-ask timeout/retirement semantics match
        `ask` exactly; asks to the SAME entity serialize across waves
        within the batch (linearized per-entity totals)."""
        from .ask_batch import BatchAsk, execute_ask_batch
        batch = [BatchAsk(int(s), int(i), m, int(steps),
                          int(max_extra_steps)) for s, i, m in requests]
        if ctxs is not None:  # per-member span ctxs (one window, N traces)
            for a, c in zip(batch, ctxs):
                a.trace = c
        with self._ask_lock:
            execute_ask_batch(self, batch)
        return [a.outcome for a in batch]

    def _reclaim_promise_slots(self) -> int:
        """Return retired ask slots whose `__promise_replied` latch is now
        True to the free list. A True latch means the late reply HAS landed,
        so no in-flight message can target the row any more and recycling
        cannot mis-deliver (every ask resets the latch before use). Called
        once per ask BATCH; safe to call directly. Returns the number
        reclaimed."""
        with self._lock:
            retired = list(self._promise_retired)
        if not retired:
            return 0
        # one static-slice fetch of the whole promise block's latch column
        # (read_promise_block: constant shape -> one XLA program ever; the
        # old per-retired-count gather recompiled for every distinct count)
        from ..batched.bridge import read_promise_block
        base = self._promise_block * self.eps
        landed, _ = read_promise_block(self.system.state, base, self.eps,
                                       "__promise_replied")
        freed = [s for s in retired if bool(landed[s])]
        with self._lock:
            for s in freed:
                self._promise_retired.remove(s)
                self._promise_free.append(s)
        return len(freed)

    # ------------------------------------------------------------ addressing
    def shard_of(self, entity_id: str) -> int:
        """extractShardId: PROCESS-STABLE hash (ShardRegion.scala:42-43) —
        FNV-1a over the id's bytes, never Python's salted hash()."""
        h = 2166136261
        for byte in entity_id.encode("utf-8"):
            h = ((h ^ byte) * 16777619) & 0xFFFFFFFF
        return h % self.spec.n_shards

    def row_of(self, shard: int, index: int) -> int:
        return int(self._shard_block[shard]) * self.eps + index

    def device_of_shard(self, shard: int) -> int:
        return int(self._shard_block[shard]) // self.blocks_per_device

    def _sync_tables(self) -> None:
        self.system.set_tables({
            "shard_row_base": (self._shard_block.astype(np.int32)
                               * np.int32(self.eps))})

    # ------------------------------------------------------------- entities
    def entity_ref(self, entity_id: str) -> DeviceEntityRef:
        """Resolve (allocating on first use — StartEntity semantics) the
        device entity for an id."""
        shard = self.shard_of(entity_id)
        new = False
        with self._lock:
            idx = self._entities[shard].get(entity_id)
            if idx is None:
                new = True
                idx = len(self._entities[shard])
                if idx >= self.eps:
                    raise RuntimeError(
                        f"shard {shard} full ({self.eps} entities)")
                self._entities[shard][entity_id] = idx
                self._rev[shard][idx] = entity_id
                if getattr(self, "_ents_fh", None) is not None:
                    self._ents_fh.write(f"{shard}\t{idx}\t{entity_id}\n")
                    self._ents_fh.flush()
        if new and self.spec.remember_store is not None:
            self.spec.remember_store.add(self.type_name, str(shard),
                                         entity_id)
        self._ensure_spawned(shard, idx)
        return DeviceEntityRef(self, shard, idx, entity_id)

    def _ensure_spawned(self, shard: int, idx: int) -> None:
        with self._lock:
            if idx < self._spawned[shard]:
                return
            n_new = idx + 1 - self._spawned[shard]
            start_idx = int(self._spawned[shard])
            self._spawned[shard] = idx + 1
            base = int(self._shard_block[shard]) * self.eps
        rows = np.arange(base + start_idx, base + start_idx + n_new,
                         dtype=np.int32)
        # device writes go under the ASK lock, not the registry lock: the
        # step donates these buffers, so activation must never race an
        # in-flight run, and two threads' read-modify-writes must not
        # overwrite each other's alive updates (each .at produces a NEW
        # array from its thread's snapshot). Taken OUTSIDE self._lock —
        # the lock order everywhere is _ask_lock then _lock.
        with self._ask_lock:
            sys = self.system
            sys.behavior_id = sys.behavior_id.at[jnp.asarray(rows)].set(0)
            sys.alive = sys.alive.at[jnp.asarray(rows)].set(True)

    def allocate_all(self) -> None:
        """Bulk-activate every entity slot (bench path: 256x4k rows live
        without a million Python calls)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        sys = self.system
        alive = np.zeros((sys.capacity,), bool)
        behavior_id = np.zeros((sys.capacity,), np.int32)
        for s in range(self.spec.n_shards):
            base = int(self._shard_block[s]) * self.eps
            alive[base:base + self.eps] = True
            self._spawned[s] = self.eps
        # the wholesale replace must preserve promise rows a prior ask()
        # spawned (asks after allocate_all spawn lazily as usual; rows
        # never asked stay dead so the user-visible alive mask is exact)
        with self._lock:
            if self._promise_spawned:
                pbase = self._promise_block * self.eps
                alive[pbase:pbase + self.eps] = True
                behavior_id[pbase:pbase + self.eps] = len(sys.behaviors) - 1
        shard = NamedSharding(sys.mesh, P(sys.axis))
        sys.alive = jax.device_put(jnp.asarray(alive), shard)
        sys.behavior_id = jax.device_put(
            jnp.asarray(behavior_id), shard)

    # ------------------------------------------------------------- rebalance
    def rebalance(self, shard: int, to_device: Optional[int] = None) -> int:
        """Move one logical shard's block to another device (slab copy —
        the hand-off of ShardCoordinator rebalance without the host round
        trips: state moves as ONE cross-device array copy, and in-flight
        messages addressed into the old block are re-pointed).

        Returns the new physical block index."""
        with self._ask_lock:
            return self._rebalance_locked(shard, to_device)

    def _rebalance_locked(self, shard: int,
                          to_device: Optional[int] = None) -> int:
        lease = self.spec.lease
        if lease is not None and not lease.acquire():
            raise RuntimeError(
                f"rebalance of shard {shard} denied: coordination lease "
                f"{lease.settings.lease_name!r} is held elsewhere")
        # hand-off window: the stray-forwarding step variant runs until the
        # in-flight messages bound for the old block have drained (the
        # steady-state step skips the stray pass entirely — r4 weak #5)
        self.system.enter_stray_mode()
        self._stray_steps_left = max(self._stray_steps_left, 3)
        with self._lock:
            old_block = int(self._shard_block[shard])
            candidates = self._free_blocks
            if not candidates:
                raise RuntimeError("no spare blocks to rebalance into")
            if to_device is None:
                new_block = candidates[0]
            else:
                on_dev = [b for b in candidates
                          if b // self.blocks_per_device == to_device]
                if not on_dev:
                    raise RuntimeError(f"no spare block on device {to_device}")
                new_block = on_dev[0]
            self._free_blocks.remove(new_block)
            self._free_blocks.append(old_block)
            self._free_blocks.sort()
            self._shard_block[shard] = new_block

        sys = self.system
        eps = self.eps
        old = slice(old_block * eps, (old_block + 1) * eps)
        new = slice(new_block * eps, (new_block + 1) * eps)
        for col in sys.state:
            arr = sys.state[col]
            sys.state[col] = arr.at[new].set(arr[old])
        sys.behavior_id = sys.behavior_id.at[new].set(sys.behavior_id[old])
        sys.alive = sys.alive.at[new].set(sys.alive[old]) \
                                 .at[old].set(False)
        # re-point in-flight messages bound for the moved block — BOTH the
        # device inbox and tells still sitting in the host staging queue
        delta = (new_block - old_block) * eps
        in_old = (sys.inbox_dst >= old.start) & (sys.inbox_dst < old.stop)
        sys.inbox_dst = jnp.where(in_old, sys.inbox_dst + delta,
                                  sys.inbox_dst)
        with sys._lock:
            sys._host_staged = [
                (d + delta if old.start <= d < old.stop else d, t, p)
                for d, t, p in sys._host_staged]
        self._sync_tables()
        if self.checkpoint_dir is not None:
            # the WAL records tells, not placement moves: drain the
            # hand-off window and snapshot NOW, so recovery never replays
            # post-move traffic onto pre-move block homes (and never
            # snapshots the stray-mode inbox layout)
            guard = 64  # bounded: each pass forwards strays one hop
            while self._stray_steps_left > 0 and guard > 0:
                guard -= self._stray_steps_left
                self.run(self._stray_steps_left)
            self.checkpoint()
        return new_block

    # ----------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        """ClusterShardingStats analogue (messages.py:137)."""
        per_device: Dict[int, int] = {}
        for s in range(self.spec.n_shards):
            d = self.device_of_shard(s)
            per_device[d] = per_device.get(d, 0) + int(self._spawned[s])
        return {"type": self.type_name,
                "shards": self.spec.n_shards,
                "entities": int(self._spawned.sum()),
                "entities_per_device": per_device,
                "free_blocks": list(self._free_blocks)}

    def ask_pool_stats(self) -> Dict[str, Any]:
        """Promise-slot occupancy for this region's ask block (the
        admission signal — see BatchedRuntimeHandle.ask_pool_stats).
        `retired` slots are quarantined timeouts still counted in-flight;
        `exhausted` counts typed AskPoolExhausted fast-fails."""
        with self._lock:
            free = len(self._promise_free)
            retired = len(self._promise_retired)
            exhausted = self._stat_ask_exhausted
        size = self.eps
        in_flight = max(0, size - free)
        return {"size": size, "free": free, "in_flight": in_flight,
                "retired": retired, "exhausted": exhausted,
                "occupancy": (in_flight / size) if size else 1.0}

    # ----------------------------------------------------- durability/failover
    def attach_journal(self, directory: str,
                       fsync_every_n: int = 1):
        """Arm the write-ahead tell journal + checkpoint directory: every
        staged tell journals BEFORE enqueue (zero lost acknowledged writes
        across kill -9 — append flushes per record; fsync batches by
        `fsync_every_n`, the akka.persistence.tell-journal.fsync-every-n
        group-commit knob). checkpoint()/restore()/failover() need this."""
        from ..persistence.tell_journal import TellJournal
        os.makedirs(directory, exist_ok=True)
        self.checkpoint_dir = directory
        self._journal = TellJournal(
            os.path.join(directory, "tells.wal"),
            flight_recorder=getattr(self.system, "flight_recorder", None),
            fsync_every_n=fsync_every_n)
        self.system.tell_journal = self._journal
        # first-touch entity allocations are WAL'd too (remember-entities
        # durability): a tell journaled to an entity allocated AFTER the
        # last snapshot must find its row alive on replay. One line per
        # allocation, flushed — same process-crash guarantee as the tell
        # WAL's flush-per-append.
        self._ents_fh = open(os.path.join(directory, "entities.log"), "a")
        return self._journal

    def attach_entity_journal(self, directory: Optional[str] = None,
                              fsync_every_n: int = 1,
                              snapshot_every: int = 64,
                              compact_every: int = 8192,
                              state_col: str = "total",
                              registry=None,
                              per_event_fsync: bool = False):
        """Arm the durable entity layer (ISSUE 15): every ok ask-wave's
        events (entity_id, op, value, step) land as ONE group-committed
        record in `entities.journal` BEFORE the wave's outcomes reach the
        caller — an acked write is durable by the time the ack exists.
        `fsync_every_n` counts WAVES (1 = one fsync per ask wave, the
        machine-crash-safe serving default; appends always flush, so a
        process kill -9 loses nothing at any n). restore()/failover()
        then rebuild each entity's `state_col` from snapshot + event
        tail — the acked frontier — after the slab+WAL replay.

        `state_col` is the behavior's durable scalar column (the counter
        family's "total"); the journaled op byte leaves room for richer
        folds without a format change. `per_event_fsync=True` is the
        bench A/B degenerate leg (one record + one fsync per EVENT —
        what a per-entity synchronous write would cost), never the
        serving configuration."""
        from ..persistence.entity_journal import EntityJournal
        directory = directory or self.checkpoint_dir
        if directory is None:
            raise RuntimeError(
                "attach_entity_journal needs a directory (or "
                "attach_journal first)")
        os.makedirs(directory, exist_ok=True)
        self._durable_col = state_col
        self._per_event_fsync = per_event_fsync
        self._entity_journal = EntityJournal(
            os.path.join(directory, "entities.journal"),
            flight_recorder=getattr(self.system, "flight_recorder", None),
            fsync_every_n=fsync_every_n, snapshot_every=snapshot_every,
            compact_every=compact_every, registry=registry)
        return self._entity_journal

    def detach_entity_journal(self) -> None:
        """Disarm (bench A/B legs): closes the journal and stops the
        wave-boundary emission; state already journaled stays on disk."""
        ej, self._entity_journal = self._entity_journal, None
        self._per_event_fsync = False
        if ej is not None:
            ej.close()

    def _commit_entity_events(self, resolved) -> None:
        """Wave-boundary group commit (called by execute_ask_batch with
        the wave's ok members while the caller still holds `_ask_lock`):
        name each resolved (shard, index) via the reverse registry, drop
        no-op events (a gateway get is add(0) — no durable effect), and
        append everything as one record. The fsync (per fsync_every_n
        waves) happens HERE, before any ack leaves — zero lost acked
        writes across a machine crash, not just a process kill.

        Members are `(shard, index, message)` or — when the gateway runs
        idempotent-session dedup (ISSUE 20) — `(shard, index, message,
        dedup_key, outcome)`: keyed members additionally record their ok
        reply `(tenant, id, status, value)` in the SAME record, so the
        dedup frontier is covered by the exact fsync that covers the
        events it acknowledges (commit-before-ack extends to the reply
        cache). A wave of keyed gets writes a replies-only record."""
        ej = self._entity_journal
        if ej is None:
            return
        from ..persistence.entity_journal import OP_ADD
        from ..serialization.frames import ST_OK
        events = []
        replies = []
        with self._lock:
            for member in resolved:
                shard, index, message = member[0], member[1], member[2]
                body = np.asarray(message, np.float64).reshape(-1)
                value = float(body[0]) if body.size else 0.0
                if len(member) >= 5 and member[3] is not None:
                    out = np.asarray(member[4], np.float64).reshape(-1)
                    replies.append((member[3][0], member[3][1], ST_OK,
                                    float(out[0]) if out.size else 0.0))
                if value == 0.0:
                    continue
                eid = self._rev[shard].get(index)
                if eid is not None:
                    events.append((eid, OP_ADD, value))
        if events or replies:
            ej.append_wave(int(self.system._host_step), events,
                           per_event_fsync=self._per_event_fsync,
                           replies=replies)

    def _respawn_remembered(self) -> None:
        """Re-host every remembered entity with zero client traffic:
        union the durable remember-entities store (spec.remember_store)
        and the entity journal's fold into the registry, allocating rows
        for ids the sidecar/entities.log missed (e.g. a store shared by a
        prior incarnation on another node). Runs BEFORE replay so the
        replayed totals always find their rows alive."""
        ids = set()
        store = self.spec.remember_store
        if store is not None:
            for shard in range(self.spec.n_shards):
                ids.update(store.remembered(self.type_name, str(shard)))
        if self._entity_journal is not None:
            ids.update(self._entity_journal.totals())
        for eid in sorted(ids):
            self.entity_ref(eid)

    def _replay_entities(self) -> Dict[str, float]:
        """Reconstruct per-entity durable state from the entity journal
        (snapshot + event tail = the acked frontier) and write it into
        the durable state column in ONE pow2-floor-64-padded scatter.
        Runs AFTER the slab+WAL replay flush: the WAL may have re-applied
        writes that were never acked (in-flight at the crash, timed-out
        asks) — overwriting with the journal fold pins restored state to
        exactly what clients were acknowledged, keeping
        acked_sum <= final_total <= sent_sum tight on the left."""
        ej = self._entity_journal
        if ej is None:
            return {}
        totals = ej.totals()
        self._durable_replayed_totals = totals
        if not totals:
            return totals
        rows, vals = [], []
        for eid, total in totals.items():
            ref = self.entity_ref(eid)
            rows.append(ref.row)
            vals.append(total)
        sys = self.system
        n = len(rows)
        pad = max(64, 1 << (n - 1).bit_length()) - n
        rows_np = np.asarray(rows, np.int32)
        vals_np = np.asarray(vals, np.float32)
        if pad:  # duplicate leading index, identical value: idempotent
            rows_np = np.concatenate([rows_np,
                                      np.full(pad, rows_np[0], np.int32)])
            vals_np = np.concatenate([vals_np,
                                      np.full(pad, vals_np[0], np.float32)])
        idx = jnp.asarray(rows_np)
        col = sys.state[self._durable_col]
        sys.state[self._durable_col] = col.at[idx].set(
            jnp.asarray(vals_np, col.dtype))
        fr = getattr(sys, "flight_recorder", None)
        if fr is not None and getattr(fr, "enabled", False):
            fr.event("entity_replayed", entities=len(totals),
                     events=int(sum(ej.replayed_events().values())),
                     step=int(sys._host_step))
        return totals

    def _sidecar_path(self) -> str:
        return os.path.join(self.checkpoint_dir, "region.json")

    def _write_sidecar(self) -> None:
        """Placement + entity registry next to the slab snapshot. The slab
        holds state/alive/behavior_id by ROW; this records which logical
        shard owns which block and which entity_id owns which row — the
        host half a fresh process cannot rederive."""
        with self._lock:
            doc = {"shard_block": [int(b) for b in self._shard_block],
                   "free_blocks": list(self._free_blocks),
                   "promise_block": int(self._promise_block),
                   "promise_spawned": bool(self._promise_spawned),
                   "promise_free": list(self._promise_free),
                   "promise_retired": list(self._promise_retired),
                   "entities": [dict(d) for d in self._entities],
                   "spawned": [int(s) for s in self._spawned]}
        tmp = self._sidecar_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._sidecar_path())

    def checkpoint(self, keep: int = 3) -> str:
        """Quiescent-barrier slab snapshot + placement sidecar + WAL
        compaction (ShardedBatchedSystem.checkpoint underneath)."""
        if self.checkpoint_dir is None:
            raise RuntimeError("attach_journal(directory) before checkpoint")
        with self._ask_lock:
            path = self.system.checkpoint(self.checkpoint_dir, keep=keep)
            self._write_sidecar()
            if self._entity_journal is not None:
                # every event so far is covered by the live fold: rewrite
                # the log as one snap-all record (bounded replay tail)
                self._entity_journal.compact()
        # allocations up to here are covered by the sidecar: reset the log
        if self._ents_fh is not None:
            self._ents_fh.close()
            self._ents_fh = open(
                os.path.join(self.checkpoint_dir, "entities.log"), "w")
        return path

    def restore(self) -> int:
        """Crash recovery in a fresh process: build an identically-spec'd
        region, attach_journal(same dir), then restore() — loads the
        placement sidecar, re-points the device tables, restores the
        latest slab snapshot and replays the WAL to the crash frontier.
        Returns the recovered host step counter."""
        from ..persistence.slab_snapshot import latest_slab_path
        if self.checkpoint_dir is None:
            raise RuntimeError("attach_journal(directory) before restore")
        with self._ask_lock:
            path = latest_slab_path(self.checkpoint_dir)
            if path is None:
                raise FileNotFoundError(
                    f"no slab snapshot under {self.checkpoint_dir}")
            with open(self._sidecar_path()) as f:
                doc = json.load(f)
            self._load_sidecar(doc)
            self._merge_entity_log()
            # durable remember-entities: allocate rows for ids known only
            # to the store / entity journal BEFORE replay, so replayed
            # state always finds its rows alive (and a restarted region
            # re-hosts every remembered entity with zero client traffic)
            self._respawn_remembered()
            self._sync_tables()  # tables feed the replayed steps
            step = self._restore_and_replay(path)
            # entity-journal replay LAST: pin durable columns to the
            # acked frontier on top of the slab+WAL reconstruction
            self._replay_entities()
            return step

    def _merge_entity_log(self) -> None:
        """Fold entities.log into the registry: allocations since the last
        sidecar write (idempotent — checkpoint truncates the log after the
        sidecar covers it, so duplicates only appear across a crash in
        between)."""
        path = os.path.join(self.checkpoint_dir, "entities.log")
        if not os.path.exists(path):
            return
        with open(path) as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if len(parts) != 3:
                    continue  # torn tail of a crashed append
                shard, idx = int(parts[0]), int(parts[1])
                with self._lock:
                    self._entities[shard].setdefault(parts[2], idx)
                    self._rev[shard][self._entities[shard][parts[2]]] = \
                        parts[2]
                    self._spawned[shard] = max(int(self._spawned[shard]),
                                               idx + 1)

    def _restore_and_replay(self, path: str) -> int:
        """Slab restore, then host-side row re-activation, THEN the WAL
        replay — replayed tells to entities allocated after the snapshot
        must find their rows alive — then a 2-step flush so the crash-
        frontier batch is applied to state, not just re-staged."""
        from ..persistence.tell_journal import replay_journal
        sys = self.system
        step = sys.restore(path, journal=None)
        self._reactivate_rows()
        if self._journal is not None:
            step = replay_journal(sys, self._journal)
        sys.run(2)
        sys.block_until_ready()
        return step

    def _reactivate_rows(self) -> None:
        import jax.numpy as jnp_
        sys = self.system
        rows: List[int] = []
        with self._lock:
            for shard in range(self.spec.n_shards):
                base = int(self._shard_block[shard]) * self.eps
                rows.extend(range(base, base + int(self._spawned[shard])))
        if rows:
            idx = jnp_.asarray(np.asarray(rows, np.int32))
            sys.behavior_id = sys.behavior_id.at[idx].set(0)
            sys.alive = sys.alive.at[idx].set(True)
        with self._lock:
            if self._promise_spawned:
                pbase = self._promise_block * self.eps
                pidx = jnp_.arange(pbase, pbase + self.eps, dtype=jnp_.int32)
                sys.behavior_id = sys.behavior_id.at[pidx].set(
                    len(sys.behaviors) - 1)
                sys.alive = sys.alive.at[pidx].set(True)

    def _load_sidecar(self, doc: Dict[str, Any]) -> None:
        with self._lock:
            self._shard_block = np.asarray(doc["shard_block"], np.int32)
            self._free_blocks = [int(b) for b in doc["free_blocks"]]
            self._promise_block = int(doc["promise_block"])
            self._promise_spawned = bool(doc["promise_spawned"])
            self._promise_free = [int(s) for s in doc["promise_free"]]
            self._promise_retired = [int(s) for s in doc["promise_retired"]]
            self._entities = [{str(k): int(v) for k, v in d.items()}
                              for d in doc["entities"]]
            self._rev = [{v: k for k, v in d.items()}
                         for d in self._entities]
            self._spawned = np.asarray(doc["spawned"], np.int32)

    def failover(self, survivors: Sequence[Any]) -> int:
        """Evict lost devices and rebuild the region on the survivor mesh
        from the latest snapshot + WAL — the MeshSentinel force-evict
        recipe applied to the sharded-entity region. The placement table
        is row-space (device-independent), so shard homes, entity rows and
        the promise block all survive; only blocks_per_device changes.
        Requires total_blocks divisible by the survivor count (the mesh
        stripes the row space evenly). Returns the recovered step."""
        with self._ask_lock:
            return self._failover_locked(survivors)

    def _failover_locked(self, survivors: Sequence[Any]) -> int:
        from ..parallel.mesh import make_mesh
        from ..persistence.slab_snapshot import latest_slab_path
        if self.checkpoint_dir is None:
            raise RuntimeError("attach_journal(directory) before failover")
        n_surv = len(survivors)
        if n_surv < 1 or self.total_blocks % n_surv:
            raise RuntimeError(
                f"cannot re-stripe {self.total_blocks} blocks over "
                f"{n_surv} survivors")
        path = latest_slab_path(self.checkpoint_dir)
        if path is None:
            raise FileNotFoundError(
                f"no slab snapshot under {self.checkpoint_dir}")
        old = self.system
        old_journal = self._journal
        spec = self.spec
        mesh = make_mesh(devices=list(survivors), axis_name=old.axis)
        new = ShardedBatchedSystem(
            capacity=old.capacity,
            behaviors=[spec.behavior, *spec.extra_behaviors,
                       self._promise_behavior(spec)],
            mesh=mesh, n_devices=n_surv,
            payload_width=spec.payload_width, out_degree=spec.out_degree,
            host_inbox_per_shard=spec.host_inbox_per_shard,
            mailbox_slots=spec.mailbox_slots,
            reroute_strays=True,
            delivery_backend=spec.delivery_backend,
            attention_latch_col="__promise_replied")
        new.flight_recorder = getattr(old, "flight_recorder", None)
        self.n_devices = n_surv
        self.blocks_per_device = self.total_blocks // n_surv
        self._stray_steps_left = 0
        self.system = new
        self._sync_tables()  # before replay: behaviors read shard_row_base
        step = self._restore_and_replay(path)
        new.tell_journal = old_journal  # re-arm AFTER replay (no re-journal)
        # durable entity layer: the in-process journal's fold is current,
        # so the survivor mesh gets the same acked-frontier overwrite a
        # fresh-process restore gets (in-flight unacked asks just failed)
        self._replay_entities()
        return step

    # ------------------------------------------------------------------ run
    def run(self, n_steps: int = 1) -> None:
        # confine the ~2x-cost stray program to the drain window: a big
        # batched run() after a rebalance must not scan hundreds of steps
        # through the hand-off variant (exactly the steady-state tax the
        # mode split removed)
        while n_steps > 0 and self._stray_steps_left > 0:
            k = min(n_steps, self._stray_steps_left)
            self.system.run(k)
            n_steps -= k
            self._stray_steps_left -= k
            if self._stray_steps_left <= 0:
                self.system.block_until_ready()
                if not self.system.exit_stray_mode():
                    self._stray_steps_left = 1  # still draining: retry
        if n_steps > 0:
            self.system.run(n_steps)

    def block_until_ready(self) -> None:
        self.system.block_until_ready()
