"""Ask micro-batching: coalesce concurrent region asks into shared step
rounds (ISSUE 9 tentpole).

PR 8's gateway routed every request through `DeviceShardRegion.ask`,
which holds `_ask_lock` for the whole stage→step→poll round — N
concurrent clients paid N full device rounds even though the promise-row
pool was built for many in-flight asks. This module is the dispatcher
`throughput` idea (many mailbox messages per thread acquisition) applied
to the ask path: collect asks that arrive within an adaptive window,
allocate each its promise row, stage ALL the tells as one coalesced
flush, run ONE shared step budget, and resolve every latch from one
static-slice read of the promise block.

Two layers:

- `execute_ask_batch(region, batch)`: the synchronous engine. Caller
  holds `region._ask_lock`; per-ask timeout/retirement semantics are
  byte-for-byte those of the old `ask` (a batch of one runs the exact
  same step schedule, so solo results are bit-identical).
- `AskBatcher`: the thread-safe futures front end the gateway uses.
  `submit()` returns a Future; a lazily-started daemon dispatcher thread
  closes batches (N pending or T µs, whichever first) and runs them
  under the ask lock. `handle_frame` stays synchronous per connection —
  batching emerges from concurrent connections.

One scheduling rule is load-bearing: the dense-inbox reduce SUMS
payloads, so two asks addressed to the SAME entity row in one step round
would sum their reply-row columns and misroute both replies. The engine
therefore stages at most one in-flight ask per destination row per wave;
duplicates wait for the current occupant to resolve and ride a later
wave — which is also what gives per-entity linearized totals.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..event.tracing import NOOP_SPAN, current_ctx, reset_ctx, set_ctx

__all__ = ["BatchAsk", "execute_ask_batch", "AskBatcher",
           "ContinuousWaveScheduler", "wait_adaptive_close"]

# idle-poll backoff bounds for the dispatcher/runner loops (ISSUE 18
# satellite): an idle loop parks IDLE_WAIT_MIN after its last work and
# doubles up to IDLE_WAIT_MAX; submit's Event.set() re-arms tight polling
# instantly, so the backoff trades idle CPU wakeups for nothing else
IDLE_WAIT_MIN = 1e-3
IDLE_WAIT_MAX = 0.25


def wait_adaptive_close(work: threading.Event, window_s: float,
                        full, idle=None) -> None:
    """THE adaptive window-close wait, shared by the ask dispatcher and
    the ingest aggregator (gateway/aggregator.py): block until `full()`
    says the window is worth closing or `window_s` has elapsed since the
    window opened — whichever first — waking early whenever `work` is
    set by a new arrival. `full` must take its own lock.

    `idle` (ISSUE 16 satellite): optional predicate saying the pipeline
    downstream of this window has nothing in flight. When it holds, the
    window closes IMMEDIATELY — a lone request under light load must not
    eat the whole adaptive window when no concurrent work could possibly
    coalesce with it. Under load the predicate is False (a wave/window
    is executing) and the adaptive wait behaves exactly as before: the
    execution time of the in-flight work IS the batching window.
    Callers must set `work` whenever `idle` transitions to True, or a
    request arriving mid-flight waits the full deadline."""
    deadline = time.perf_counter() + window_s
    while not full():
        if idle is not None and idle():
            return
        remain = deadline - time.perf_counter()
        if remain <= 0:
            return
        work.wait(remain)
        work.clear()


class BatchAsk:
    """One ask riding a batch: request in, outcome (reply payload or the
    per-ask exception instance) out.

    `trace` is the submitter's span context (event/tracing.py SpanCtx,
    None when the request is unsampled) snapshotted at submit time —
    that snapshot is what carries causality across the dispatcher
    thread hop and into columnar waves."""

    __slots__ = ("shard", "index", "message", "steps", "max_extra_steps",
                 "slot", "prow", "row", "start", "outcome", "future",
                 "t_submit", "trace", "t_stage", "step_stage", "wave",
                 "was_deferred", "resolve_seq", "dedup_key")

    def __init__(self, shard: int, index: int, message: Any,
                 steps: int = 2, max_extra_steps: int = 8,
                 trace=None):
        self.shard = shard
        self.index = index
        self.message = message
        self.steps = steps
        self.max_extra_steps = max_extra_steps
        self.slot: Optional[int] = None
        self.prow: Optional[int] = None
        self.row: Optional[int] = None
        self.start = 0
        self.outcome: Any = None
        self.future: Optional[Future] = None
        self.t_submit = 0.0
        self.trace = trace
        self.t_stage = 0.0
        self.step_stage = 0
        # continuous wave scheduling (ISSUE 16): owning wave handle, the
        # per-wave deferred marker (the engine infers it from `start`,
        # which is a GLOBAL step count under the scheduler), and the
        # global resolve ordinal of an ok outcome — what lets the
        # gateway's replica publishes stay per-entity monotone when wave
        # resolve boundaries complete out of submit order
        self.wave = None
        self.was_deferred = False
        self.resolve_seq = 0
        # idempotent-session dedup key (ISSUE 20): the gateway's
        # (tenant, request_id) for this member, or None. Rides the ask to
        # the journal commit sites so the wave's group commit records the
        # reply under the same fsync as the events it acknowledges.
        self.dedup_key = None


def _reset_batch_latches(region, slots: Sequence[int]) -> None:
    """Lower `__promise_replied` for the batch's slots before reuse: ONE
    static-shape masked update over the whole promise block (the bridge
    `_clear_latches` idiom — a per-slot-count scatter would recompile for
    every distinct batch size). Slots NOT in the batch — live asks from a
    previous wave, retired timeouts waiting for their late reply — are
    deliberately untouched."""
    sys = region.system
    eps = region.eps
    base = region._promise_block * eps
    mask = np.zeros((eps,), np.bool_)
    mask[np.asarray(list(slots), np.int64)] = True
    col = sys.state["__promise_replied"]
    blk = jnp.where(jnp.asarray(mask), False, col[base:base + eps])
    sys.state["__promise_replied"] = col.at[base:base + eps].set(blk)


def _assemble_slots(region, batch: Sequence[BatchAsk]) -> List[BatchAsk]:
    """Stage-phase slot assembly (shared by the serialized engine and the
    continuous scheduler — ISSUE 16 split): one promise slot per member;
    pool overflow is a typed per-member fast-fail (the admission layer
    sheds on it), not a batch failure. Caller holds `region._ask_lock`.
    Returns the live members, each with slot/prow/row assigned."""
    from ..batched.bridge import AskPoolExhausted, max_exact_row_id

    sys = region.system
    eps = region.eps
    base = region._promise_block * eps
    limit = max_exact_row_id(sys.payload_dtype)
    live: List[BatchAsk] = []
    for a in batch:
        with region._lock:
            if not region._promise_free:
                region._stat_ask_exhausted += 1
                a.outcome = AskPoolExhausted(
                    f"promise rows exhausted ({eps} slots, "
                    f"{len(region._promise_retired)} retired)")
                continue
            a.slot = region._promise_free.pop()
        prow = base + a.slot
        if prow > limit:
            with region._lock:
                region._promise_free.append(a.slot)
            a.slot = None
            a.outcome = ValueError(
                f"promise row {prow} not exactly representable in "
                f"{jnp.dtype(sys.payload_dtype).name} payloads")
            continue
        a.prow = prow
        a.row = region.row_of(a.shard, a.index)
        live.append(a)
    return live


def _stage_tell(sys, a: BatchAsk, cum: int) -> None:
    """Stage ONE ask's tell into the next flush (shared stage phase):
    payload body + reply-to promise row in the last column, `start`
    stamped with the step count the timeout clock runs against."""
    payload = np.zeros((sys.payload_width,), np.float32)
    body = np.atleast_1d(
        np.asarray(a.message, np.float32)).reshape(-1)
    payload[:min(len(body), sys.payload_width - 1)] = \
        body[:sys.payload_width - 1]
    payload[-1] = float(a.prow)
    sys.tell(a.row, payload)
    a.start = cum
    if a.trace is not None:
        a.t_stage = time.monotonic()
        a.step_stage = int(sys._host_step)


def execute_ask_batch(region, batch: Sequence[BatchAsk]) -> None:
    """Run a batch of asks through shared step rounds. Caller holds
    `region._ask_lock`. Fills each member's `.outcome` with the reply
    payload (np.ndarray) or an exception instance (AskPoolExhausted /
    ValueError / TimeoutError) — never raises for per-ask conditions, so
    one member's timeout cannot fail its batch-mates."""
    from ..batched.supervision import decode_attention

    region._ensure_promise_rows()
    region._reclaim_promise_slots()  # once per BATCH, not once per ask
    sys = region.system
    eps = region.eps
    base = region._promise_block * eps

    live = _assemble_slots(region, batch)
    if not live:
        return

    # every wave (= one engine invocation, serialized by _ask_lock) gets
    # a monotone wave_id; the same counter is what AskBatcher.stats()
    # surfaces as last_wave_id, so span wave_ids and collector stats can
    # be cross-checked (ISSUE 12)
    region._wave_seq = wave_id = getattr(region, "_wave_seq", 0) + 1
    tracer = getattr(region, "tracer", None)
    wspan = NOOP_SPAN
    if tracer is not None:
        sampled = [a for a in live if a.trace is not None]
        if sampled:
            # ONE wave span regardless of how many sampled members ride
            # it: rooted in the first member's trace, joined to the rest
            # by wave_id + member_traces (the request-tree join key)
            wspan = tracer.begin(
                "ask.wave", sampled[0].trace, parent=0, wave_id=wave_id,
                n_members=len(live), n_sampled=len(sampled),
                member_traces=[a.trace.trace_id for a in sampled])
    cum = 0  # steps run so far in this batch
    rounds = 0
    try:
        # stage/resolve phase attribution (ISSUE 16 satellite): the three
        # coarse children — wave.stage (latch reset + coalesced flush),
        # wave.inflight_wait (the step rounds) and wave.resolve (journal
        # commit) — retro-emitted around the existing fine-grained kids,
        # so the bench artifact shows where a serialized wave's latency
        # actually lives. Quiet path: tracer None or unsampled wave keeps
        # the one-predicate cost (emit on a None ctx is a no-op).
        t_stage0 = time.monotonic() if tracer is not None else 0.0
        with wspan.child("wave.latch_reset", wave_id=wave_id):
            _reset_batch_latches(region, [a.slot for a in live])

        # -- wave scheduling: at most ONE in-flight ask per destination
        # row (see module docstring); each wave's tells coalesce into
        # the next run's single flush
        waiting = list(live)
        in_flight = {}  # row -> BatchAsk
        ok_resolved: List[BatchAsk] = []  # replied members, wave order

        def stage_ready() -> None:
            nonlocal waiting
            rest: List[BatchAsk] = []
            for a in waiting:
                if a.row in in_flight:
                    rest.append(a)
                    continue
                _stage_tell(sys, a, cum)
                in_flight[a.row] = a
            waiting = rest

        def resolve_member(a: BatchAsk, outcome: str) -> None:
            # retro-emitted: the member's in-flight window (staged ->
            # resolved), parented under the SUBMITTER's span so the
            # request tree crosses the thread hop intact
            tracer.emit("ask.member", a.trace, t0=a.t_stage,
                        t1=time.monotonic(), step0=a.step_stage,
                        step1=int(sys._host_step), wave_id=wave_id,
                        slot=a.slot, row=a.row, deferred=a.start > 0,
                        outcome=outcome)

        with wspan.child("wave.flush", wave_id=wave_id, coalesced=True,
                         n_staged=len(waiting)):
            stage_ready()
        t_wait0 = time.monotonic() if tracer is not None else 0.0
        if tracer is not None:
            tracer.emit("wave.stage", wspan.ctx, t0=t_stage0, t1=t_wait0,
                        wave_id=wave_id, n_staged=len(in_flight),
                        n_deferred=len(waiting))
        first = True
        rounds = 0
        while in_flight:
            # shared budget: one `steps`-deep round for the whole wave,
            # then single steps — a batch of one runs the exact schedule
            # the pre-batching ask() ran ([steps] + [1]*max_extra_steps)
            n_steps = min(a.steps for a in in_flight.values()) \
                if first else 1
            first = False
            rounds += 1
            with wspan.child("wave.step_round", wave_id=wave_id,
                             n_steps=n_steps, round=rounds) as rspan:
                sys.run(n_steps)
                rspan.set(host_step=int(sys._host_step))
            cum += n_steps
            # "all replied?" rides the attention word: the tiny
            # device_get doubles as the run's sync (bridge _drain_one
            # idiom), and the wide promise-block readback is paid only
            # when ATT_LATCH_BIT says some latch is actually high
            att = decode_attention(sys.attention)
            replied_blk = reply_blk = None
            if att["any_latched"] or not getattr(region, "_ask_latch_wired",
                                                 False):
                from ..batched.bridge import read_promise_block
                with wspan.child("wave.readback", wave_id=wave_id,
                                 round=rounds):
                    replied_blk, reply_blk = read_promise_block(
                        sys.state, base, eps, "__promise_replied",
                        "__promise_reply")
            done_rows: List[int] = []
            for row, a in in_flight.items():
                if replied_blk is not None and bool(replied_blk[a.slot]):
                    a.outcome = np.asarray(reply_blk[a.slot])
                    ok_resolved.append(a)
                    with region._lock:
                        region._promise_free.append(a.slot)
                    if a.trace is not None and tracer is not None:
                        resolve_member(a, "reply")
                    done_rows.append(row)
                elif cum - a.start >= a.steps + a.max_extra_steps:
                    # timed out: RETIRE the slot (late replies must land
                    # in a row no future ask will read);
                    # _reclaim_promise_slots returns it once the
                    # straggler's latch shows up
                    with region._lock:
                        region._promise_retired.append(a.slot)
                    a.outcome = TimeoutError(
                        f"ask to shard {a.shard} index {a.index} "
                        f"unanswered after "
                        f"{a.steps + a.max_extra_steps} steps")
                    if a.trace is not None and tracer is not None:
                        resolve_member(a, "timeout")
                    done_rows.append(row)
            for row in done_rows:
                del in_flight[row]
            if waiting:  # duplicates deferred from earlier waves
                with wspan.child("wave.flush", wave_id=wave_id,
                                 deferred=True, n_staged=len(waiting)):
                    stage_ready()

        t_res0 = time.monotonic() if tracer is not None else 0.0
        if tracer is not None:
            tracer.emit("wave.inflight_wait", wspan.ctx, t0=t_wait0,
                        t1=t_res0, wave_id=wave_id, rounds=rounds)

        # durable entity layer (ISSUE 15): ONE group-committed journal
        # write for the whole wave's ok events, BEFORE outcomes reach the
        # callers — an acked write is on disk by the time the ack exists.
        # Regions without attach_entity_journal pay one attribute read.
        if ok_resolved and \
                getattr(region, "_entity_journal", None) is not None:
            with wspan.child("wave.journal", wave_id=wave_id,
                             n_events=len(ok_resolved)):
                region._commit_entity_events(
                    [(a.shard, a.index, a.message, a.dedup_key, a.outcome)
                     for a in ok_resolved])
        if tracer is not None:
            tracer.emit("wave.resolve", wspan.ctx, t0=t_res0,
                        t1=time.monotonic(), wave_id=wave_id,
                        n_ok=len(ok_resolved))
    finally:
        wspan.finish(rounds=rounds, steps=cum)


class _WaveHandle:
    """One wave open on the continuous scheduler: completion latch,
    resolve-boundary callback, wave span, and the members' resolve
    bookkeeping. `done` is set strictly AFTER the wave's journal group
    commit and after every member future holds its outcome."""

    __slots__ = ("batch", "remaining", "ok", "done", "on_resolve",
                 "wspan", "wave_id", "t_stage1")

    def __init__(self, batch: List[BatchAsk]):
        self.batch = batch
        self.remaining = 0
        self.ok: List[BatchAsk] = []  # replied members, resolve order
        self.done = threading.Event()
        self.on_resolve: Optional[Callable[["_WaveHandle"], None]] = None
        self.wspan = NOOP_SPAN
        self.wave_id = 0
        self.t_stage1 = 0.0

    def outcomes(self) -> List[Any]:
        return [a.outcome for a in self.batch]


class ContinuousWaveScheduler:
    """Continuous wave formation (ISSUE 16 tentpole): overlap wave N+1's
    staging with wave N's device rounds.

    The serialized engine holds `region._ask_lock` for a whole
    stage→step→poll round, so concurrent waves pay their device rounds
    back to back — the authoritative-latency floor the PR 14 A/B
    measured (208 ms p99 at 64 clients). This scheduler splits the
    engine at its stage/resolve seam:

    - `submit_wave` holds the lock only for the STAGING INSTANT (slot
      assembly, latch reset, coalesced tell flush) and returns a handle
      immediately — the submitting thread is free to decode and
      admission-charge the next window while the device runs.
    - ONE runner thread drives shared single-step rounds for ALL open
      waves, keeping up to `depth` dispatched rounds in flight on the
      bridge (PR 3's enqueue-ahead deque of non-donated attention
      words; the device_get on the oldest handle doubles as that
      round's sync) and paying the wide promise-block readback only
      when the packed attention word says some latch is actually high.
    - members of EVERY open wave resolve off the same readback as their
      latches land; a wave's resolve boundary (journal group commit →
      member futures → `on_resolve`) fires when its LAST member
      retires, preserving the PR 15 commit-before-ack ordering per
      wave.

    Cross-wave scheduling rule: the dense-inbox reduce still SUMS
    payloads, so the one-in-flight-ask-per-destination-row rule extends
    across waves — `_row_owner` maps each destination row to its single
    in-flight ask and `_deferred` holds the row's FIFO of late joiners
    (from the SAME wave or any later one), staged into the next step
    round the moment the row frees. Per-entity linearization is
    therefore submit order, exactly as under the serialized engine.

    Locking: every piece of scheduler wave state (_row_owner, _deferred,
    _waves, _cum, _resolve_seq) is mutated only under `region._ask_lock`
    — the same lock checkpoint/rebalance/failover/sum already take, so
    maintenance ops interleave between rounds instead of between waves.
    `self._lock` guards only the overlap statistics."""

    def __init__(self, region, depth: int = 4):
        self.region = region
        self.depth = max(1, int(depth))
        # attention rounds kept in flight ahead of the drain: 2 is the
        # bridge pump's sweet spot (dispatch round k+1 while round k
        # syncs); deeper only delays resolution within the timeout budget
        self._ahead = min(self.depth, 2)
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._waves: List[_WaveHandle] = []      # open waves, submit order
        self._row_owner: Dict[int, BatchAsk] = {}
        self._deferred: List[BatchAsk] = []      # submit-order FIFO
        self._deferred_rows: Dict[int, int] = {}  # row -> queued count
        self._cum = 0          # global steps this scheduler has run
        self._att_q: deque = deque()  # (cum_at_dispatch, attention handle)
        self._resolve_seq = 0
        # overlap accounting (satellite: overlap_ratio in ask_batch stats)
        self._open = 0
        self._t_mark: Optional[float] = None
        self._busy_s = 0.0
        self._overlap_s = 0.0
        self._waves_done = 0
        # idle-wakeup accounting (ISSUE 18 satellite): the runner backs
        # off exponentially while idle instead of spinning at a fixed
        # 0.25 s poll — these count the empty wakeups that remain
        self._idle_wakeups = 0
        self._t_loop0: Optional[float] = None
        # idle-transition hook (wait_adaptive_close fast-close): callers
        # park on their own events; the scheduler pokes this when the
        # last open wave resolves
        self.on_idle: Optional[Callable[[], Any]] = None

    # -------------------------------------------------------------- submit
    def submit_wave(self, batch: Sequence[BatchAsk],
                    on_resolve=None) -> _WaveHandle:
        """Stage one wave and return immediately. The lock is held for
        the staging instant only; rounds run on the scheduler thread.
        Per-member typed failures (pool exhaustion, unrepresentable
        rows) land in `.outcome` at submit, never raise. A wave with no
        live members completes synchronously on the submitting thread
        (journal n/a — nothing resolved ok)."""
        region = self.region
        with self._lock:
            if self._closed:
                raise RuntimeError("ContinuousWaveScheduler is closed")
        h = _WaveHandle(list(batch))
        h.on_resolve = on_resolve
        tracer = getattr(region, "tracer", None)
        with region._ask_lock:
            region._ensure_promise_rows()
            region._reclaim_promise_slots()
            sys = region.system
            try:
                live = _assemble_slots(region, h.batch)
            except BaseException as e:  # noqa: BLE001 — never half-resolve
                for a in h.batch:
                    if a.outcome is None:
                        a.outcome = e
                live = []
            h.remaining = len(live)
            region._wave_seq = wave_id = \
                getattr(region, "_wave_seq", 0) + 1
            h.wave_id = wave_id
            if tracer is not None:
                sampled = [a for a in live if a.trace is not None]
                if sampled:
                    h.wspan = tracer.begin(
                        "ask.wave", sampled[0].trace, parent=0,
                        wave_id=wave_id, n_members=len(live),
                        n_sampled=len(sampled), continuous=True,
                        member_traces=[a.trace.trace_id for a in sampled])
            t_stage0 = time.monotonic() if tracer is not None else 0.0
            staged = 0
            if live:
                with h.wspan.child("wave.latch_reset", wave_id=wave_id):
                    _reset_batch_latches(region, [a.slot for a in live])
                for a in live:
                    a.wave = h
                    # a row already in flight OR with older deferred
                    # waiters queues behind them — cross-wave FIFO per
                    # destination row, never a queue jump
                    if a.row in self._row_owner \
                            or self._deferred_rows.get(a.row):
                        a.was_deferred = True
                        self._deferred.append(a)
                        self._deferred_rows[a.row] = \
                            self._deferred_rows.get(a.row, 0) + 1
                    else:
                        _stage_tell(sys, a, self._cum)
                        self._row_owner[a.row] = a
                        staged += 1
            h.t_stage1 = time.monotonic() if tracer is not None else 0.0
            if tracer is not None:
                tracer.emit("wave.stage", h.wspan.ctx, t0=t_stage0,
                            t1=h.t_stage1, wave_id=wave_id,
                            n_staged=staged,
                            n_deferred=h.remaining - staged)
            if h.remaining:
                self._waves.append(h)
                self._mark_open(+1)
        if not h.remaining:
            self._complete(h)
            return h
        with self._lock:
            if self._thread is None:
                t = threading.Thread(target=self._loop, daemon=True,
                                     name="akka-tpu-wave-scheduler")
                self._thread = t
                t.start()
        self._work.set()
        return h

    # -------------------------------------------------------------- runner
    def _loop(self) -> None:
        # exponential idle backoff (ISSUE 18 satellite): park 1 ms after
        # work, doubling to 250 ms while nothing arrives; `_work.set()`
        # interrupts the wait instantly, so the re-arm to tight polling
        # costs zero latency when work shows up
        idle_wait = IDLE_WAIT_MIN
        with self._lock:
            if self._t_loop0 is None:
                self._t_loop0 = time.monotonic()
        while True:
            fired = self._work.wait(idle_wait)
            self._work.clear()
            if fired:
                idle_wait = IDLE_WAIT_MIN
            else:
                idle_wait = min(idle_wait * 2.0, IDLE_WAIT_MAX)
                with self._lock:
                    self._idle_wakeups += 1
            while True:
                region = self.region
                with region._ask_lock:
                    if not self._row_owner and not self._deferred:
                        # nothing in flight: stale pre-stage attention
                        # snapshots resolve nobody — drop them
                        self._att_q.clear()
                        break
                    sys = region.system
                    self._stage_deferred_locked(sys)
                    # the serialized engine's step schedule, continuous
                    # form: when every in-flight ask still needs k > 1
                    # steps before its reply can latch (fresh stages with
                    # steps=2), run all k in ONE dispatch — same device
                    # work, half the dispatch+sync round trips; any ask
                    # whose reply could land now pins the round to 1 so
                    # resolution is never delayed
                    n_steps = 1
                    if self._row_owner:
                        n_steps = max(1, min(
                            a.steps - (self._cum - a.start)
                            for a in self._row_owner.values()))
                    sys.run(n_steps)
                    self._cum += n_steps
                    # non-donated attention word handle: the enqueue-
                    # ahead deque (bridge _enqueue_step idiom)
                    self._att_q.append((self._cum, sys.attention))
                    # bridge latency policy: once some in-flight ask has
                    # run its full step budget, its reply may already be
                    # latched — resolution beats enqueue-ahead, so drain
                    # the whole deque; only fresh stages (no latchable
                    # reply yet) keep `_ahead` rounds enqueued
                    reply_due = any(
                        self._cum - a.start >= a.steps
                        for a in self._row_owner.values())
                ahead = 1 if reply_due else self._ahead
                while len(self._att_q) >= ahead:
                    self._drain_one()
            with self._lock:
                if self._closed:
                    return

    def _stage_deferred_locked(self, sys) -> None:
        """Admit late joiners into the NEXT step round of the open
        schedule: deferred asks whose destination row has freed stage
        now (coalescing into this round's single flush), in submit
        order — the first waiter per row wins, later ones keep
        waiting."""
        if not self._deferred:
            return
        rest: List[BatchAsk] = []
        for a in self._deferred:
            if a.row in self._row_owner:
                rest.append(a)
                continue
            _stage_tell(sys, a, self._cum)
            self._row_owner[a.row] = a
            n = self._deferred_rows.get(a.row, 1) - 1
            if n:
                self._deferred_rows[a.row] = n
            else:
                self._deferred_rows.pop(a.row, None)
        self._deferred = rest

    def _drain_one(self) -> None:
        """Retire the oldest in-flight round: the tiny attention
        device_get doubles as its sync (bridge _drain_one idiom); the
        wide promise-block readback is paid only when the packed latch
        bit says some reply actually landed. Resolves members of ALL
        open waves, then fires any completed wave's resolve boundary."""
        from ..batched.supervision import decode_attention

        cum_at, att_h = self._att_q.popleft()
        att = decode_attention(att_h)
        region = self.region
        finished: List[_WaveHandle] = []
        with region._ask_lock:
            sys = region.system
            eps = region.eps
            base = region._promise_block * eps
            replied_blk = reply_blk = None
            if att["any_latched"] or not getattr(region,
                                                 "_ask_latch_wired", False):
                from ..batched.bridge import read_promise_block
                replied_blk, reply_blk = read_promise_block(
                    sys.state, base, eps, "__promise_replied",
                    "__promise_reply")
            tracer = getattr(region, "tracer", None)
            done_rows: List[int] = []
            for row, a in self._row_owner.items():
                h = a.wave
                if replied_blk is not None and bool(replied_blk[a.slot]):
                    a.outcome = np.asarray(reply_blk[a.slot])
                    self._resolve_seq += 1
                    a.resolve_seq = self._resolve_seq
                    h.ok.append(a)
                    with region._lock:
                        region._promise_free.append(a.slot)
                    if a.trace is not None and tracer is not None:
                        tracer.emit(
                            "ask.member", a.trace, t0=a.t_stage,
                            t1=time.monotonic(), step0=a.step_stage,
                            step1=int(sys._host_step), wave_id=h.wave_id,
                            slot=a.slot, row=row, deferred=a.was_deferred,
                            outcome="reply")
                elif cum_at - a.start >= a.steps + a.max_extra_steps:
                    # timed out: RETIRE the slot (the late reply must
                    # land in a row no future ask will read); reclaimed
                    # once the straggler's latch shows up — exactly the
                    # serialized engine's semantics, counted against the
                    # steps that had run when THIS round was dispatched
                    with region._lock:
                        region._promise_retired.append(a.slot)
                    a.outcome = TimeoutError(
                        f"ask to shard {a.shard} index {a.index} "
                        f"unanswered after "
                        f"{a.steps + a.max_extra_steps} steps")
                    if a.trace is not None and tracer is not None:
                        tracer.emit(
                            "ask.member", a.trace, t0=a.t_stage,
                            t1=time.monotonic(), step0=a.step_stage,
                            step1=int(sys._host_step), wave_id=h.wave_id,
                            slot=a.slot, row=row, deferred=a.was_deferred,
                            outcome="timeout")
                else:
                    continue
                done_rows.append(row)
                h.remaining -= 1
            for row in done_rows:
                del self._row_owner[row]
            for h in [w for w in self._waves if w.remaining == 0]:
                self._waves.remove(h)
                self._mark_open(-1)
                # per-wave resolve boundary, part 1 (under the lock):
                # the PR 15 group commit — one fsync'd record for the
                # wave's ok events BEFORE any outcome reaches a caller
                if h.ok and getattr(region, "_entity_journal",
                                    None) is not None:
                    with h.wspan.child("wave.journal", wave_id=h.wave_id,
                                       n_events=len(h.ok)):
                        region._commit_entity_events(
                            [(a.shard, a.index, a.message, a.dedup_key,
                              a.outcome) for a in h.ok])
                finished.append(h)
        for h in finished:
            self._complete(h)

    def _complete(self, h: _WaveHandle) -> None:
        """Resolve boundary, part 2 (outside the lock): member futures,
        the `on_resolve` callback (the gateway's reply encode / replica
        publish / SLO round ride here), the completion latch, and the
        wave span's stage-attribution children."""
        region = self.region
        tracer = getattr(region, "tracer", None)
        t_res0 = time.monotonic()
        if tracer is not None and h.wspan is not NOOP_SPAN:
            tracer.emit("wave.inflight_wait", h.wspan.ctx, t0=h.t_stage1,
                        t1=t_res0, wave_id=h.wave_id)
        for a in h.batch:
            if a.future is not None and not a.future.done():
                if isinstance(a.outcome, BaseException):
                    a.future.set_exception(a.outcome)
                else:
                    a.future.set_result(a.outcome)
        if h.on_resolve is not None:
            try:
                h.on_resolve(h)
            except Exception:  # noqa: BLE001 — the runner must survive
                pass           # a resolve callback's failure
        h.done.set()
        with self._lock:
            self._waves_done += 1
        if tracer is not None and h.wspan is not NOOP_SPAN:
            tracer.emit("wave.resolve", h.wspan.ctx, t0=t_res0,
                        t1=time.monotonic(), wave_id=h.wave_id,
                        n_ok=len(h.ok))
        h.wspan.finish(n_ok=len(h.ok))
        if self.idle():
            cb = self.on_idle
            if cb is not None:
                cb()

    # --------------------------------------------------------------- state
    def idle(self) -> bool:
        """True when no wave is open (racy read — a timing hint for the
        adaptive window close, not a synchronization primitive)."""
        return not self._row_owner and not self._deferred \
            and not self._waves

    def quiesce(self, timeout: float = 30.0) -> bool:
        """Block until every open wave has resolved (conserved-value
        probes read device state directly — they must not observe a
        half-applied wave). Returns False on timeout."""
        deadline = time.monotonic() + timeout
        while not self.idle():
            if time.monotonic() >= deadline:
                return False
            time.sleep(1e-3)
        return True

    def _mark_open(self, delta: int) -> None:
        now = time.monotonic()
        with self._lock:
            if self._t_mark is not None:
                span = now - self._t_mark
                if self._open >= 1:
                    self._busy_s += span
                if self._open >= 2:
                    self._overlap_s += span
            self._t_mark = now
            self._open += delta

    def stats(self) -> Dict[str, float]:
        """Overlap evidence for the ask_batch collector: overlap_ratio
        is the fraction of wave-busy wall time during which two or more
        waves were open — 0.0 means the pipeline degenerated to the
        serialized one-wave-at-a-time schedule."""
        with self._lock:
            busy, over = self._busy_s, self._overlap_s
            up = (time.monotonic() - self._t_loop0) \
                if self._t_loop0 is not None else 0.0
            return {"open_waves": float(self._open),
                    "waves_resolved": float(self._waves_done),
                    "busy_s": busy, "overlap_s": over,
                    "overlap_ratio": (over / busy) if busy > 0 else 0.0,
                    "idle_wakeups": float(self._idle_wakeups),
                    "idle_wakeups_per_s":
                        (self._idle_wakeups / up) if up > 0 else 0.0}

    def open_wave_depth(self) -> float:
        """Open waves over pipeline depth, 0..1+ (ISSUE 18 satellite):
        the pressure form of the promise-pool headroom — 1.0 means the
        wave pipeline is full and the next window will block on a slot,
        so admission should start shedding BEFORE the pool drains."""
        with self._lock:
            return self._open / self.depth

    # ----------------------------------------------------------- lifecycle
    def close(self, timeout: float = 10.0) -> None:
        """Drain: open waves resolve (their members reply or time out —
        the step budget bounds the wait) before the runner exits; any
        member still unresolved after `timeout` gets a typed RuntimeError
        so no caller hangs on a dead scheduler."""
        with self._lock:
            self._closed = True
            t = self._thread
        self._work.set()
        if t is not None:
            t.join(timeout)
        with self.region._ask_lock:
            leftovers, self._waves = self._waves, []
            self._row_owner.clear()
            self._deferred = []
            self._deferred_rows.clear()
            # commit-before-ack holds even for a force-drained wave: its
            # already-resolved members' events hit the journal before
            # their outcomes reach any caller below
            for h in leftovers:
                if h.ok and getattr(self.region, "_entity_journal",
                                    None) is not None:
                    self.region._commit_entity_events(
                        [(a.shard, a.index, a.message, a.dedup_key,
                          a.outcome) for a in h.ok])
        for h in leftovers:
            for a in h.batch:
                if a.outcome is None:
                    a.outcome = RuntimeError(
                        "ContinuousWaveScheduler is closed")
            h.remaining = 0
            self._complete(h)


class AskBatcher:
    """Thread-safe futures front end over `execute_ask_batch`.

    `submit()` appends to the pending list and returns a Future; a
    daemon dispatcher thread (started on first submit, the bridge pump
    idiom) closes a batch when `max_batch` asks are pending or
    `window_s` has elapsed since it saw the first one — whichever first
    — and runs it under the region's ask lock. Callers never become
    batch leaders, so no connection handler gets stuck dispatching other
    tenants' traffic under sustained load.

    With a MetricsRegistry: `gateway_ask_batch_size` and
    `gateway_ask_batch_window_us` histograms, plus an "ask_batch"
    collector exposing the summary counters."""

    def __init__(self, region, max_batch: int = 32,
                 window_s: float = 200e-6, steps: int = 2,
                 max_extra_steps: int = 8, registry=None,
                 continuous: bool = False, pipeline_depth: int = 4):
        self.region = region
        # a batch larger than the promise pool would guarantee typed
        # exhaustion for the overflow members; cap it at the pool size
        pool = int(getattr(region, "eps", max_batch))
        self.max_batch = max(1, min(int(max_batch), pool))
        self.window_s = float(window_s)
        self.steps = int(steps)
        self.max_extra_steps = int(max_extra_steps)
        self._lock = threading.Lock()
        self._work = threading.Event()
        # continuous wave formation (ISSUE 16): waves go through the
        # scheduler instead of running the engine inline, so up to
        # `pipeline_depth` waves overlap on the bridge. continuous=False
        # keeps the serialized engine path byte-for-byte (the A/B escape
        # hatch the acceptance criteria pin).
        self.continuous = bool(continuous)
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._sched: Optional[ContinuousWaveScheduler] = None
        if self.continuous:
            self._sched = ContinuousWaveScheduler(
                region, depth=self.pipeline_depth)
            self._sched.on_idle = self._work.set
        self._inflight_sem = threading.BoundedSemaphore(self.pipeline_depth)
        self._executing = 0  # serialized engine calls in flight (idle hint)
        self._pending: List[BatchAsk] = []
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._batches = 0
        self._asks = 0
        self._multi = 0
        self._max_seen = 0
        self._idle_wakeups = 0
        self._t_loop0: Optional[float] = None
        self._h_size = self._h_wait = None
        if registry is not None:
            self._h_size = registry.histogram(
                "gateway_ask_batch_size",
                "asks coalesced per shared device step round")
            self._h_wait = registry.histogram(
                "gateway_ask_batch_window_us",
                "microseconds an ask waited for its batch to close")
            registry.register_collector("ask_batch", self.stats)

    # ------------------------------------------------------------- submit
    def submit(self, shard: int, index: int, message: Any,
               steps: Optional[int] = None,
               max_extra_steps: Optional[int] = None,
               dedup_key=None) -> Future:
        a = BatchAsk(int(shard), int(index), message,
                     self.steps if steps is None else int(steps),
                     self.max_extra_steps if max_extra_steps is None
                     else int(max_extra_steps),
                     # the submitter's span ctx crosses the dispatcher
                     # thread hop pinned to the ask itself (None when the
                     # request is unsampled — the one read the quiet path
                     # pays)
                     trace=current_ctx())
        a.dedup_key = dedup_key
        a.future = Future()
        a.t_submit = time.perf_counter()
        with self._lock:
            if self._closed:
                raise RuntimeError("AskBatcher is closed")
            self._pending.append(a)
            if self._thread is None:
                t = threading.Thread(target=self._loop,
                                     name="akka-tpu-ask-batcher",
                                     daemon=True)
                self._thread = t
                t.start()
        self._work.set()
        return a.future

    def ask(self, shard: int, index: int, message: Any,
            steps: Optional[int] = None,
            max_extra_steps: Optional[int] = None,
            dedup_key=None):
        """Submit and wait: returns the reply payload or raises the
        per-ask exception (TimeoutError / AskPoolExhausted / ...)."""
        return self.submit(shard, index, message, steps,
                           max_extra_steps, dedup_key=dedup_key).result()

    def ask_many(self, requests: Sequence[Any],
                 ctxs: Optional[Sequence[Any]] = None,
                 with_seqs: bool = False,
                 keys: Optional[Sequence[Any]] = None):
        """Columnar wave entry (ISSUE 11): `requests` is a sequence of
        `(shard, index, message)` decoded from one binary window.
        Returns outcomes aligned with `requests` — the reply payload or
        the per-ask exception INSTANCE (never raises per-ask).

        `ctxs` (ISSUE 12): optional aligned per-member span contexts —
        one binary window carries MANY traces, so the ambient contextvar
        cannot represent it; the gateway passes each sampled record's
        root ctx explicitly.

        A multi-request wave IS already a batch, so it skips the
        per-call future hop and the dispatcher window entirely: the
        caller's thread runs `execute_ask_batch` directly under the
        region's ask lock (serialized with dispatcher batches by that
        same lock — wave linearization per entity is unchanged). A
        wave of one submits through the dispatcher as usual so it can
        coalesce with concurrent single asks.

        Continuous mode (ISSUE 16): the wave is STAGED on the scheduler
        and this thread blocks only on its own wave's resolve boundary —
        other threads' waves overlap it on the bridge instead of queuing
        behind `_ask_lock`. `with_seqs=True` additionally returns the
        per-member resolve ordinals (aligned, 0 for failures) the
        gateway uses to keep replica publishes per-entity monotone when
        resolve boundaries complete out of submit order; in serialized
        mode the seqs are None — waves resolve in submit order there, so
        publish order needs no filter (bit-parity with PR 15).

        `keys` (ISSUE 20): optional aligned dedup keys — the gateway's
        (tenant, request_id) per member, pinned to the BatchAsk so the
        journal commit sites can record the reply with the wave."""
        reqs = list(requests)
        if not reqs:
            return ([], None) if with_seqs else []
        if self._sched is not None:
            batch = [BatchAsk(int(s), int(i), m, self.steps,
                              self.max_extra_steps) for s, i, m in reqs]
            if ctxs is not None:
                for a, c in zip(batch, ctxs):
                    a.trace = c
            if keys is not None:
                for a, k in zip(batch, keys):
                    a.dedup_key = k
            if len(batch) == 1:
                # a wave of one rides the dispatcher window exactly as
                # in serialized mode, so concurrent solo asks coalesce
                # into SHARED waves — without this, 64 solo callers
                # would stage 64 one-member waves and pay the per-wave
                # overhead 64 times instead of once
                a = batch[0]
                a.future = Future()
                a.t_submit = time.perf_counter()
                with self._lock:
                    if self._closed:
                        raise RuntimeError("AskBatcher is closed")
                    self._pending.append(a)
                    if self._thread is None:
                        t = threading.Thread(
                            target=self._loop, name="akka-tpu-ask-batcher",
                            daemon=True)
                        self._thread = t
                        t.start()
                self._work.set()
                try:
                    a.future.result(60.0)
                except BaseException:  # noqa: BLE001 — outcome convention
                    pass
                outcomes = [a.outcome]
                if with_seqs:
                    return outcomes, [a.resolve_seq]
                return outcomes
            with self._lock:
                if self._closed:
                    raise RuntimeError("AskBatcher is closed")
            handles = [self._submit_wave(batch[lo:lo + self.max_batch])
                       for lo in range(0, len(batch), self.max_batch)]
            for h in handles:
                h.done.wait(60.0)
            outcomes = [a.outcome for a in batch]
            if with_seqs:
                return outcomes, [a.resolve_seq for a in batch]
            return outcomes
        if len(reqs) == 1:
            s, i, m = reqs[0]
            tok = None
            if ctxs is not None and ctxs[0] is not None:
                tok = set_ctx(ctxs[0])  # submit() snapshots it per ask
            try:
                out = [self.ask(s, i, m, dedup_key=keys[0]
                                if keys is not None else None)]
            except BaseException as e:  # noqa: BLE001 — outcome convention
                out = [e]
            finally:
                if tok is not None:
                    reset_ctx(tok)
            return (out, None) if with_seqs else out
        with self._lock:
            if self._closed:
                raise RuntimeError("AskBatcher is closed")
        batch = [BatchAsk(int(s), int(i), m, self.steps,
                          self.max_extra_steps) for s, i, m in reqs]
        if ctxs is not None:
            for a, c in zip(batch, ctxs):
                a.trace = c
        if keys is not None:
            for a, k in zip(batch, keys):
                a.dedup_key = k
        region = self.region
        t0 = time.perf_counter()
        # waves larger than the promise pool ride consecutive sub-batches
        # (the submit path's max_batch cap, applied here without futures)
        for lo in range(0, len(batch), self.max_batch):
            sub = batch[lo:lo + self.max_batch]
            with self._lock:
                self._executing += 1
            try:
                with region._ask_lock:
                    execute_ask_batch(region, sub)
            except BaseException as e:  # noqa: BLE001 — never half-resolve
                for a in sub:
                    if a.outcome is None:
                        a.outcome = e
            finally:
                with self._lock:
                    self._executing -= 1
                    if self._executing == 0:
                        # idle transition: wake the dispatcher so a solo
                        # submit that arrived mid-wave closes now instead
                        # of eating the rest of its adaptive window
                        self._work.set()
            with self._lock:
                self._batches += 1
                self._asks += len(sub)
                self._max_seen = max(self._max_seen, len(sub))
                if len(sub) > 1:
                    self._multi += 1
            if self._h_size is not None:
                self._h_size.observe(float(len(sub)))
            if self._h_wait is not None:
                # columnar waves never wait for a window to close: the
                # whole wave arrived at once, so its wait is dispatch lag
                self._h_wait.observe((time.perf_counter() - t0) * 1e6)
        outcomes = [a.outcome for a in batch]
        return (outcomes, None) if with_seqs else outcomes

    def ask_many_async(self, requests: Sequence[Any],
                       ctxs: Optional[Sequence[Any]] = None,
                       on_done: Optional[Callable[
                           [List[Any], List[int]], Any]] = None,
                       keys: Optional[Sequence[Any]] = None) -> None:
        """Continuous-mode async wave entry (ISSUE 16): stage the wave
        NOW on the calling thread (preserving per-connection submit
        order — staging order IS the linearization order) and return
        immediately; `on_done(outcomes, seqs)` fires on the scheduler
        thread at the LAST chunk's resolve boundary, with both lists
        aligned to `requests` (seqs are the global resolve ordinals, 0
        for failed members). This is what lets the gateway resolve
        window N while the aggregator decodes and admission-charges
        window N+1."""
        if self._sched is None:
            raise RuntimeError("ask_many_async requires continuous=True")
        with self._lock:
            if self._closed:
                raise RuntimeError("AskBatcher is closed")
        reqs = list(requests)
        batch = [BatchAsk(int(s), int(i), m, self.steps,
                          self.max_extra_steps) for s, i, m in reqs]
        if ctxs is not None:
            for a, c in zip(batch, ctxs):
                a.trace = c
        if keys is not None:
            for a, k in zip(batch, keys):
                a.dedup_key = k
        if not batch:
            if on_done is not None:
                on_done([], [])
            return
        chunks = [batch[lo:lo + self.max_batch]
                  for lo in range(0, len(batch), self.max_batch)]
        state = {"left": len(chunks)}
        state_lock = threading.Lock()

        def _chunk_done(_h) -> None:
            with state_lock:
                state["left"] -= 1
                last = state["left"] == 0
            if last and on_done is not None:
                on_done([a.outcome for a in batch],
                        [a.resolve_seq for a in batch])

        for c in chunks:
            self._submit_wave(c, on_resolve=_chunk_done)

    def _submit_wave(self, sub: List[BatchAsk], on_resolve=None):
        """Stage one wave on the continuous scheduler with the batcher's
        stats/histograms recorded at ITS resolve boundary (the engine
        paths record after their synchronous run; here the wave is still
        in flight when submit returns)."""
        t0 = time.perf_counter()

        def _done(h) -> None:
            with self._lock:
                self._batches += 1
                self._asks += len(sub)
                self._max_seen = max(self._max_seen, len(sub))
                if len(sub) > 1:
                    self._multi += 1
            if self._h_size is not None:
                self._h_size.observe(float(len(sub)))
            if self._h_wait is not None:
                self._h_wait.observe((time.perf_counter() - t0) * 1e6)
            if on_resolve is not None:
                on_resolve(h)

        return self._sched.submit_wave(sub, on_resolve=_done)

    # ---------------------------------------------------------- dispatcher
    def _full(self) -> bool:
        with self._lock:
            return len(self._pending) >= self.max_batch

    def idle(self) -> bool:
        """Downstream idleness: nothing is executing below the window.
        Public because the ingest aggregator folds it into ITS
        window-close predicate."""
        if self._sched is not None:
            return self._sched.idle()
        with self._lock:
            return self._executing == 0

    def open_wave_depth(self) -> float:
        """Pressure form of wave-pipeline fullness, 0..1+ (ISSUE 18
        satellite): continuous mode reports the scheduler's open waves
        over `pipeline_depth`; the serialized engine reports in-flight
        engine calls over the same depth (0 or 1/depth — it can never
        pipeline)."""
        if self._sched is not None:
            return self._sched.open_wave_depth()
        with self._lock:
            return self._executing / self.pipeline_depth

    def _solo_idle(self) -> bool:
        """The solo-latency fast-close predicate (ISSUE 16 satellite):
        exactly ONE ask is pending AND nothing is executing downstream,
        so nothing could possibly coalesce with it — close immediately.
        Two or more pending asks ARE concurrency (and downstream
        idleness flickers true between waves), so under load the
        adaptive wait behaves exactly as before."""
        with self._lock:
            if len(self._pending) > 1:
                return False
        return self.idle()

    def _loop(self) -> None:
        idle_wait = IDLE_WAIT_MIN  # exponential idle backoff (ISSUE 18)
        with self._lock:
            if self._t_loop0 is None:
                self._t_loop0 = time.monotonic()
        while True:
            fired = self._work.wait(idle_wait)
            self._work.clear()
            if fired:
                idle_wait = IDLE_WAIT_MIN
            else:
                idle_wait = min(idle_wait * 2.0, IDLE_WAIT_MAX)
                with self._lock:
                    self._idle_wakeups += 1
            if self._closed:
                self._fail_pending(RuntimeError("AskBatcher is closed"))
                return
            while True:
                with self._lock:
                    if not self._pending:
                        break
                # adaptive window: wait for the batch to fill, close on
                # max_batch pending, window_s elapsed, or the pipeline
                # going idle (solo fast-close) — whichever first
                wait_adaptive_close(self._work, self.window_s, self._full,
                                    idle=self._solo_idle)
                if self._sched is not None:
                    # wave-slot admission BEFORE the window closes: while
                    # this thread waits for one of the `pipeline_depth`
                    # in-flight waves to free a slot, late arrivals keep
                    # joining the still-open window instead of eating a
                    # whole extra wave cycle — the window closes as late
                    # as the pipeline allows
                    while not self._inflight_sem.acquire(timeout=0.25):
                        with self._lock:
                            closed = self._closed
                        if closed:
                            self._fail_pending(
                                RuntimeError("AskBatcher is closed"))
                            return
                with self._lock:
                    close_batch = self._pending[:self.max_batch]
                    del self._pending[:self.max_batch]
                if close_batch:
                    self._run_batch(close_batch)
                elif self._sched is not None:
                    self._inflight_sem.release()

    def _run_batch(self, close_batch: List[BatchAsk]) -> None:
        t_close = time.perf_counter()
        if self._h_wait is not None:
            for a in close_batch:
                self._h_wait.observe((t_close - a.t_submit) * 1e6)
        if self._sched is not None:
            # continuous: stage and move on — the dispatcher is free to
            # close the NEXT window while this wave's rounds run. The
            # scheduler sets the futures at the resolve boundary; the
            # wave slot (pipeline_depth semaphore) was acquired by the
            # dispatcher loop BEFORE the window closed, so a submit
            # storm cannot outrun the promise pool unboundedly.

            def _release(_h) -> None:
                self._inflight_sem.release()

            try:
                self._submit_wave(close_batch, on_resolve=_release)
            except BaseException as e:  # noqa: BLE001 — never hang waiters
                self._inflight_sem.release()
                for a in close_batch:
                    if a.future is not None and not a.future.done():
                        a.future.set_exception(e)
            return
        region = self.region
        with self._lock:
            self._executing += 1
        try:
            with region._ask_lock:
                execute_ask_batch(region, close_batch)
        except BaseException as e:  # noqa: BLE001 — waiters must never hang
            for a in close_batch:
                if a.outcome is None:
                    a.outcome = e
        finally:
            with self._lock:
                self._executing -= 1
                if self._executing == 0:
                    self._work.set()
        with self._lock:
            self._batches += 1
            self._asks += len(close_batch)
            self._max_seen = max(self._max_seen, len(close_batch))
            if len(close_batch) > 1:
                self._multi += 1
        if self._h_size is not None:
            self._h_size.observe(float(len(close_batch)))
        for a in close_batch:
            if isinstance(a.outcome, BaseException):
                a.future.set_exception(a.outcome)
            else:
                a.future.set_result(a.outcome)

    # ------------------------------------------------------------ lifecycle
    def _fail_pending(self, exc: BaseException) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        for a in pending:
            if a.future is not None and not a.future.done():
                a.future.set_exception(exc)

    def quiesce(self, timeout: float = 30.0) -> bool:
        """Block until no wave is in flight (continuous mode; serialized
        engine calls are synchronous, so there is nothing to wait on).
        Consistency reads (`sum_all`, conserved-value probes) call this
        so they never observe a half-resolved wave's device state as
        final."""
        if self._sched is not None:
            return self._sched.quiesce(timeout)
        return True

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            self._closed = True
            t = self._thread
        self._work.set()
        if t is not None:
            t.join(timeout)
        if self._sched is not None:
            self._sched.close(timeout)
        self._fail_pending(RuntimeError("AskBatcher is closed"))

    # ---------------------------------------------------------------- stats
    def stats(self):
        """Numeric summary (registry-collector compatible)."""
        with self._lock:
            b, n = self._batches, self._asks
            up = (time.monotonic() - self._t_loop0) \
                if self._t_loop0 is not None else 0.0
            idle = self._idle_wakeups
            out = {"batches": float(b), "asks": float(n),
                   # idle-backoff evidence (ISSUE 18 satellite): empty
                   # dispatcher wakeups and their rate — bounded by
                   # 1/IDLE_WAIT_MAX (= 4/s) once the backoff saturates
                   "idle_wakeups": float(idle),
                   "idle_wakeups_per_s": (idle / up) if up > 0 else 0.0,
                   "mean_batch_size": (n / b) if b else 0.0,
                   "max_batch_size": float(self._max_seen),
                   "multi_ask_batches": float(self._multi),
                   "pending": float(len(self._pending)),
                   # the engine's wave counter (ISSUE 12): every
                   # execute_ask_batch invocation is one wave, and this
                   # is the id the newest wave's spans carry — the
                   # cross-check key between the trace timeline and
                   # these stats
                   "last_wave_id": float(
                       getattr(self.region, "_wave_seq", 0))}
        # overlap evidence (ISSUE 16 satellite): fraction of wave-busy
        # wall time with >= 2 waves open on the bridge. Serialized mode
        # reports 0.0 by construction — the A/B artifact's fingerprint.
        if self._sched is not None:
            sst = self._sched.stats()
            out["overlap_ratio"] = sst["overlap_ratio"]
            out["waves_overlap_s"] = sst["overlap_s"]
            out["waves_busy_s"] = sst["busy_s"]
            out["runner_idle_wakeups"] = sst["idle_wakeups"]
            out["runner_idle_wakeups_per_s"] = sst["idle_wakeups_per_s"]
            out["open_wave_depth"] = self._sched.open_wave_depth()
        else:
            out["overlap_ratio"] = 0.0
            out["waves_overlap_s"] = 0.0
            out["waves_busy_s"] = 0.0
            out["runner_idle_wakeups"] = 0.0
            out["runner_idle_wakeups_per_s"] = 0.0
            out["open_wave_depth"] = 0.0
        return out
