"""Ask micro-batching: coalesce concurrent region asks into shared step
rounds (ISSUE 9 tentpole).

PR 8's gateway routed every request through `DeviceShardRegion.ask`,
which holds `_ask_lock` for the whole stage→step→poll round — N
concurrent clients paid N full device rounds even though the promise-row
pool was built for many in-flight asks. This module is the dispatcher
`throughput` idea (many mailbox messages per thread acquisition) applied
to the ask path: collect asks that arrive within an adaptive window,
allocate each its promise row, stage ALL the tells as one coalesced
flush, run ONE shared step budget, and resolve every latch from one
static-slice read of the promise block.

Two layers:

- `execute_ask_batch(region, batch)`: the synchronous engine. Caller
  holds `region._ask_lock`; per-ask timeout/retirement semantics are
  byte-for-byte those of the old `ask` (a batch of one runs the exact
  same step schedule, so solo results are bit-identical).
- `AskBatcher`: the thread-safe futures front end the gateway uses.
  `submit()` returns a Future; a lazily-started daemon dispatcher thread
  closes batches (N pending or T µs, whichever first) and runs them
  under the ask lock. `handle_frame` stays synchronous per connection —
  batching emerges from concurrent connections.

One scheduling rule is load-bearing: the dense-inbox reduce SUMS
payloads, so two asks addressed to the SAME entity row in one step round
would sum their reply-row columns and misroute both replies. The engine
therefore stages at most one in-flight ask per destination row per wave;
duplicates wait for the current occupant to resolve and ride a later
wave — which is also what gives per-entity linearized totals.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..event.tracing import NOOP_SPAN, current_ctx, reset_ctx, set_ctx

__all__ = ["BatchAsk", "execute_ask_batch", "AskBatcher",
           "wait_adaptive_close"]


def wait_adaptive_close(work: threading.Event, window_s: float,
                        full) -> None:
    """THE adaptive window-close wait, shared by the ask dispatcher and
    the ingest aggregator (gateway/aggregator.py): block until `full()`
    says the window is worth closing or `window_s` has elapsed since the
    window opened — whichever first — waking early whenever `work` is
    set by a new arrival. `full` must take its own lock."""
    deadline = time.perf_counter() + window_s
    while not full():
        remain = deadline - time.perf_counter()
        if remain <= 0:
            return
        work.wait(remain)
        work.clear()


class BatchAsk:
    """One ask riding a batch: request in, outcome (reply payload or the
    per-ask exception instance) out.

    `trace` is the submitter's span context (event/tracing.py SpanCtx,
    None when the request is unsampled) snapshotted at submit time —
    that snapshot is what carries causality across the dispatcher
    thread hop and into columnar waves."""

    __slots__ = ("shard", "index", "message", "steps", "max_extra_steps",
                 "slot", "prow", "row", "start", "outcome", "future",
                 "t_submit", "trace", "t_stage", "step_stage")

    def __init__(self, shard: int, index: int, message: Any,
                 steps: int = 2, max_extra_steps: int = 8,
                 trace=None):
        self.shard = shard
        self.index = index
        self.message = message
        self.steps = steps
        self.max_extra_steps = max_extra_steps
        self.slot: Optional[int] = None
        self.prow: Optional[int] = None
        self.row: Optional[int] = None
        self.start = 0
        self.outcome: Any = None
        self.future: Optional[Future] = None
        self.t_submit = 0.0
        self.trace = trace
        self.t_stage = 0.0
        self.step_stage = 0


def _reset_batch_latches(region, slots: Sequence[int]) -> None:
    """Lower `__promise_replied` for the batch's slots before reuse: ONE
    static-shape masked update over the whole promise block (the bridge
    `_clear_latches` idiom — a per-slot-count scatter would recompile for
    every distinct batch size). Slots NOT in the batch — live asks from a
    previous wave, retired timeouts waiting for their late reply — are
    deliberately untouched."""
    sys = region.system
    eps = region.eps
    base = region._promise_block * eps
    mask = np.zeros((eps,), np.bool_)
    mask[np.asarray(list(slots), np.int64)] = True
    col = sys.state["__promise_replied"]
    blk = jnp.where(jnp.asarray(mask), False, col[base:base + eps])
    sys.state["__promise_replied"] = col.at[base:base + eps].set(blk)


def execute_ask_batch(region, batch: Sequence[BatchAsk]) -> None:
    """Run a batch of asks through shared step rounds. Caller holds
    `region._ask_lock`. Fills each member's `.outcome` with the reply
    payload (np.ndarray) or an exception instance (AskPoolExhausted /
    ValueError / TimeoutError) — never raises for per-ask conditions, so
    one member's timeout cannot fail its batch-mates."""
    from ..batched.bridge import AskPoolExhausted, max_exact_row_id
    from ..batched.supervision import decode_attention

    region._ensure_promise_rows()
    region._reclaim_promise_slots()  # once per BATCH, not once per ask
    sys = region.system
    eps = region.eps
    base = region._promise_block * eps
    limit = max_exact_row_id(sys.payload_dtype)

    # -- assembly: one promise slot per member; pool overflow is a typed
    # per-member fast-fail (the admission layer sheds on it), not a batch
    # failure
    live: List[BatchAsk] = []
    for a in batch:
        with region._lock:
            if not region._promise_free:
                region._stat_ask_exhausted += 1
                a.outcome = AskPoolExhausted(
                    f"promise rows exhausted ({eps} slots, "
                    f"{len(region._promise_retired)} retired)")
                continue
            a.slot = region._promise_free.pop()
        prow = base + a.slot
        if prow > limit:
            with region._lock:
                region._promise_free.append(a.slot)
            a.slot = None
            a.outcome = ValueError(
                f"promise row {prow} not exactly representable in "
                f"{jnp.dtype(sys.payload_dtype).name} payloads")
            continue
        a.prow = prow
        a.row = region.row_of(a.shard, a.index)
        live.append(a)
    if not live:
        return

    # every wave (= one engine invocation, serialized by _ask_lock) gets
    # a monotone wave_id; the same counter is what AskBatcher.stats()
    # surfaces as last_wave_id, so span wave_ids and collector stats can
    # be cross-checked (ISSUE 12)
    region._wave_seq = wave_id = getattr(region, "_wave_seq", 0) + 1
    tracer = getattr(region, "tracer", None)
    wspan = NOOP_SPAN
    if tracer is not None:
        sampled = [a for a in live if a.trace is not None]
        if sampled:
            # ONE wave span regardless of how many sampled members ride
            # it: rooted in the first member's trace, joined to the rest
            # by wave_id + member_traces (the request-tree join key)
            wspan = tracer.begin(
                "ask.wave", sampled[0].trace, parent=0, wave_id=wave_id,
                n_members=len(live), n_sampled=len(sampled),
                member_traces=[a.trace.trace_id for a in sampled])
    cum = 0  # steps run so far in this batch
    rounds = 0
    try:
        with wspan.child("wave.latch_reset", wave_id=wave_id):
            _reset_batch_latches(region, [a.slot for a in live])

        # -- wave scheduling: at most ONE in-flight ask per destination
        # row (see module docstring); each wave's tells coalesce into
        # the next run's single flush
        waiting = list(live)
        in_flight = {}  # row -> BatchAsk
        ok_resolved: List[BatchAsk] = []  # replied members, wave order

        def stage_ready() -> None:
            nonlocal waiting
            rest: List[BatchAsk] = []
            for a in waiting:
                if a.row in in_flight:
                    rest.append(a)
                    continue
                payload = np.zeros((sys.payload_width,), np.float32)
                body = np.atleast_1d(
                    np.asarray(a.message, np.float32)).reshape(-1)
                payload[:min(len(body), sys.payload_width - 1)] = \
                    body[:sys.payload_width - 1]
                payload[-1] = float(a.prow)
                sys.tell(a.row, payload)
                a.start = cum
                if a.trace is not None:
                    a.t_stage = time.monotonic()
                    a.step_stage = int(sys._host_step)
                in_flight[a.row] = a
            waiting = rest

        def resolve_member(a: BatchAsk, outcome: str) -> None:
            # retro-emitted: the member's in-flight window (staged ->
            # resolved), parented under the SUBMITTER's span so the
            # request tree crosses the thread hop intact
            tracer.emit("ask.member", a.trace, t0=a.t_stage,
                        t1=time.monotonic(), step0=a.step_stage,
                        step1=int(sys._host_step), wave_id=wave_id,
                        slot=a.slot, row=a.row, deferred=a.start > 0,
                        outcome=outcome)

        with wspan.child("wave.flush", wave_id=wave_id, coalesced=True,
                         n_staged=len(waiting)):
            stage_ready()
        first = True
        rounds = 0
        while in_flight:
            # shared budget: one `steps`-deep round for the whole wave,
            # then single steps — a batch of one runs the exact schedule
            # the pre-batching ask() ran ([steps] + [1]*max_extra_steps)
            n_steps = min(a.steps for a in in_flight.values()) \
                if first else 1
            first = False
            rounds += 1
            with wspan.child("wave.step_round", wave_id=wave_id,
                             n_steps=n_steps, round=rounds) as rspan:
                sys.run(n_steps)
                rspan.set(host_step=int(sys._host_step))
            cum += n_steps
            # "all replied?" rides the attention word: the tiny
            # device_get doubles as the run's sync (bridge _drain_one
            # idiom), and the wide promise-block readback is paid only
            # when ATT_LATCH_BIT says some latch is actually high
            att = decode_attention(sys.attention)
            replied_blk = reply_blk = None
            if att["any_latched"] or not getattr(region, "_ask_latch_wired",
                                                 False):
                from ..batched.bridge import read_promise_block
                with wspan.child("wave.readback", wave_id=wave_id,
                                 round=rounds):
                    replied_blk, reply_blk = read_promise_block(
                        sys.state, base, eps, "__promise_replied",
                        "__promise_reply")
            done_rows: List[int] = []
            for row, a in in_flight.items():
                if replied_blk is not None and bool(replied_blk[a.slot]):
                    a.outcome = np.asarray(reply_blk[a.slot])
                    ok_resolved.append(a)
                    with region._lock:
                        region._promise_free.append(a.slot)
                    if a.trace is not None and tracer is not None:
                        resolve_member(a, "reply")
                    done_rows.append(row)
                elif cum - a.start >= a.steps + a.max_extra_steps:
                    # timed out: RETIRE the slot (late replies must land
                    # in a row no future ask will read);
                    # _reclaim_promise_slots returns it once the
                    # straggler's latch shows up
                    with region._lock:
                        region._promise_retired.append(a.slot)
                    a.outcome = TimeoutError(
                        f"ask to shard {a.shard} index {a.index} "
                        f"unanswered after "
                        f"{a.steps + a.max_extra_steps} steps")
                    if a.trace is not None and tracer is not None:
                        resolve_member(a, "timeout")
                    done_rows.append(row)
            for row in done_rows:
                del in_flight[row]
            if waiting:  # duplicates deferred from earlier waves
                with wspan.child("wave.flush", wave_id=wave_id,
                                 deferred=True, n_staged=len(waiting)):
                    stage_ready()

        # durable entity layer (ISSUE 15): ONE group-committed journal
        # write for the whole wave's ok events, BEFORE outcomes reach the
        # callers — an acked write is on disk by the time the ack exists.
        # Regions without attach_entity_journal pay one attribute read.
        if ok_resolved and \
                getattr(region, "_entity_journal", None) is not None:
            with wspan.child("wave.journal", wave_id=wave_id,
                             n_events=len(ok_resolved)):
                region._commit_entity_events(
                    [(a.shard, a.index, a.message) for a in ok_resolved])
    finally:
        wspan.finish(rounds=rounds, steps=cum)


class AskBatcher:
    """Thread-safe futures front end over `execute_ask_batch`.

    `submit()` appends to the pending list and returns a Future; a
    daemon dispatcher thread (started on first submit, the bridge pump
    idiom) closes a batch when `max_batch` asks are pending or
    `window_s` has elapsed since it saw the first one — whichever first
    — and runs it under the region's ask lock. Callers never become
    batch leaders, so no connection handler gets stuck dispatching other
    tenants' traffic under sustained load.

    With a MetricsRegistry: `gateway_ask_batch_size` and
    `gateway_ask_batch_window_us` histograms, plus an "ask_batch"
    collector exposing the summary counters."""

    def __init__(self, region, max_batch: int = 32,
                 window_s: float = 200e-6, steps: int = 2,
                 max_extra_steps: int = 8, registry=None):
        self.region = region
        # a batch larger than the promise pool would guarantee typed
        # exhaustion for the overflow members; cap it at the pool size
        pool = int(getattr(region, "eps", max_batch))
        self.max_batch = max(1, min(int(max_batch), pool))
        self.window_s = float(window_s)
        self.steps = int(steps)
        self.max_extra_steps = int(max_extra_steps)
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._pending: List[BatchAsk] = []
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._batches = 0
        self._asks = 0
        self._multi = 0
        self._max_seen = 0
        self._h_size = self._h_wait = None
        if registry is not None:
            self._h_size = registry.histogram(
                "gateway_ask_batch_size",
                "asks coalesced per shared device step round")
            self._h_wait = registry.histogram(
                "gateway_ask_batch_window_us",
                "microseconds an ask waited for its batch to close")
            registry.register_collector("ask_batch", self.stats)

    # ------------------------------------------------------------- submit
    def submit(self, shard: int, index: int, message: Any,
               steps: Optional[int] = None,
               max_extra_steps: Optional[int] = None) -> Future:
        a = BatchAsk(int(shard), int(index), message,
                     self.steps if steps is None else int(steps),
                     self.max_extra_steps if max_extra_steps is None
                     else int(max_extra_steps),
                     # the submitter's span ctx crosses the dispatcher
                     # thread hop pinned to the ask itself (None when the
                     # request is unsampled — the one read the quiet path
                     # pays)
                     trace=current_ctx())
        a.future = Future()
        a.t_submit = time.perf_counter()
        with self._lock:
            if self._closed:
                raise RuntimeError("AskBatcher is closed")
            self._pending.append(a)
            if self._thread is None:
                t = threading.Thread(target=self._loop,
                                     name="akka-tpu-ask-batcher",
                                     daemon=True)
                self._thread = t
                t.start()
        self._work.set()
        return a.future

    def ask(self, shard: int, index: int, message: Any,
            steps: Optional[int] = None,
            max_extra_steps: Optional[int] = None):
        """Submit and wait: returns the reply payload or raises the
        per-ask exception (TimeoutError / AskPoolExhausted / ...)."""
        return self.submit(shard, index, message, steps,
                           max_extra_steps).result()

    def ask_many(self, requests: Sequence[Any],
                 ctxs: Optional[Sequence[Any]] = None) -> List[Any]:
        """Columnar wave entry (ISSUE 11): `requests` is a sequence of
        `(shard, index, message)` decoded from one binary window.
        Returns outcomes aligned with `requests` — the reply payload or
        the per-ask exception INSTANCE (never raises per-ask).

        `ctxs` (ISSUE 12): optional aligned per-member span contexts —
        one binary window carries MANY traces, so the ambient contextvar
        cannot represent it; the gateway passes each sampled record's
        root ctx explicitly.

        A multi-request wave IS already a batch, so it skips the
        per-call future hop and the dispatcher window entirely: the
        caller's thread runs `execute_ask_batch` directly under the
        region's ask lock (serialized with dispatcher batches by that
        same lock — wave linearization per entity is unchanged). A
        wave of one submits through the dispatcher as usual so it can
        coalesce with concurrent single asks."""
        reqs = list(requests)
        if not reqs:
            return []
        if len(reqs) == 1:
            s, i, m = reqs[0]
            tok = None
            if ctxs is not None and ctxs[0] is not None:
                tok = set_ctx(ctxs[0])  # submit() snapshots it per ask
            try:
                return [self.ask(s, i, m)]
            except BaseException as e:  # noqa: BLE001 — outcome convention
                return [e]
            finally:
                if tok is not None:
                    reset_ctx(tok)
        with self._lock:
            if self._closed:
                raise RuntimeError("AskBatcher is closed")
        batch = [BatchAsk(int(s), int(i), m, self.steps,
                          self.max_extra_steps) for s, i, m in reqs]
        if ctxs is not None:
            for a, c in zip(batch, ctxs):
                a.trace = c
        region = self.region
        t0 = time.perf_counter()
        # waves larger than the promise pool ride consecutive sub-batches
        # (the submit path's max_batch cap, applied here without futures)
        for lo in range(0, len(batch), self.max_batch):
            sub = batch[lo:lo + self.max_batch]
            try:
                with region._ask_lock:
                    execute_ask_batch(region, sub)
            except BaseException as e:  # noqa: BLE001 — never half-resolve
                for a in sub:
                    if a.outcome is None:
                        a.outcome = e
            with self._lock:
                self._batches += 1
                self._asks += len(sub)
                self._max_seen = max(self._max_seen, len(sub))
                if len(sub) > 1:
                    self._multi += 1
            if self._h_size is not None:
                self._h_size.observe(float(len(sub)))
            if self._h_wait is not None:
                # columnar waves never wait for a window to close: the
                # whole wave arrived at once, so its wait is dispatch lag
                self._h_wait.observe((time.perf_counter() - t0) * 1e6)
        return [a.outcome for a in batch]

    # ---------------------------------------------------------- dispatcher
    def _full(self) -> bool:
        with self._lock:
            return len(self._pending) >= self.max_batch

    def _loop(self) -> None:
        while True:
            self._work.wait(0.25)
            self._work.clear()
            if self._closed:
                self._fail_pending(RuntimeError("AskBatcher is closed"))
                return
            while True:
                with self._lock:
                    if not self._pending:
                        break
                # adaptive window: wait for the batch to fill, close on
                # max_batch pending or window_s elapsed, whichever first
                wait_adaptive_close(self._work, self.window_s, self._full)
                with self._lock:
                    close_batch = self._pending[:self.max_batch]
                    del self._pending[:self.max_batch]
                if close_batch:
                    self._run_batch(close_batch)

    def _run_batch(self, close_batch: List[BatchAsk]) -> None:
        t_close = time.perf_counter()
        region = self.region
        try:
            with region._ask_lock:
                execute_ask_batch(region, close_batch)
        except BaseException as e:  # noqa: BLE001 — waiters must never hang
            for a in close_batch:
                if a.outcome is None:
                    a.outcome = e
        with self._lock:
            self._batches += 1
            self._asks += len(close_batch)
            self._max_seen = max(self._max_seen, len(close_batch))
            if len(close_batch) > 1:
                self._multi += 1
        if self._h_size is not None:
            self._h_size.observe(float(len(close_batch)))
        for a in close_batch:
            if self._h_wait is not None:
                self._h_wait.observe((t_close - a.t_submit) * 1e6)
            if isinstance(a.outcome, BaseException):
                a.future.set_exception(a.outcome)
            else:
                a.future.set_result(a.outcome)

    # ------------------------------------------------------------ lifecycle
    def _fail_pending(self, exc: BaseException) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        for a in pending:
            if a.future is not None and not a.future.done():
                a.future.set_exception(exc)

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            self._closed = True
            t = self._thread
        self._work.set()
        if t is not None:
            t.join(timeout)
        self._fail_pending(RuntimeError("AskBatcher is closed"))

    # ---------------------------------------------------------------- stats
    def stats(self):
        """Numeric summary (registry-collector compatible)."""
        with self._lock:
            b, n = self._batches, self._asks
            return {"batches": float(b), "asks": float(n),
                    "mean_batch_size": (n / b) if b else 0.0,
                    "max_batch_size": float(self._max_seen),
                    "multi_ask_batches": float(self._multi),
                    "pending": float(len(self._pending)),
                    # the engine's wave counter (ISSUE 12): every
                    # execute_ask_batch invocation is one wave, and this
                    # is the id the newest wave's spans carry — the
                    # cross-check key between the trace timeline and
                    # these stats
                    "last_wave_id": float(
                        getattr(self.region, "_wave_seq", 0))}
