"""Sharding protocol messages.

Reference parity: akka-cluster-sharding/src/main/scala/akka/cluster/sharding/
ShardRegion.scala (StartEntity :440-446, Passivate, extractEntityId/
extractShardId :42-43) and ShardCoordinator.scala Internal protocol
(Register/RegisterAck/GetShardHome/ShardHome/BeginHandOff/HandOff/
ShardStopped/RebalanceTick).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple


# -- user-facing -------------------------------------------------------------

@dataclass(frozen=True)
class ShardingEnvelope:
    """(reference: sharding-typed ClusterSharding.scala:362) — explicit
    (entity_id, message) addressing; the default extractor understands it."""
    entity_id: str
    message: Any


@dataclass(frozen=True)
class StartEntity:
    """Start an entity without sending it a message (remember-entities uses
    this internally; reference ShardRegion.scala:440)."""
    entity_id: str


@dataclass(frozen=True)
class StartEntityAck:
    entity_id: str
    shard_id: str


@dataclass(frozen=True)
class Passivate:
    """Entity → its Shard parent: stop me gracefully; buffered messages will
    restart me (reference: ShardRegion.Passivate)."""
    stop_message: Any = "poison-pill"


# -- region <-> coordinator ---------------------------------------------------

@dataclass(frozen=True)
class Register:
    """Region registers itself (path string resolves cross-node)."""
    region_path: str


@dataclass(frozen=True)
class RegisterProxy:
    region_path: str


@dataclass(frozen=True)
class RegisterAck:
    coordinator_path: str


@dataclass(frozen=True)
class GetShardHome:
    shard_id: str


@dataclass(frozen=True)
class ShardHome:
    shard_id: str
    region_path: str


@dataclass(frozen=True)
class HostShard:
    """Coordinator → owning region: you now host this shard."""
    shard_id: str


@dataclass(frozen=True)
class ShardStarted:
    shard_id: str


@dataclass(frozen=True)
class BeginHandOff:
    """Coordinator → all regions: forget this shard's home (rebalance step 1)."""
    shard_id: str


@dataclass(frozen=True)
class BeginHandOffAck:
    shard_id: str


@dataclass(frozen=True)
class HandOff:
    """Coordinator → owning region: stop the shard's entities, then ack."""
    shard_id: str


@dataclass(frozen=True)
class ShardStopped:
    shard_id: str


@dataclass(frozen=True)
class GracefulShutdownReq:
    region_path: str


# -- introspection ------------------------------------------------------------

@dataclass(frozen=True)
class GetShardRegionState:
    pass


@dataclass(frozen=True)
class ShardState:
    shard_id: str
    entity_ids: Tuple[str, ...]


@dataclass(frozen=True)
class CurrentShardRegionState:
    shards: Tuple[ShardState, ...]


@dataclass(frozen=True)
class GetClusterShardingStats:
    timeout: float = 3.0


@dataclass(frozen=True)
class ClusterShardingStats:
    regions: Any  # Dict[address_str, Dict[shard_id, entity_count]]
