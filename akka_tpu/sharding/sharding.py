"""ClusterSharding extension: start/proxy/region lookup.

Reference parity: akka-cluster-sharding/src/main/scala/akka/cluster/sharding/
ClusterSharding.scala (start/startProxy/shardRegion) — per type-name it
starts (a) a ClusterSingletonManager hosting the ShardCoordinator and (b) the
local ShardRegion.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from ..actor.props import Props
from ..actor.system import ActorSystem, ExtensionId
from ..cluster_tools.singleton import (ClusterSingletonManager,
                                       ClusterSingletonSettings)
from .coordinator import (LeastShardAllocationStrategy, ShardAllocationStrategy,
                          ShardCoordinator)
from .region import (ClusterShardingSettings, RememberEntitiesStore,
                     ShardRegion, default_extract_entity_id,
                     make_default_extract_shard_id)


class ClusterSharding(ExtensionId):
    def create_extension(self, system: ActorSystem) -> "_ShardingExt":
        return _ShardingExt(system)

    @staticmethod
    def get(system: ActorSystem) -> "_ShardingExt":
        return system.register_extension(ClusterSharding())


class _ShardingExt:
    def __init__(self, system: ActorSystem):
        self.system = system
        self._regions: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def start(self, type_name: str,
              entity_props: "Props | Callable[[str], Props]",
              settings: Optional[ClusterShardingSettings] = None,
              extract_entity_id=None, extract_shard_id=None,
              allocation_strategy: Optional[ShardAllocationStrategy] = None,
              store: Optional[RememberEntitiesStore] = None):
        """Start a region that hosts entities (reference:
        ClusterSharding.start). `entity_props` is a Props (same for every
        entity) or a factory entity_id -> Props."""
        settings = settings or ClusterShardingSettings()
        factory = entity_props if callable(entity_props) \
            and not isinstance(entity_props, Props) else (lambda _eid: entity_props)
        return self._start(type_name, factory, settings, extract_entity_id,
                           extract_shard_id, allocation_strategy, store)

    def start_proxy(self, type_name: str,
                    settings: Optional[ClusterShardingSettings] = None,
                    extract_entity_id=None, extract_shard_id=None):
        """Region in proxy mode: routes but never hosts (reference:
        ClusterSharding.startProxy)."""
        settings = settings or ClusterShardingSettings()
        return self._start(type_name, None, settings, extract_entity_id,
                           extract_shard_id, None, None)

    def _start(self, type_name, entity_props_factory, settings,
               extract_entity_id, extract_shard_id, allocation_strategy,
               store):
        with self._lock:
            if type_name in self._regions:
                return self._regions[type_name]
            manager_name = f"sharding-{type_name}-coordinator"
            manager_path = f"/system/{manager_name}"
            # every node runs a singleton manager; the oldest hosts the
            # coordinator child named "coordinator"
            self.system.system_actor_of(
                Props.create(
                    ClusterSingletonManager,
                    Props.create(ShardCoordinator, type_name,
                                 allocation_strategy or LeastShardAllocationStrategy(),
                                 settings.rebalance_interval),
                    ClusterSingletonSettings(
                        singleton_name="coordinator", role=settings.role,
                        hand_over_retry_interval=settings.retry_interval)),
                manager_name)
            region = self.system.system_actor_of(
                Props.create(ShardRegion, type_name, entity_props_factory,
                             extract_entity_id, extract_shard_id, settings,
                             manager_path, store),
                f"sharding-{type_name}")
            self._regions[type_name] = region
            return region

    def shard_region(self, type_name: str):
        """(reference: ClusterSharding.shardRegion)"""
        with self._lock:
            if type_name not in self._regions:
                raise KeyError(f"sharding type {type_name!r} not started")
            return self._regions[type_name]
