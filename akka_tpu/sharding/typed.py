"""Typed sharding façade: EntityTypeKey / Entity / init / EntityRef.

Reference parity: akka-cluster-sharding-typed/src/main/scala/akka/cluster/
sharding/typed/scaladsl/ClusterSharding.scala (:178 init, :234 entityRefFor,
:362 ShardingEnvelope) — entities are typed Behaviors; `init(Entity(key,
ctx -> behavior))` returns an ActorRef[ShardingEnvelope].
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..actor.ref import ActorRef
from ..pattern.ask import ask
from ..typed.adapter import props_from_behavior
from .messages import ShardingEnvelope
from .region import ClusterShardingSettings
from .sharding import ClusterSharding as _ClassicSharding


@dataclass(frozen=True)
class EntityTypeKey:
    """(reference: scaladsl/EntityTypeKey.scala)"""
    name: str


@dataclass(frozen=True)
class EntityContext:
    entity_type_key: EntityTypeKey
    entity_id: str
    shard: Optional[ActorRef] = None


@dataclass(frozen=True)
class Entity:
    """(reference: scaladsl/Entity.scala) — behavior factory per entity."""
    type_key: EntityTypeKey
    create_behavior: Callable[[EntityContext], Any]
    settings: Optional[ClusterShardingSettings] = None
    stop_message: Any = None
    extract_entity_id: Any = None
    extract_shard_id: Any = None


class EntityRef:
    """(reference: EntityRef — tell/ask addressed by entity id)"""

    def __init__(self, region: ActorRef, type_key: EntityTypeKey,
                 entity_id: str, system):
        self.region = region
        self.type_key = type_key
        self.entity_id = entity_id
        self._system = system

    def tell(self, message: Any, sender: Optional[ActorRef] = None) -> None:
        self.region.tell(ShardingEnvelope(self.entity_id, message), sender)

    def ask(self, message: Any, timeout: float = 5.0):
        return ask(self.region, ShardingEnvelope(self.entity_id, message),
                   timeout=timeout, system=self._system)

    def __repr__(self) -> str:
        return f"EntityRef({self.type_key.name}/{self.entity_id})"


class ClusterShardingTyped:
    """`ClusterShardingTyped.get(system).init(Entity(...))`"""

    def __init__(self, system):
        self.system = system
        self._classic = _ClassicSharding.get(system)

    @staticmethod
    def get(system) -> "ClusterShardingTyped":
        return ClusterShardingTyped(system)

    def init(self, entity: Entity) -> ActorRef:
        key = entity.type_key

        def props_factory(entity_id: str):
            behavior = entity.create_behavior(EntityContext(key, entity_id))
            return props_from_behavior(behavior)

        return self._classic.start(
            key.name, props_factory, entity.settings,
            extract_entity_id=entity.extract_entity_id,
            extract_shard_id=entity.extract_shard_id)

    def init_device(self, spec, mesh=None):
        """Device-backed entity type: entities become rows in a
        ShardedBatchedSystem on the mesh (see sharding/device.py —
        the ClusterSharding.init analogue for BatchedBehavior entities)."""
        from .device import DeviceShardRegion
        region = DeviceShardRegion(spec, mesh=mesh)
        self._device_regions = getattr(self, "_device_regions", {})
        self._device_regions[spec.type_name] = region
        return region

    def device_region(self, type_name: str):
        return getattr(self, "_device_regions", {})[type_name]

    def entity_ref_for(self, type_key: EntityTypeKey,
                       entity_id: str) -> EntityRef:
        region = self._classic.shard_region(type_key.name)
        return EntityRef(region, type_key, entity_id, self.system)

    def shard_region(self, type_key: EntityTypeKey) -> ActorRef:
        return self._classic.shard_region(type_key.name)
