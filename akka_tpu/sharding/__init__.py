"""Cluster sharding: entities → shards → regions (SURVEY.md §2.5).

Control plane (this package): host actors mirroring the reference's
ShardRegion / ShardCoordinator / Shard protocol. Data plane: the sharded
batched runtime (akka_tpu/batched/sharded.py) maps shards onto mesh axes with
all_to_all exchange — the TPU-native analogue noted in SURVEY.md §2.5.
"""

from .messages import (BeginHandOff, ClusterShardingStats,
                       CurrentShardRegionState, GetClusterShardingStats,
                       GetShardHome, GetShardRegionState, HandOff, HostShard,
                       Passivate, Register, RegisterAck, ShardHome,
                       ShardingEnvelope, ShardState, ShardStopped, StartEntity,
                       StartEntityAck)
from .coordinator import (LeastShardAllocationStrategy,
                          ShardAllocationStrategy, ShardCoordinator)
from .region import (ClusterShardingSettings, DDataRememberEntitiesStore,
                     InProcRememberEntitiesStore,
                     JournalRememberEntitiesStore, RememberEntitiesStore,
                     Shard, ShardRegion, default_extract_entity_id,
                     make_default_extract_shard_id,
                     make_remember_entities_store)
from .sharding import ClusterSharding
from .typed import (ClusterShardingTyped, Entity, EntityContext, EntityRef,
                    EntityTypeKey)
from .daemon_process import (ShardedDaemonProcess,
                             ShardedDaemonProcessSettings)
from .ask_batch import AskBatcher, ContinuousWaveScheduler

__all__ = [
    "ShardingEnvelope", "StartEntity", "StartEntityAck", "Passivate",
    "ClusterSharding", "ClusterShardingSettings", "ShardRegion", "Shard",
    "ShardCoordinator", "ShardAllocationStrategy",
    "LeastShardAllocationStrategy", "RememberEntitiesStore",
    "InProcRememberEntitiesStore", "JournalRememberEntitiesStore",
    "DDataRememberEntitiesStore", "make_remember_entities_store",
    "default_extract_entity_id",
    "make_default_extract_shard_id", "GetShardRegionState",
    "CurrentShardRegionState", "GetClusterShardingStats",
    "ClusterShardingStats", "ShardState",
    "ClusterShardingTyped", "Entity", "EntityContext", "EntityRef",
    "EntityTypeKey",
    "ShardedDaemonProcess", "ShardedDaemonProcessSettings",
    "AskBatcher", "ContinuousWaveScheduler",
]
