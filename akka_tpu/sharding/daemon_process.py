"""ShardedDaemonProcess: N always-alive, sharding-pinned workers.

Reference parity: akka-cluster-sharding-typed/src/main/scala/akka/cluster/
sharding/typed/scaladsl/ShardedDaemonProcess.scala:20-39 and impl/
ShardedDaemonProcessImpl.scala — the "keep N consumers of a sharded event
stream running" pattern. Each instance index becomes a sharded entity whose
id IS its shard id (one shard per instance, so the allocation strategy
spreads the N workers across the cluster and rebalances them with it), and
a keep-alive pinger periodically sends StartEntity for every index so
workers start immediately, restart after crashes, and re-spawn on their new
home after a rebalance or node loss (KeepAlivePinger in the reference impl).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..actor.actor import Actor
from ..actor.props import Props
from ..actor.ref import ActorRef
from .messages import StartEntity
from .region import ClusterShardingSettings
from .typed import ClusterShardingTyped, Entity, EntityTypeKey


@dataclass(frozen=True)
class ShardedDaemonProcessSettings:
    """(reference: ShardedDaemonProcessSettings.scala)"""
    keep_alive_interval: float = 10.0   # reference default: 10s
    role: Optional[str] = None
    sharding_settings: Optional[ClusterShardingSettings] = None


class _KeepAlivePinger(Actor):
    """(reference: ShardedDaemonProcessImpl.KeepAlivePinger) — periodically
    StartEntity-pings every instance id; runs on every node hosting the
    type so at least one live node keeps the workers alive through
    departures. StartEntityAck replies are absorbed here."""

    class _Tick:
        pass

    def __init__(self, region: ActorRef, ids: tuple, interval: float):
        super().__init__()
        self._region = region
        self._ids = ids
        self._interval = interval
        self._task = None

    def pre_start(self) -> None:
        self._ping()
        self._task = self.context.system.scheduler \
            .schedule_tell_with_fixed_delay(
                self._interval, self._interval, self.self_ref, self._Tick())

    def post_stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    def _ping(self) -> None:
        for eid in self._ids:
            self._region.tell(StartEntity(eid), self.self_ref)

    def receive(self, message: Any) -> Any:
        if isinstance(message, self._Tick):
            self._ping()
        # StartEntityAck and anything else: absorbed


class ShardedDaemonProcess:
    """`ShardedDaemonProcess.get(system).init(name, n, factory)`
    (reference: scaladsl/ShardedDaemonProcess.scala:20)"""

    def __init__(self, system):
        self.system = system

    @staticmethod
    def get(system) -> "ShardedDaemonProcess":
        return ShardedDaemonProcess(system)

    def init(self, name: str, number_of_instances: int,
             behavior_factory: Callable[[int], Any],
             stop_message: Any = None,
             settings: Optional[ShardedDaemonProcessSettings] = None
             ) -> ActorRef:
        """Start (this node's share of) N always-alive workers; returns the
        backing shard region. `behavior_factory(i)` builds worker i's typed
        behavior; workers are addressed internally as entities "0".."N-1"
        of type `sharded-daemon-process-{name}`."""
        settings = settings or ShardedDaemonProcessSettings()
        ids = tuple(str(i) for i in range(number_of_instances))
        key = EntityTypeKey(f"sharded-daemon-process-{name}")

        import dataclasses
        base = settings.sharding_settings or \
            ClusterShardingSettings(role=settings.role)
        # one shard per instance: the id IS the shard (reference impl's
        # shardId = entityId message extractor), so LeastShardAllocation
        # spreads and rebalances the workers like any other shards;
        # daemons never passivate
        sharding_settings = dataclasses.replace(
            base, number_of_shards=number_of_instances,
            passivate_idle_after=None)

        def extract_shard_id(message: Any) -> Optional[str]:
            from .messages import ShardingEnvelope
            if isinstance(message, StartEntity):
                return message.entity_id
            if isinstance(message, ShardingEnvelope):
                return message.entity_id
            return None

        region = ClusterShardingTyped.get(self.system).init(Entity(
            type_key=key,
            create_behavior=lambda ctx: behavior_factory(int(ctx.entity_id)),
            settings=sharding_settings,
            stop_message=stop_message,
            extract_shard_id=extract_shard_id))
        self.system.actor_of(
            Props.create(_KeepAlivePinger, region, ids,
                         settings.keep_alive_interval),
            f"sharded-daemon-pinger-{name}")
        return region
