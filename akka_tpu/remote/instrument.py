"""RemoteInstrument: user-pluggable per-message wire metadata hooks.

Reference parity: akka-remote/src/main/scala/akka/remote/artery/
RemoteInstrument.scala:32 — each instrument owns a reserved identifier
(1..31) in the envelope's metadata section, writes opaque bytes at
serialize time on the sender (`remoteWriteMetadata`) and reads them back
at deliver time on the receiver (`remoteReadMetadata`), plus optional
sent/received timing callbacks. This is the seam tracing/telemetry
vendors plug into (context propagation across actor messages) without
touching payload serialization.

Register programmatically
(`provider.remote_instruments.add(instr)`) or via config:

    akka.remote.instruments = ["my.module:MyInstrument"]
"""

from __future__ import annotations

import importlib
import logging
from typing import Any, Dict, List, Optional

_log = logging.getLogger("akka_tpu.remote.instrument")


class RemoteInstrument:
    """(reference: artery/RemoteInstrument.scala:32)"""

    #: reserved metadata key, 1..31; unique per instrument in a system
    identifier: int = 1

    def remote_write_metadata(self, recipient, message: Any,
                              sender) -> Optional[bytes]:
        """Called on the SENDING side for every outbound remote message.
        Return the metadata bytes to ride the envelope (None = nothing)."""
        return None

    def remote_read_metadata(self, recipient, message: Any, sender,
                             metadata: bytes) -> None:
        """Called on the RECEIVING side before delivery, with the bytes
        the same-identifier instrument wrote on the sender."""

    def remote_message_sent(self, recipient, message: Any, sender,
                            size: int) -> None:
        """Timing/accounting hook after a successful transport send."""

    def remote_message_received(self, recipient, message: Any, sender,
                                size: int) -> None:
        """Timing/accounting hook after inbound deserialization."""


class RemoteInstruments:
    """The per-provider aggregate: fans hooks out to every registered
    instrument and marshals the metadata dict that rides WireEnvelope
    (reference: artery/RemoteInstruments.scala — the composite that
    serializes all instruments' metadata into the envelope block)."""

    def __init__(self, instruments: Optional[List[RemoteInstrument]] = None):
        self._instruments: List[RemoteInstrument] = []
        for ins in instruments or []:
            self.add(ins)

    def add(self, instrument: RemoteInstrument) -> None:
        key = int(instrument.identifier)
        if not 1 <= key <= 31:
            raise ValueError(
                f"RemoteInstrument identifier {key} outside the reserved "
                f"1..31 range (RemoteInstrument.scala identifier contract)")
        if any(i.identifier == key for i in self._instruments):
            raise ValueError(f"duplicate RemoteInstrument identifier {key}")
        self._instruments.append(instrument)

    def __bool__(self) -> bool:
        return bool(self._instruments)

    def __len__(self) -> int:
        return len(self._instruments)

    # -- envelope integration ------------------------------------------------
    def write_metadata(self, recipient, message, sender
                       ) -> Optional[Dict[int, bytes]]:
        md: Dict[int, bytes] = {}
        for ins in self._instruments:
            try:
                b = ins.remote_write_metadata(recipient, message, sender)
            except Exception:  # noqa: BLE001 — instruments must not break sends
                _log.warning("RemoteInstrument %s remote_write_metadata "
                             "failed", type(ins).__name__, exc_info=True)
                continue
            if b:
                md[ins.identifier] = bytes(b)
        return md or None

    def read_metadata(self, recipient, message, sender,
                      metadata: Optional[Dict[int, bytes]]) -> None:
        if not metadata:
            return
        for ins in self._instruments:
            b = metadata.get(ins.identifier)
            if b is not None:
                try:
                    ins.remote_read_metadata(recipient, message, sender, b)
                except Exception:  # noqa: BLE001
                    _log.warning("RemoteInstrument %s remote_read_metadata "
                                 "failed", type(ins).__name__, exc_info=True)
                    continue

    def message_sent(self, recipient, message, sender, size: int) -> None:
        for ins in self._instruments:
            try:
                ins.remote_message_sent(recipient, message, sender, size)
            except Exception:  # noqa: BLE001
                _log.warning("RemoteInstrument %s remote_message_sent "
                             "failed", type(ins).__name__, exc_info=True)
                continue

    def message_received(self, recipient, message, sender,
                         size: int) -> None:
        for ins in self._instruments:
            try:
                ins.remote_message_received(recipient, message, sender, size)
            except Exception:  # noqa: BLE001
                _log.warning("RemoteInstrument %s remote_message_received "
                             "failed", type(ins).__name__, exc_info=True)
                continue

    @staticmethod
    def from_config(specs) -> "RemoteInstruments":
        """Build from config entries of the form "module.path:ClassName"
        (the create-instruments-by-FQCN seam of RemoteInstrument.scala)."""
        out = RemoteInstruments()
        for spec in specs or []:
            mod_name, _, cls_name = str(spec).partition(":")
            cls = getattr(importlib.import_module(mod_name), cls_name)
            out.add(cls())
        return out
