"""Phi-accrual + deadline failure detectors and the per-resource registry.

Reference parity: akka-remote/src/main/scala/akka/remote/
PhiAccrualFailureDetector.scala:57 (normal-distribution estimate of heartbeat
arrival intervals; phi = -log10(P(arrival later than now))),
DeadlineFailureDetector.scala, DefaultFailureDetectorRegistry.scala.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, Generic, Hashable, Optional, TypeVar

T = TypeVar("T", bound=Hashable)


class FailureDetector:
    def heartbeat(self) -> None:
        raise NotImplementedError

    @property
    def is_available(self) -> bool:
        raise NotImplementedError

    @property
    def is_monitoring(self) -> bool:
        raise NotImplementedError


class HeartbeatHistory:
    """Bounded sample window with streaming mean/variance
    (reference: PhiAccrualFailureDetector.HeartbeatHistory)."""

    __slots__ = ("max_sample_size", "_intervals", "_sum", "_sq_sum")

    def __init__(self, max_sample_size: int):
        self.max_sample_size = max_sample_size
        self._intervals: deque = deque()
        self._sum = 0.0
        self._sq_sum = 0.0

    def add(self, interval: float) -> None:
        if len(self._intervals) >= self.max_sample_size:
            old = self._intervals.popleft()
            self._sum -= old
            self._sq_sum -= old * old
        self._intervals.append(interval)
        self._sum += interval
        self._sq_sum += interval * interval

    def __len__(self) -> int:
        return len(self._intervals)

    @property
    def mean(self) -> float:
        n = len(self._intervals)
        return self._sum / n if n else 0.0

    @property
    def variance(self) -> float:
        n = len(self._intervals)
        if not n:
            return 0.0
        m = self.mean
        return max(self._sq_sum / n - m * m, 0.0)

    @property
    def std_deviation(self) -> float:
        return math.sqrt(self.variance)


class PhiAccrualFailureDetector(FailureDetector):
    def __init__(self, threshold: float = 8.0, max_sample_size: int = 1000,
                 min_std_deviation: float = 0.1,
                 acceptable_heartbeat_pause: float = 3.0,
                 first_heartbeat_estimate: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = threshold
        self.min_std_deviation = min_std_deviation
        self.acceptable_heartbeat_pause = acceptable_heartbeat_pause
        self.clock = clock
        self._history = HeartbeatHistory(max_sample_size)
        # bootstrap sample (reference: firstHeartbeatEstimate with std-dev/4)
        mean = first_heartbeat_estimate
        std = mean / 4.0
        self._history.add(mean - std)
        self._history.add(mean + std)
        self._last_timestamp: Optional[float] = None
        self._lock = threading.Lock()

    def heartbeat(self) -> None:
        with self._lock:
            now = self.clock()
            if self._last_timestamp is not None:
                interval = now - self._last_timestamp
                if self.is_available_at(now):
                    # winsorize the admitted sample: a scheduling stall that
                    # slips under a generous acceptable-pause would otherwise
                    # enter the history at full size, inflate the std
                    # deviation, admit even LARGER stalls, and run away
                    # until phi can never cross the threshold (observed on a
                    # loaded single-core host: 180s of real silence went
                    # undetected). Capping at mean+pause keeps the estimator
                    # adaptive without the unbounded ratchet.
                    cap = self._history.mean + self.acceptable_heartbeat_pause
                    self._history.add(min(interval, cap))
            self._last_timestamp = now

    def phi(self, at: Optional[float] = None) -> float:
        with self._lock:
            return self._phi(at if at is not None else self.clock())

    def _phi(self, now: float) -> float:
        if self._last_timestamp is None:
            return 0.0
        elapsed = now - self._last_timestamp
        mean = self._history.mean + self.acceptable_heartbeat_pause
        std = max(self._history.std_deviation, self.min_std_deviation)
        y = (elapsed - mean) / std
        # logistic approximation of the normal CDF (reference :230-238).
        # The reference computes this in IEEE doubles, where a hugely
        # NEGATIVE y (a fresh heartbeat against a wide acceptable-pause
        # window, e.g. load-dilated test configs) overflows e to +inf and
        # phi comes out 0; python's math.exp RAISES instead, which used to
        # crash the cluster daemon's reap tick on every loaded run — clamp
        # explicitly (exp(709) is the float64 edge)
        exp_arg = -y * (1.5976 + 0.070566 * y * y)
        if exp_arg > 709.0:
            return 0.0  # arrival later is virtually certain: phi ~ 0
        e = math.exp(exp_arg)
        if elapsed > mean:
            return -math.log10(e / (1.0 + e)) if e != 0 else 35.0
        return -math.log10(1.0 - 1.0 / (1.0 + e))

    @property
    def is_available(self) -> bool:
        return self.is_available_at(self.clock())

    def is_available_at(self, at: float) -> bool:
        return self._phi(at) < self.threshold

    @property
    def is_monitoring(self) -> bool:
        return self._last_timestamp is not None


class DeadlineFailureDetector(FailureDetector):
    """(reference: DeadlineFailureDetector.scala)"""

    def __init__(self, acceptable_heartbeat_pause: float = 4.0,
                 heartbeat_interval: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.deadline = acceptable_heartbeat_pause + heartbeat_interval
        self.clock = clock
        self._last = None

    def heartbeat(self) -> None:
        self._last = self.clock()

    @property
    def is_available(self) -> bool:
        return self._last is None or (self.clock() - self._last) <= self.deadline

    @property
    def is_monitoring(self) -> bool:
        return self._last is not None


class FailureDetectorRegistry(Generic[T]):
    """Per-resource (address) detector instances
    (reference: DefaultFailureDetectorRegistry.scala)."""

    def __init__(self, factory: Callable[[], FailureDetector]):
        self.factory = factory
        self._detectors: Dict[T, FailureDetector] = {}
        self._lock = threading.Lock()

    def heartbeat(self, resource: T) -> None:
        with self._lock:
            fd = self._detectors.get(resource)
            if fd is None:
                fd = self.factory()
                self._detectors[resource] = fd
        fd.heartbeat()

    def is_available(self, resource: T) -> bool:
        fd = self._detectors.get(resource)
        return fd.is_available if fd is not None else True

    def is_monitoring(self, resource: T) -> bool:
        fd = self._detectors.get(resource)
        return fd.is_monitoring if fd is not None else False

    def phi(self, resource: T) -> float:
        """Current suspicion level of a monitored resource: the detector's
        phi for accrual detectors, 0.0 for boolean detectors or resources
        never heartbeated. The sentinel records this in device_suspected
        events so a post-mortem shows HOW suspicious the shard looked."""
        fd = self._detectors.get(resource)
        if fd is None:
            return 0.0
        return float(fd.phi()) if hasattr(fd, "phi") else 0.0

    def remove(self, resource: T) -> None:
        with self._lock:
            self._detectors.pop(resource, None)

    def reset(self) -> None:
        with self._lock:
            self._detectors.clear()
