"""Remote deployment: create an actor ON another node from a local Props.

Reference parity: akka-remote/src/main/scala/akka/remote/
RemoteActorRefProvider.scala:152 (actorOf consults the deployer; a
RemoteScope deploy routes creation through the remote daemon),
RemoteDeployer.scala (parses `remote = "akka://sys@host:port"` deployment
config), and RemoteDaemon (remote/RemoteActorRefProvider.scala RemoteDeadLetterActorRef
sibling — the `/remote` guardian that instantiates DaemonMsgCreate payloads,
remote/RemoteSystemDaemon semantics).

TPU-first deviations, by design:
- Props travel as a *recipe* (module-qualified class + codec-encoded ctor
  args), never as pickled closures — consistent with the fixed-schema wire
  (serialization/codec.py). Classes must be registered deployable on the
  target (register_deployable) unless the node opts into trusted mode
  (`akka.remote.allow-pickle = true`, mirroring the reference's
  untrusted-mode gate, remote/RemoteActorRefProvider.scala untrusted checks).
- The deployed actor is supervised by the target's remote daemon (restart on
  failure per its strategy); the deploying parent observes lifecycle via
  remote DeathWatch. The reference instead proxies Supervise/Failed over the
  wire; collapsing that round-trip keeps supervision local to the data.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from ..actor.actor import Actor
from ..actor.deploy import Deploy, RemoteScope
from ..actor.messages import DeadLetter, Terminated
from ..actor.path import Address
from ..actor.props import Props
from ..serialization.codec import register_wire_class

_DEPLOYABLE: Dict[str, type] = {}
_DEPLOYABLE_LOCK = threading.Lock()


def register_deployable(cls: type) -> type:
    """Mark an Actor class as instantiable by remote DaemonMsgCreate on this
    node. Usable as a decorator. Also registers the class key both ways."""
    key = f"{cls.__module__}:{cls.__qualname__}"
    with _DEPLOYABLE_LOCK:
        _DEPLOYABLE[key] = cls
    return cls


def _class_key(cls: type) -> str:
    return f"{cls.__module__}:{cls.__qualname__}"


def _resolve_deployable(key: str, allow_import: bool) -> type:
    with _DEPLOYABLE_LOCK:
        cls = _DEPLOYABLE.get(key)
    if cls is not None:
        return cls
    if not allow_import:
        raise PermissionError(
            f"refusing to deploy unregistered class {key!r}: call "
            "register_deployable on the target node (or enable "
            "akka.remote.allow-pickle for trusted links)")
    module, _, qualname = key.partition(":")
    import importlib
    obj: Any = importlib.import_module(module)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    if not isinstance(obj, type):
        raise TypeError(f"deploy key {key!r} did not resolve to a class")
    return obj


@register_wire_class
@dataclass(frozen=True)
class DaemonMsgCreate:
    """The wire recipe for a remote spawn (reference:
    remote/DaemonMsgCreateSerializer.scala — class + args + deploy + path)."""
    class_key: str
    args: tuple
    kwargs: tuple                 # sorted (name, value) items
    child_name: str               # daemon-local (mangled) child name
    origin_path: str              # full origin-side path, for diagnostics
    dispatcher: Optional[str] = None
    mailbox: Optional[str] = None


@register_wire_class
@dataclass(frozen=True)
class DaemonMsgCreateFailed:
    child_name: str
    reason: str


@dataclass(frozen=True)
class _DeliverToChild:
    """Local-only wrapper: an inbound message that raced the child's
    creation (transport delivered it before the daemon's mailbox processed
    DaemonMsgCreate). The daemon buffers until the child exists — the remote
    analogue of mailbox-before-Create buffering (dungeon/Dispatch.scala:63-100
    enqueues Create before any user message can run). `system` marks system
    messages (Watch/Unwatch/Terminate), which must not be lost either."""
    child_name: str
    message: Any
    sender: Any
    system: bool = False


def mangle(origin_path: str) -> str:
    """Deterministic daemon-child name for a deployed actor: both ends derive
    it from the origin-side path (reference: RemoteActorRefProvider gives
    deployed actors paths under /remote/<protocol>/<origin-addr>/...).
    urlsafe-base64 so the name stays a single valid path element."""
    import base64
    return base64.urlsafe_b64encode(origin_path.encode()).decode().rstrip("=")


def deployed_path_for(remote_address: Address, origin_path: str):
    """The full path of the actor once deployed at `remote_address`."""
    from ..actor.path import ActorPath
    return ActorPath(remote_address) / "remote" / mangle(origin_path)


class RemoteSystemDaemon(Actor):
    """Lives at /remote on every remote-enabled system; instantiates
    DaemonMsgCreate recipes as supervised children (reference:
    RemoteSystemDaemon in remote/RemoteActorRefProvider.scala)."""

    MAX_BUFFERED_PER_CHILD = 1000

    def __init__(self, provider):
        super().__init__()
        self.provider = provider
        self._pending: Dict[str, list] = {}   # child_name -> early messages
        self._failed: Dict[str, str] = {}     # child_name -> reason
        # origin parent path -> daemon-child names whose life is tied to it
        self._parent_children: Dict[str, set] = {}

    @property
    def supervisor_strategy(self):
        from ..actor.supervision import OneForOneStrategy, default_decider
        return OneForOneStrategy(decider=default_decider)

    def receive(self, message: Any):
        if isinstance(message, DaemonMsgCreate):
            self._create(message)
        elif isinstance(message, _DeliverToChild):
            self._deliver(message)
        elif isinstance(message, tuple) and message and message[0] == "drop-pending":
            for m, snd, _sys in self._pending.pop(message[1], ()):
                self.context.system.event_stream.publish(
                    DeadLetter(m, snd, self.self_ref))
        elif isinstance(message, tuple) and message and message[0] == "drop-failed":
            # failure records only need to live long enough to dead-letter
            # in-flight sends; on a long-lived node they must not accumulate
            self._failed.pop(message[1], None)
        elif isinstance(message, tuple) and message and message[0] == "origin-parent-died":
            for name in self._parent_children.pop(message[1], ()):
                child = self.context.child(name)
                if child is not None:
                    self.context.stop(child)
        elif isinstance(message, Terminated):
            # one of OUR children stopped: drop life-cycle bookkeeping, and
            # once an origin parent has no deployed children left, unwatch it
            # and drop its (now empty) entry
            name = message.actor.path.name
            for parent, kids in list(self._parent_children.items()):
                kids.discard(name)
                if not kids:
                    del self._parent_children[parent]
                    parent_ref = self.provider.resolve_actor_ref(parent)
                    if parent_ref is not self.provider.dead_letters:
                        self.context.unwatch(parent_ref)
        else:
            return NotImplemented
        return None

    @staticmethod
    def _send_to(child, message, sender, system: bool) -> None:
        from ..dispatch import sysmsg as _sysmsg
        from .provider import _RemoteTerminate
        if isinstance(message, _RemoteTerminate):
            child.stop()
        elif system and isinstance(message, _sysmsg.SystemMessage):
            if isinstance(message, (_sysmsg.Watch, _sysmsg.Unwatch)):
                # a Watch that raced the deploy deserialized its watchee ref
                # BEFORE the child existed → dead letters; by protocol the
                # watchee of a Watch delivered to child X is X, so re-point
                import dataclasses
                message = dataclasses.replace(message, watchee=child)
            child.send_system_message(message)
        else:
            child.tell(message, sender)

    def _deliver(self, msg: _DeliverToChild) -> None:
        child = self.context.child(msg.child_name)
        if child is not None:
            self._send_to(child, msg.message, msg.sender, msg.system)
            return
        if msg.child_name in self._failed:
            self.context.system.event_stream.publish(
                DeadLetter(msg.message, msg.sender, self.self_ref))
            return
        # creation may still be in flight (unordered transport); buffer with
        # a deadline after which unclaimed messages become dead letters
        buf = self._pending.get(msg.child_name)
        if buf is None:
            buf = self._pending[msg.child_name] = []
            me, name = self.self_ref, msg.child_name
            self.context.system.scheduler.schedule_once(
                5.0, lambda: me.tell(("drop-pending", name)))
        if len(buf) >= self.MAX_BUFFERED_PER_CHILD:
            self.context.system.event_stream.publish(
                DeadLetter(msg.message, msg.sender, self.self_ref))
        else:
            buf.append((msg.message, msg.sender, msg.system))

    def _create(self, msg: DaemonMsgCreate) -> None:
        allow_import = self.provider.serialization.allow_pickle
        try:
            cls = _resolve_deployable(msg.class_key, allow_import)
            props = Props.create(cls, *msg.args, **dict(msg.kwargs))
            if msg.dispatcher:
                props = props.with_dispatcher(msg.dispatcher)
            if msg.mailbox:
                props = props.with_mailbox(msg.mailbox)
            existing = self.context.child(msg.child_name)
            if existing is not None:
                return  # idempotent re-delivery
            child = self.context.actor_of(props, msg.child_name)
            self.context.watch(child)
            # tie the child's life to its origin-side parent: when the parent
            # (or its whole node) dies, stop the orphans (the reference keeps
            # parent supervision over the wire; we collapse it to deathwatch).
            # One watch per distinct parent — cell.watch would overwrite a
            # per-child watchWith message for an already-watched ref.
            origin_parent = msg.origin_path.rsplit("/", 1)[0]
            kids = self._parent_children.get(origin_parent)
            if kids is None:
                kids = self._parent_children[origin_parent] = set()
                parent_ref = self.provider.resolve_actor_ref(origin_parent)
                if parent_ref is not self.provider.dead_letters:
                    self.context.watch(
                        parent_ref,
                        message=("origin-parent-died", origin_parent))
            kids.add(msg.child_name)
            for m, snd, sys_ in self._pending.pop(msg.child_name, ()):
                self._send_to(child, m, snd, sys_)
            fr = getattr(self.context.system, "flight_recorder", None)
            if fr is not None:
                fr.event("remote_deploy", child=str(child.path),
                         origin=msg.origin_path)
        except Exception as e:  # noqa: BLE001 — report, don't kill the daemon
            self._failed[msg.child_name] = repr(e)
            me, name = self.self_ref, msg.child_name
            self.context.system.scheduler.schedule_once(
                5.0, lambda: me.tell(("drop-failed", name)))
            for m, snd, _sys in self._pending.pop(msg.child_name, ()):
                self.context.system.event_stream.publish(
                    DeadLetter(m, snd, self.self_ref))
            self.context.system.event_stream.publish(DeadLetter(
                DaemonMsgCreateFailed(msg.child_name, repr(e)),
                None, self.self_ref))
            if self.sender is not None:
                self.sender.tell(DaemonMsgCreateFailed(msg.child_name, repr(e)),
                                 self.self_ref)


def remote_deploy(provider, props: Props, path, deploy: Deploy):
    """Origin-side half: ship the recipe, return the remote ref immediately
    (the reference's actorOf does the same — the RemoteActorRef exists before
    the remote child does; early tells buffer in transit)."""
    if props.router_config is not None:
        raise ValueError(
            "deploying a router remotely is not supported; deploy routees "
            "remotely instead (cluster/routing.py ClusterRouterPool)")
    if not props.has_recipe:
        raise ValueError(
            "remote deployment needs Props.create(cls, *args) — a factory "
            "closure cannot travel to another node")
    addr = Address.parse(deploy.scope.address)
    origin = path.with_address(provider.local_address).to_serialization_format()
    msg = DaemonMsgCreate(
        class_key=_class_key(props.cls), args=props.args, kwargs=props.kwargs,
        child_name=mangle(origin), origin_path=origin,
        dispatcher=props.dispatcher,
        mailbox=props.mailbox if isinstance(props.mailbox, str) else None)
    daemon = provider.resolve_actor_ref(f"akka://{addr.system}@{addr.host}:"
                                        f"{addr.port}/remote")
    daemon.tell(msg)
    target_path = deployed_path_for(addr, origin)
    from .provider import RemoteActorRef
    return RemoteActorRef(target_path, provider)
