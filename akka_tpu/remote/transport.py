"""Transports: the host control-plane wire (TCP + in-process).

Reference parity: akka-remote Artery transports — TCP framing
(remote/artery/tcp/ArteryTcpTransport.scala, TcpFraming.scala) and the
scriptable TestTransport (remote/transport/TestTransport.scala). The in-proc
transport doubles as the multi-node testkit's fault-injectable link
(ThrottlerTransportAdapter.scala:212 / FailureInjectorTransportAdapter.scala:65
semantics via FaultInjector).

On TPU pods the DATA plane is the sharded step's all_to_all over ICI
(akka_tpu/batched/sharded.py); these transports carry the control plane
(membership gossip, remote watch, system messages) the way Artery's control
lane does (ArteryTransport.scala:383-397).
"""

from __future__ import annotations


import queue
import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

from ..actor.path import Address

_LEN = struct.Struct(">I")


_ENV_HEAD = struct.Struct(">HBBiqqq")   # magic, version, flags, sid, uid, seq, ack
_ENV_MAGIC = 0xAF7A
# version 2 added the flag-bit2 reserved metadata section (RemoteInstrument
# header space); a v1 peer would misparse the count byte as a string length,
# so the layout change rides a version bump and v1 frames are still readable
_ENV_VERSION = 2
_LANES = ("ordinary", "control", "large")


@dataclass
class WireEnvelope:
    """What crosses the wire (reference: artery Codecs.scala EnvelopeBuffer
    layout — recipient, sender, serializer id, class manifest, payload; plus
    the system-message seq/ack channel of SystemMessageDelivery.scala).

    Fixed binary layout — NO pickle at the framing layer:
      >H magic  >B version  >B flags(bit0 is_system, bit2 metadata present,
      bits4-5 lane)  >i serializer_id  >q from_uid  >q seq(-1=None)
      >q ack(-1=None); when flag bit2: the RESERVED METADATA SECTION —
      >B entry count, then per entry >B key >I length + bytes (the
      RemoteInstrument header space, artery Codecs/EnvelopeBuffer metadata
      block; keys 1..31 belong to instruments); then length-prefixed
      UTF-8: recipient, sender(flag bit1 = present), manifest,
      from_address; length-prefixed payload bytes."""

    recipient: str                 # serialization-format path
    sender: Optional[str]
    serializer_id: int
    manifest: str
    payload: bytes
    is_system: bool = False
    seq: Optional[int] = None      # system-message sequence number
    ack: Optional[int] = None      # cumulative ack
    from_address: str = ""
    from_uid: int = 0
    lane: str = "ordinary"         # control | ordinary | large
    metadata: Optional[Dict[int, bytes]] = None  # instrument key -> bytes

    def to_bytes(self) -> bytes:
        flags = (1 if self.is_system else 0) | \
                (2 if self.sender is not None else 0) | \
                (4 if self.metadata else 0) | \
                (_LANES.index(self.lane) << 4)
        # the v1 and v2 layouts are identical when flag bit2 is clear, so
        # metadata-free frames are stamped v1 — a rolling upgrade keeps
        # working in BOTH directions until an instrument actually writes
        # metadata (the v2 stamp is reserved for frames that carry it)
        version = _ENV_VERSION if self.metadata else 1
        parts = [_ENV_HEAD.pack(
            _ENV_MAGIC, version, flags, self.serializer_id,
            self.from_uid, -1 if self.seq is None else self.seq,
            -1 if self.ack is None else self.ack)]
        if self.metadata:
            parts.append(struct.pack(">B", len(self.metadata)))
            for key, blob in sorted(self.metadata.items()):
                parts.append(struct.pack(">B", key))
                parts.append(_LEN.pack(len(blob)))
                parts.append(blob)
        for s in (self.recipient, self.sender or "", self.manifest,
                  self.from_address):
            b = s.encode("utf-8")
            parts.append(_LEN.pack(len(b)))
            parts.append(b)
        parts.append(_LEN.pack(len(self.payload)))
        parts.append(self.payload)
        return b"".join(parts)

    @staticmethod
    def from_bytes(data: bytes) -> "WireEnvelope":
        magic, version, flags, sid, uid, seq, ack = _ENV_HEAD.unpack_from(data, 0)
        if magic != _ENV_MAGIC:
            raise ValueError(f"bad envelope magic 0x{magic:04x}")
        if not 1 <= version <= _ENV_VERSION:
            raise ValueError(f"unsupported envelope version {version}")
        off = _ENV_HEAD.size
        metadata = None
        if version >= 2 and flags & 4:
            (count,) = struct.unpack_from(">B", data, off)
            off += 1
            metadata = {}
            for _ in range(count):
                (key,) = struct.unpack_from(">B", data, off)
                off += 1
                (n,) = _LEN.unpack_from(data, off)
                off += 4
                metadata[key] = data[off:off + n]
                off += n
        strings = []
        for _ in range(4):
            (n,) = _LEN.unpack_from(data, off)
            off += 4
            strings.append(data[off:off + n].decode("utf-8"))
            off += n
        (n,) = _LEN.unpack_from(data, off)
        off += 4
        payload = data[off:off + n]
        if len(payload) != n:
            raise ValueError("truncated envelope payload")
        recipient, sender_s, manifest, from_address = strings
        return WireEnvelope(
            recipient=recipient,
            sender=sender_s if flags & 2 else None,
            serializer_id=sid, manifest=manifest, payload=payload,
            is_system=bool(flags & 1),
            seq=None if seq < 0 else seq,
            ack=None if ack < 0 else ack,
            from_address=from_address, from_uid=uid,
            lane=_LANES[(flags >> 4) & 3],
            metadata=metadata)


InboundHandler = Callable[[WireEnvelope], None]


class Transport:
    scheme = "akka"

    def listen(self, host: str, port: int, handler: InboundHandler) -> Tuple[str, int]:
        raise NotImplementedError

    def send(self, host: str, port: int, envelope: WireEnvelope) -> bool:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class FaultInjector:
    """Per-link fault injection (reference: TestConductor throttle/blackhole,
    remote/testconductor/Conductor.scala:128,148)."""

    def __init__(self):
        self._modes: Dict[Tuple[str, str], Any] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(a: str, b: str) -> Tuple[str, str]:
        return (a, b)

    def blackhole(self, from_addr: str, to_addr: str) -> None:
        with self._lock:
            self._modes[(from_addr, to_addr)] = "blackhole"

    def throttle(self, from_addr: str, to_addr: str, rate_msgs_per_sec: float) -> None:
        with self._lock:
            self._modes[(from_addr, to_addr)] = ("throttle", rate_msgs_per_sec, [0.0])

    def pass_through(self, from_addr: str, to_addr: str) -> None:
        with self._lock:
            self._modes.pop((from_addr, to_addr), None)

    def reset(self) -> None:
        with self._lock:
            self._modes.clear()

    def allow(self, from_addr: str, to_addr: str) -> bool:
        """False -> drop; may sleep for throttling."""
        with self._lock:
            mode = self._modes.get((from_addr, to_addr))
        if mode is None:
            return True
        if mode == "blackhole":
            return False
        if isinstance(mode, tuple) and mode[0] == "throttle":
            _, rate, last = mode
            now = time.monotonic()
            min_gap = 1.0 / max(rate, 1e-9)
            if now - last[0] < min_gap:
                time.sleep(min_gap - (now - last[0]))
            last[0] = time.monotonic()
            return True
        return True


class InProcTransport(Transport):
    """Process-local 'network': multi-node tests run N systems in one process
    with real serialization + fault injection, no sockets."""

    _registry: Dict[Tuple[str, int], InboundHandler] = {}
    _reg_lock = threading.Lock()
    _port_counter = [20000]
    fault_injector = FaultInjector()

    _registry_queues: Dict[Tuple[str, int], "queue.Queue[Optional[WireEnvelope]]"] = {}

    def __init__(self, local_address: str = ""):
        self.local_address = local_address
        self._bound: Optional[Tuple[str, int]] = None
        self._down = False

    def listen(self, host: str, port: int, handler: InboundHandler) -> Tuple[str, int]:
        with self._reg_lock:
            if port == 0:
                self._port_counter[0] += 1
                port = self._port_counter[0]
            if (host, port) in self._registry:
                raise OSError(f"inproc address {host}:{port} already bound")
            self._registry[(host, port)] = handler
            self._bound = (host, port)
            # one delivery queue + worker per listener: FIFO per link, async
            # w.r.t. the sender (like a real socket's receive path)
            q: "queue.Queue[Optional[WireEnvelope]]" = queue.Queue()
            self._registry_queues[(host, port)] = q

            def _drain():
                while True:
                    env = q.get()
                    if env is None:
                        return
                    try:
                        handler(env)
                    except Exception:  # noqa: BLE001 — bad frame must not kill the loop
                        pass

            threading.Thread(target=_drain, daemon=True,
                             name=f"akka-tpu-inproc-{host}:{port}").start()
        return host, port

    def send(self, host: str, port: int, envelope: WireEnvelope) -> bool:
        if self._down:  # a dead process sends nothing
            return False
        q = self._registry_queues.get((host, port))
        if q is None:
            return False
        to_addr = f"{host}:{port}"
        if not self.fault_injector.allow(self.local_address, to_addr):
            return False
        q.put(envelope)
        return True

    def shutdown(self) -> None:
        self._down = True
        with self._reg_lock:
            if self._bound is not None:
                self._registry.pop(self._bound, None)
                q = self._registry_queues.pop(self._bound, None)
                if q is not None:
                    q.put(None)


class TcpTransport(Transport):
    """Framed TCP: 4-byte big-endian length + binary WireEnvelope. One
    outbound connection per (peer, LANE), kept open — the control /
    ordinary / large lanes each get their own socket so a multi-megabyte
    payload in flight on the large lane cannot head-of-line-block
    heartbeats or ordinary tells (ArteryTransport.scala:383-428 lane
    partitioning; ordering is per-lane, as in Artery)."""

    def __init__(self, local_address: str = ""):
        self.local_address = local_address
        self._server_sock: Optional[socket.socket] = None
        self._conns: Dict[Tuple[str, int, str], socket.socket] = {}
        self._peer_locks: Dict[Tuple[str, int, str], threading.Lock] = {}
        self._conn_lock = threading.Lock()
        self._stop = threading.Event()
        self.fault_injector = FaultInjector()

    # TLS seam (SSLEngineProvider.scala:66 createServerSSLEngine /
    # createClientSSLEngine): the plain transport returns sockets as-is
    def _wrap_server(self, conn: socket.socket) -> socket.socket:
        return conn

    def _connect(self, host: str, port: int) -> socket.socket:
        return socket.create_connection((host, port), timeout=5.0)

    def listen(self, host: str, port: int, handler: InboundHandler) -> Tuple[str, int]:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(128)
        self._server_sock = srv
        bound_host, bound_port = srv.getsockname()

        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return

                def start(conn=conn):
                    try:
                        wrapped = self._wrap_server(conn)
                    except Exception:  # noqa: BLE001 — bad/unauthenticated peer
                        try:
                            conn.close()
                        except OSError:
                            pass
                        return
                    self._read_loop(wrapped, handler)
                threading.Thread(target=start, daemon=True).start()

        threading.Thread(target=accept_loop, daemon=True,
                         name=f"akka-tpu-tcp-accept-{bound_port}").start()
        return bound_host, bound_port

    def _read_loop(self, conn: socket.socket, handler: InboundHandler) -> None:
        try:
            buf = b""
            while not self._stop.is_set():
                while len(buf) < 4:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                (length,) = _LEN.unpack(buf[:4])
                while len(buf) < 4 + length:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                frame, buf = buf[4: 4 + length], buf[4 + length:]
                try:
                    handler(WireEnvelope.from_bytes(frame))
                except Exception:  # noqa: BLE001 — bad frame must not kill the loop
                    pass
        finally:
            conn.close()

    def _peer_lock(self, key: Tuple[str, int, str]) -> threading.Lock:
        # per-(peer, lane) lock so a slow/blocked transfer on one lane
        # doesn't stall sends (e.g. failure-detector heartbeats) on others
        with self._conn_lock:
            lock = self._peer_locks.get(key)
            if lock is None:
                lock = self._peer_locks[key] = threading.Lock()
            return lock

    def send(self, host: str, port: int, envelope: WireEnvelope) -> bool:
        if not self.fault_injector.allow(self.local_address, f"{host}:{port}"):
            return False
        data = envelope.to_bytes()
        frame = _LEN.pack(len(data)) + data
        key = (host, port, envelope.lane)
        with self._peer_lock(key):
            sock = self._conns.get(key)
            if sock is None:
                try:
                    sock = self._connect(host, port)
                except OSError:
                    return False
                with self._conn_lock:
                    self._conns[key] = sock
            try:
                sock.sendall(frame)
                return True
            except OSError:
                with self._conn_lock:
                    self._conns.pop(key, None)
                try:
                    sock.close()
                except OSError:
                    pass
                return False

    def shutdown(self) -> None:
        self._stop.set()
        if self._server_sock is not None:
            try:
                self._server_sock.close()
            except OSError:
                pass
        with self._conn_lock:
            for s in self._conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()


@dataclass(frozen=True)
class TlsSettings:
    """PEM-based TLS configuration (reference: artery's
    remote/artery/tcp/ssl/ConfigSSLEngineProvider — key-store/trust-store
    paths + mutual-auth flags; here PEM files via akka_tpu.pki instead of
    JKS, which is the idiomatic non-JVM form)."""

    cert_file: str
    key_file: str
    ca_file: str
    require_mutual_auth: bool = True

    @staticmethod
    def from_config(cfg) -> "TlsSettings":
        return TlsSettings(
            cert_file=cfg.get_string("akka.remote.tls.cert-file", ""),
            key_file=cfg.get_string("akka.remote.tls.key-file", ""),
            ca_file=cfg.get_string("akka.remote.tls.ca-file", ""),
            require_mutual_auth=cfg.get_bool(
                "akka.remote.tls.require-mutual-auth", True))


class TlsTcpTransport(TcpTransport):
    """TLS on the wire (reference: remote/artery/tcp/ArteryTcpTransport with
    SSLEngineProvider.scala:66 server/client engines): same framing as
    TcpTransport, sockets wrapped in SSLContext with CA-pinned verification
    and optional mutual auth (client certs REQUIRED by default — a peer
    without a CA-signed cert is rejected during the handshake).

    Certificates/keys are PEM (validated up-front via akka_tpu.pki so
    misconfiguration fails at system start with a clear error, not at the
    first connection)."""

    def __init__(self, settings: TlsSettings, local_address: str = ""):
        super().__init__(local_address)
        import ssl

        from ..pki import load_certificates, load_private_key

        # fail fast on malformed PEM (PEMDecoder semantics)
        load_certificates(settings.cert_file)
        load_private_key(settings.key_file)
        load_certificates(settings.ca_file)
        self.settings = settings

        srv = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        srv.load_cert_chain(settings.cert_file, settings.key_file)
        srv.load_verify_locations(settings.ca_file)
        srv.verify_mode = (ssl.CERT_REQUIRED if settings.require_mutual_auth
                           else ssl.CERT_NONE)
        self._server_ctx = srv

        cli = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        cli.load_cert_chain(settings.cert_file, settings.key_file)
        cli.load_verify_locations(settings.ca_file)
        # peers are addressed by host:port, not DNS names; trust is the CA
        # pin + (mutual) client certs, as in artery's ConfigSSLEngineProvider
        cli.check_hostname = False
        cli.verify_mode = ssl.CERT_REQUIRED
        self._client_ctx = cli

    def _wrap_server(self, conn: socket.socket) -> socket.socket:
        return self._server_ctx.wrap_socket(conn, server_side=True)

    def _connect(self, host: str, port: int) -> socket.socket:
        raw = socket.create_connection((host, port), timeout=5.0)
        try:
            return self._client_ctx.wrap_socket(raw)
        except Exception:
            try:
                raw.close()
            except OSError:
                pass
            raise OSError("TLS handshake failed")
