"""RemoteActorRefProvider: location transparency across systems.

Reference parity: akka-remote/src/main/scala/akka/remote/
RemoteActorRefProvider.scala (:152 wraps LocalActorRefProvider; RemoteActorRef
tell -> remote.send :651,732), ArteryTransport association model
(artery/Association.scala: per-peer state, quarantine :290-314), system-message
reliability (artery/SystemMessageDelivery.scala: seq + cumulative ack +
resend), RemoteWatcher (remote/RemoteWatcher.scala:34-88: heartbeats +
phi-accrual -> AddressTerminated).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from ..actor.actor import Actor
from ..actor.messages import DeadLetter, Terminated
from ..actor.path import ActorPath, Address, new_uid, parse_actor_path
from ..actor.props import Props
from ..actor.provider import LocalActorRefProvider
from ..actor.ref import ActorRef, InternalActorRef
from ..dispatch import sysmsg
from ..serialization.serialization import Serialization, transport_information
from .failure_detector import FailureDetectorRegistry, PhiAccrualFailureDetector
from .transport import InProcTransport, TcpTransport, Transport, WireEnvelope


@dataclass(frozen=True)
class AddressTerminated:
    """Published on the event stream when a remote address is deemed down."""
    address: Address


@dataclass(frozen=True)
class QuarantinedEvent:
    address: Address
    uid: int


class RemoteActorRef(InternalActorRef):
    """(reference: RemoteActorRefProvider.scala:651-760)"""

    def __init__(self, path: ActorPath, provider: "RemoteActorRefProvider"):
        self.path = path
        self.provider = provider
        self._system = provider.system

    @property
    def is_local(self) -> bool:
        return False

    def tell(self, message: Any, sender: Optional[ActorRef] = None) -> None:
        self.provider.remote_send(self, message, sender, is_system=False)

    def send_system_message(self, message: sysmsg.SystemMessage) -> None:
        if isinstance(message, sysmsg.Watch):
            # node-level: heartbeat the address (RemoteWatcher.scala:34-88);
            # actor-level: forward Watch so the watchee's cell registers the
            # remote watcher and emits DeathWatchNotification on normal stop
            self.provider.remote_watcher_watch(message.watchee, message.watcher)
            self.provider.remote_send(self, message, None, is_system=True)
        elif isinstance(message, sysmsg.Unwatch):
            self.provider.remote_watcher_unwatch(message.watchee, message.watcher)
            self.provider.remote_send(self, message, None, is_system=True)
        elif isinstance(message, sysmsg.Terminate):
            # remote stop: deliver PoisonPill-ish via system channel
            self.provider.remote_send(self, _RemoteTerminate(), None, is_system=True)
        else:
            self.provider.remote_send(self, message, None, is_system=True)

    def stop(self) -> None:
        self.send_system_message(sysmsg.Terminate())


@dataclass(frozen=True)
class _RemoteTerminate:
    pass


@dataclass(frozen=True)
class _Heartbeat:
    from_address: str


@dataclass(frozen=True)
class _HeartbeatRsp:
    from_address: str


class Association:
    """Per-peer state: uid, quarantine, system-message resend buffer
    (reference: artery/Association.scala + SystemMessageDelivery.scala)."""

    def __init__(self, peer: Tuple[str, int]):
        self.peer = peer
        self.peer_uid: Optional[int] = None
        self.quarantined_uids: set[int] = set()
        self.seq = itertools.count(1)
        self.pending_acks: Dict[int, WireEnvelope] = {}   # seq -> envelope
        self.last_delivered_seq = 0                        # inbound dedup
        self.lock = threading.Lock()

    def quarantine(self, uid: int) -> None:
        with self.lock:
            self.quarantined_uids.add(uid)

    def is_quarantined(self, uid: int) -> bool:
        return uid in self.quarantined_uids


class RemoteWatcher(Actor):
    """Cross-node DeathWatch: heartbeats per watched address + phi accrual
    (reference: remote/RemoteWatcher.scala:34-88)."""

    def __init__(self, provider: "RemoteActorRefProvider",
                 heartbeat_interval: float, fd_factory):
        super().__init__()
        self.provider = provider
        self.heartbeat_interval = heartbeat_interval
        self.fd = FailureDetectorRegistry(fd_factory)
        # watchee remote ref -> set of local watcher refs
        self.watching: Dict[ActorRef, set] = {}
        self._tick_task = None

    def pre_start(self) -> None:
        self._tick_task = self.context.system.scheduler.schedule_tell_with_fixed_delay(
            self.heartbeat_interval, self.heartbeat_interval,
            self.self_ref, "tick", self.self_ref)

    def post_stop(self) -> None:
        if self._tick_task is not None:
            self._tick_task.cancel()

    def _addresses(self):
        return {str(w.path.address) for w in self.watching}

    def receive(self, message: Any):
        if message == "tick":
            for addr_s in self._addresses():
                addr = Address.parse(addr_s)
                self.provider.send_control(addr, _Heartbeat(str(self.provider.local_address)))
                if self.fd.is_monitoring(addr_s) and not self.fd.is_available(addr_s):
                    self._address_terminated(addr)
        elif isinstance(message, _HeartbeatRsp):
            self.fd.heartbeat(message.from_address)
        elif isinstance(message, tuple) and message and message[0] == "watch":
            _, watchee, watcher = message
            self.watching.setdefault(watchee, set()).add(watcher)
        elif isinstance(message, tuple) and message and message[0] == "unwatch":
            _, watchee, watcher = message
            watchers = self.watching.get(watchee)
            if watchers is not None:
                watchers.discard(watcher)
                if not watchers:
                    self.watching.pop(watchee, None)
        else:
            return NotImplemented
        return None

    def _address_terminated(self, address: Address) -> None:
        self.context.system.event_stream.publish(AddressTerminated(address))
        addr_s = str(address)
        for watchee, watchers in list(self.watching.items()):
            if str(watchee.path.address) == addr_s:
                for watcher in watchers:
                    if isinstance(watcher, InternalActorRef):
                        watcher.send_system_message(sysmsg.DeathWatchNotification(
                            watchee, existence_confirmed=False, address_terminated=True))
                self.watching.pop(watchee, None)
        self.fd.remove(addr_s)


class RemoteActorRefProvider(LocalActorRefProvider):
    def __init__(self, system_name: str, settings, event_stream):
        super().__init__(system_name, settings, event_stream)
        self.uid = new_uid() + int(time.time() * 1000) % (1 << 20)
        self.transport: Optional[Transport] = None
        self.local_address: Optional[Address] = None
        # pickle on the wire is opt-in only (JavaSerializer-off parity;
        # default = fixed-schema codecs, serialization/codec.py)
        self.serialization = Serialization(
            allow_pickle=settings.config.get_bool(
                "akka.remote.allow-pickle", False))
        self._associations: Dict[Tuple[str, int], Association] = {}
        self._assoc_lock = threading.Lock()
        self._remote_watcher = None
        self._resend_task = None
        # per-message wire instrumentation (RemoteInstrument.scala:32):
        # config entries "module:Class" plus programmatic
        # provider.remote_instruments.add(...)
        from .instrument import RemoteInstruments
        self.remote_instruments = RemoteInstruments.from_config(
            settings.config.get_list("akka.remote.instruments", []))

    # -- bootstrap -----------------------------------------------------------
    def init(self, system) -> None:
        super().init(system)

    def post_init(self, system) -> None:
        cfg = self.settings.config
        host = cfg.get_string("akka.remote.canonical.hostname", "127.0.0.1")
        port = cfg.get_int("akka.remote.canonical.port", 0)
        self.large_message_threshold = cfg.get_int(
            "akka.remote.large-message-threshold", 32 * 1024)
        kind = cfg.get_string("akka.remote.transport", "tcp")
        if kind == "inproc":
            self.transport = InProcTransport()
        elif kind == "tls-tcp":
            # TLS on the wire (SSLEngineProvider.scala:66 seam): PEM paths
            # from akka.remote.tls.*, mutual auth on by default
            from .transport import TlsSettings, TlsTcpTransport
            self.transport = TlsTcpTransport(TlsSettings.from_config(cfg))
        else:
            self.transport = TcpTransport()
        bound_host, bound_port = self.transport.listen(host, port, self._inbound)
        self.local_address = Address("akka", self.system_name, bound_host, bound_port)
        self.transport.local_address = f"{bound_host}:{bound_port}"
        self._flight = getattr(system, "flight_recorder", None)
        if self._flight is not None:
            self._flight.transport_started(str(self.local_address))
        # rebase the guardian hierarchy's notion of our address for remote paths
        self.root_path = ActorPath(self.local_address)
        fd_cfg = cfg.get_config("akka.remote.watch-failure-detector")
        self._remote_watcher = system.system_actor_of(
            Props.create(
                RemoteWatcher, self,
                fd_cfg.get_duration("heartbeat-interval", "1s"),
                lambda: PhiAccrualFailureDetector(
                    threshold=fd_cfg.get_float("threshold", 10.0),
                    max_sample_size=fd_cfg.get_int("max-sample-size", 200),
                    min_std_deviation=fd_cfg.get_duration("min-std-deviation", "100ms"),
                    acceptable_heartbeat_pause=fd_cfg.get_duration(
                        "acceptable-heartbeat-pause", "10s"),
                    first_heartbeat_estimate=fd_cfg.get_duration(
                        "expected-first-heartbeat-estimate", "1s"))),
            "remote-watcher")
        resend_interval = cfg.get_duration("akka.remote.system-message-resend-interval", "1s")
        self._resend_task = system.scheduler.schedule_with_fixed_delay(
            resend_interval, resend_interval, self._resend_pending)
        # /remote daemon: instantiates DaemonMsgCreate recipes from peers
        # (reference: RemoteSystemDaemon under the root guardian)
        from .deploy import RemoteSystemDaemon
        self.remote_daemon = self.root_guardian.cell.actor_of(
            Props.create(RemoteSystemDaemon, self).with_dispatcher(
                system.dispatchers.INTERNAL_DISPATCHER_ID),
            "remote")
        system.register_on_termination(self.shutdown_transport)

    def shutdown_transport(self) -> None:
        if self._resend_task is not None:
            self._resend_task.cancel()
        if self.transport is not None:
            self.transport.shutdown()

    # -- address helpers -----------------------------------------------------
    @property
    def default_address(self) -> Address:
        return self.local_address or self.root_path.address

    def _association(self, addr: Address) -> Association:
        key = (addr.host, addr.port)
        with self._assoc_lock:
            a = self._associations.get(key)
            if a is None:
                a = Association(key)
                self._associations[key] = a
                fr = getattr(self, "_flight", None)
                if fr is not None:
                    fr.association_opened(f"{addr.host}:{addr.port}")
            return a

    def quarantine(self, address: Address, uid: int) -> None:
        """(reference: Association quarantine :290-314)"""
        self._association(address).quarantine(uid)
        self.event_stream.publish(QuarantinedEvent(address, uid))
        fr = getattr(self, "_flight", None)
        if fr is not None:
            fr.association_quarantined(str(address), f"uid={uid}")

    # -- outbound ------------------------------------------------------------
    def remote_send(self, ref: RemoteActorRef, message: Any,
                    sender: Optional[ActorRef], is_system: bool) -> None:
        addr = ref.path.address
        assoc = self._association(addr)
        if assoc.peer_uid is not None and assoc.is_quarantined(assoc.peer_uid):
            self.dead_letters.tell(DeadLetter(message, sender, ref), sender)
            return
        with transport_information(self):
            sid, manifest, payload = self.serialization.serialize(message)
        sender_path = None
        if sender is not None:
            sp = sender.path
            if sp.address.has_local_scope and self.local_address is not None:
                sp = sp.with_address(self.local_address)
            sender_path = sp.to_serialization_format()
        # lane selection (ArteryTransport.scala:383-428): system messages
        # ride the control lane; oversized payloads ride a DEDICATED large
        # lane (own connection) so one big transfer cannot head-of-line
        # block ordinary traffic. Artery picks by destination config; a
        # size threshold is the natural form when payloads are on hand.
        # Like Artery, ordering holds WITHIN a lane, not across lanes.
        if is_system:
            lane = "control"
        elif len(payload) >= self.large_message_threshold:
            lane = "large"
        else:
            lane = "ordinary"
        env = WireEnvelope(
            recipient=ref.path.to_serialization_format(),
            sender=sender_path,
            serializer_id=sid, manifest=manifest, payload=payload,
            is_system=is_system,
            from_address=str(self.local_address), from_uid=self.uid,
            lane=lane)
        if self.remote_instruments:
            # serialize-time hook: instruments stamp the reserved header
            # space (RemoteInstrument.remoteWriteMetadata)
            env.metadata = self.remote_instruments.write_metadata(
                ref, message, sender)
        if is_system:
            with assoc.lock:
                env.seq = next(assoc.seq)
                assoc.pending_acks[env.seq] = env
        ok = self.transport.send(addr.host, addr.port, env)
        if ok and self.remote_instruments:
            self.remote_instruments.message_sent(
                ref, message, sender, len(env.payload or b""))
        fr = getattr(self, "_flight", None)
        if fr is not None:
            if ok:
                fr.remote_message_sent(f"{addr.host}:{addr.port}",
                                       len(env.payload or b""))
            else:
                fr.event("remote_send_failed",
                         peer=f"{addr.host}:{addr.port}")
        if not ok and not is_system:
            self.dead_letters.tell(DeadLetter(message, sender, ref), sender)

    def send_control(self, addr: Address, message: Any) -> None:
        with transport_information(self):
            sid, manifest, payload = self.serialization.serialize(message)
        env = WireEnvelope(
            recipient=f"{addr}/system/remote-watcher",
            sender=None, serializer_id=sid, manifest=manifest, payload=payload,
            from_address=str(self.local_address), from_uid=self.uid, lane="control")
        self.transport.send(addr.host, addr.port, env)

    def _resend_pending(self) -> None:
        with self._assoc_lock:
            assocs = list(self._associations.items())
        for (host, port), assoc in assocs:
            with assoc.lock:
                pending = list(assoc.pending_acks.values())
            for env in pending:
                self.transport.send(host, port, env)

    # -- inbound -------------------------------------------------------------
    def _inbound(self, env: WireEnvelope) -> None:
        try:
            fr = getattr(self, "_flight", None)
            if fr is not None:
                fr.remote_message_received(env.from_address or "?",
                                           len(env.payload or b""))
            self._handle_inbound(env)
        except Exception as e:  # noqa: BLE001 — transport thread must survive
            self.event_stream.publish(DeadLetter(f"inbound error: {e!r}", None, None))

    def _handle_inbound(self, env: WireEnvelope) -> None:
        from_addr = Address.parse(env.from_address) if env.from_address else None
        ack_after_delivery = None
        if from_addr is not None:
            assoc = self._association(from_addr)
            if assoc.is_quarantined(env.from_uid):
                return
            if assoc.peer_uid is None:
                assoc.peer_uid = env.from_uid
            elif assoc.peer_uid != env.from_uid:
                # restarted incarnation: quarantine the old uid (reference:
                # quarantine of stale UIDs, artery/Handshake + InboundQuarantineCheck)
                assoc.quarantine(assoc.peer_uid)
                assoc.peer_uid = env.from_uid
                assoc.last_delivered_seq = 0
            if env.is_system and env.seq is not None:
                with assoc.lock:
                    if env.seq <= assoc.last_delivered_seq:
                        self._send_ack(from_addr, assoc)
                        return  # duplicate
                # ack only AFTER successful deserialize+delivery, so a failed
                # delivery is resent rather than silently acked away
                ack_after_delivery = (from_addr, assoc, env.seq)
            if env.ack is not None:
                with assoc.lock:
                    for s in [s for s in assoc.pending_acks if s <= env.ack]:
                        assoc.pending_acks.pop(s, None)
                if env.serializer_id == -1:
                    return  # pure ack

        with transport_information(self):
            message = self.serialization.deserialize(env.serializer_id, env.manifest,
                                                     env.payload)
        # control-plane messages
        if isinstance(message, _Heartbeat):
            addr = Address.parse(message.from_address)
            self.send_control(addr, _HeartbeatRsp(str(self.local_address)))
            return
        if isinstance(message, _HeartbeatRsp):
            if self._remote_watcher is not None:
                self._remote_watcher.tell(message)
            return

        recipient = self.resolve_actor_ref(env.recipient)
        sender = (self.resolve_actor_ref(env.sender) if env.sender
                  else self.dead_letters)
        if self.remote_instruments:
            # deliver-time hook: same-identifier instruments read back the
            # metadata stamped on the sending side
            self.remote_instruments.read_metadata(
                recipient, message, sender, env.metadata)
            self.remote_instruments.message_received(
                recipient, message, sender, len(env.payload or b""))
        if recipient is self.dead_letters:
            # a message (user OR system: Watch must not be lost either) that
            # raced a remote deployment: hand it to the daemon, which buffers
            # until DaemonMsgCreate lands (remote/deploy.py)
            try:
                elements = list(parse_actor_path(env.recipient).elements)
            except ValueError:
                elements = []
            if len(elements) == 2 and elements[0] == "remote":
                from .deploy import _DeliverToChild
                self.remote_daemon.tell(
                    _DeliverToChild(elements[1], message, sender,
                                    system=env.is_system))
                if ack_after_delivery is not None:
                    addr, assoc, seq = ack_after_delivery
                    with assoc.lock:
                        assoc.last_delivered_seq = max(assoc.last_delivered_seq, seq)
                    self._send_ack(addr, assoc)
                return
        if isinstance(message, _RemoteTerminate):
            if isinstance(recipient, InternalActorRef):
                recipient.stop()
        elif env.is_system and isinstance(message, sysmsg.SystemMessage):
            if isinstance(recipient, InternalActorRef):
                recipient.send_system_message(message)
        else:
            recipient.tell(message, sender)
        if ack_after_delivery is not None:
            addr, assoc, seq = ack_after_delivery
            with assoc.lock:
                assoc.last_delivered_seq = max(assoc.last_delivered_seq, seq)
            self._send_ack(addr, assoc)

    def _send_ack(self, addr: Address, assoc: Association) -> None:
        env = WireEnvelope(recipient="", sender=None, serializer_id=-1,
                           manifest="", payload=b"", is_system=False,
                           ack=assoc.last_delivered_seq,
                           from_address=str(self.local_address), from_uid=self.uid,
                           lane="control")
        self.transport.send(addr.host, addr.port, env)

    # -- remote deployment (reference: RemoteActorRefProvider.actorOf :152
    # — a RemoteScope deploy creates the actor on the remote node) -----------
    def actor_of(self, system, props: Props, supervisor: InternalActorRef,
                 path: ActorPath) -> InternalActorRef:
        from ..actor.deploy import RemoteScope
        eff_props, deploy = self.effective_props(props, path)
        scope = getattr(deploy, "scope", None)
        if (isinstance(scope, RemoteScope) and self.local_address is not None
                and Address.parse(scope.address) != self.local_address):
            from .deploy import remote_deploy
            return remote_deploy(self, eff_props, path, deploy)
        return super().actor_of(system, eff_props, supervisor, path,
                                _resolved=True)

    # -- resolution ----------------------------------------------------------
    def resolve_actor_ref(self, path: Any) -> ActorRef:
        if isinstance(path, str):
            try:
                path = parse_actor_path(path)
            except ValueError:
                return self.dead_letters
        if self.local_address is not None and path.address == self.local_address:
            return self.resolve_local(path)
        if path.address == ActorPath(Address("akka", self.system_name)).address:
            return self.resolve_local(path)
        if path.address.has_global_scope:
            return RemoteActorRef(path, self)
        return self.dead_letters

    # -- remote deathwatch ----------------------------------------------------
    def remote_watcher_watch(self, watchee, watcher) -> None:
        if self._remote_watcher is not None:
            self._remote_watcher.tell(("watch", watchee, watcher))

    def remote_watcher_unwatch(self, watchee, watcher) -> None:
        if self._remote_watcher is not None:
            self._remote_watcher.tell(("unwatch", watchee, watcher))
