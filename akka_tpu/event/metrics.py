"""MetricsRegistry: the host half of the telemetry plane.

The reference ships metrics as a first-class layer next to the flight
recorder (cluster metrics + JFR emitters, SURVEY §2.10); our reproduction
had the event half (flight_recorder.py) and a pile of ad-hoc dicts
(`pipeline_stats`/`checkpoint_stats`/`sentinel_stats` on the bridge). This
module unifies them: counters, gauges, and log-bucket histograms with
nearest-rank percentile snapshots, plus ingestion of the device metric slab
(batched/metrics_slab.py) drained at the pump's busy→idle edge and the
checkpoint barrier.

Correlation contract: every sample is stamped with the device step counter
current at its last update (`set_step` / the `step` argument of
`ingest_device_slab`), so registry samples, flight-recorder events (which
carry step fields), and `trace_span` profiler brackets line up on ONE axis —
the recipe is in docs/OBSERVABILITY.md.

Sinks:
- `expose()` — Prometheus text exposition (device histograms carry
  power-of-two `le` buckets from metrics_slab.bucket_upper_bounds; host
  histograms carry `quantile` summary lines).
- an opt-in tiny HTTP endpoint (`serve_http`, behind
  `akka.metrics.http-port`; 0 = off, the default).
- a periodic JSONL emitter (`start_jsonl`) sharing the flight recorder's
  file conventions: makedirs, line-buffered append, `"event"`/`"ts"` keys.

Everything is thread-safe and noop-cheap: a registry that nobody feeds
holds a dict and does nothing.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# host-histogram bucketing mirrors the device slab's power-of-two rule
# (metrics_slab.bucket_of) but with enough range for microsecond latencies:
# bucket(v) = #{k : v >= 2^k}, v <= 0 -> 0
_HOST_BUCKETS = 64


def _host_bucket(v: float) -> int:
    if v < 1.0:
        return 0
    return min(int(v).bit_length(), _HOST_BUCKETS - 1)


class Counter:
    """Monotonic int64 counter."""

    __slots__ = ("name", "help", "_value", "step")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0
        self.step: Optional[int] = None

    def inc(self, n: int = 1, step: Optional[int] = None) -> None:
        self._value += int(n)
        if step is not None:
            self.step = int(step)

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "help", "_value", "step")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self.step: Optional[int] = None

    def set(self, v: float, step: Optional[int] = None) -> None:
        self._value = float(v)
        if step is not None:
            self.step = int(step)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Host-side log-bucket histogram (power-of-two buckets, like the
    device slab but 64 wide) with nearest-rank percentile snapshots.

    Percentile estimation returns the UPPER bound of the bucket holding
    the nearest-rank sample (rank = ceil(q*n), 1-based — the corrected
    rule, see pipeline_stats' pct fix), i.e. a conservative estimate that
    never under-reports; exact to within one power of two."""

    __slots__ = ("name", "help", "_buckets", "_count", "_sum", "step")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._buckets = np.zeros((_HOST_BUCKETS,), np.int64)
        self._count = 0
        self._sum = 0.0
        self.step: Optional[int] = None

    def observe(self, v: float, step: Optional[int] = None) -> None:
        self._buckets[_host_bucket(v)] += 1
        self._count += 1
        self._sum += float(v)
        if step is not None:
            self.step = int(step)

    def observe_many(self, vs, step: Optional[int] = None) -> None:
        """Vectorized observe for a whole wave of samples (the binary
        ingress path records per-window): one bincount instead of N
        scalar bucket updates. Bucket math matches _host_bucket exactly
        (bit_length of the integer part, clamped)."""
        arr = np.asarray(vs, np.float64).reshape(-1)
        if arr.size == 0:
            return
        idx = np.where(
            arr < 1.0, 0,
            np.minimum(
                np.frexp(np.maximum(arr, 1.0).astype(np.int64)
                         .astype(np.float64))[1],
                _HOST_BUCKETS - 1))
        self._buckets += np.bincount(idx.astype(np.int64),
                                     minlength=_HOST_BUCKETS)
        self._count += int(arr.size)
        self._sum += float(arr.sum())
        if step is not None:
            self.step = int(step)

    def percentile(self, q: float) -> float:
        if self._count == 0:
            return 0.0
        rank = max(1, math.ceil(q * self._count))  # 1-based nearest rank
        cum = np.cumsum(self._buckets)
        b = int(np.searchsorted(cum, rank))
        return float((1 << b) - 1) if b > 0 else 0.0

    def snapshot(self) -> Dict[str, Any]:
        return {"count": self._count, "sum": self._sum,
                "p50": self.percentile(0.50), "p95": self.percentile(0.95),
                "p99": self.percentile(0.99), "step": self.step}


class DeviceHistogram:
    """One drained device-slab lane: CUMULATIVE fixed-bucket counts (the
    slab accumulates monotonically between restores), stamped with the
    device step of the last drain."""

    __slots__ = ("name", "buckets", "step")

    def __init__(self, name: str):
        self.name = name
        from ..batched.metrics_slab import N_BUCKETS
        self.buckets = np.zeros((N_BUCKETS,), np.int64)
        self.step: Optional[int] = None

    @property
    def count(self) -> int:
        return int(self.buckets.sum())

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the bucket counts; returns the
        bucket's inclusive upper bound (+inf for the saturating bucket)."""
        from ..batched.metrics_slab import bucket_upper_bounds
        n = self.count
        if n == 0:
            return 0.0
        rank = max(1, math.ceil(q * n))
        cum = np.cumsum(self.buckets)
        return float(bucket_upper_bounds()[int(np.searchsorted(cum, rank))])


class MetricsRegistry:
    """Process-wide metric registry. Series are created on first touch and
    live for the registry's lifetime; collectors are pull-time callables
    whose numeric fields surface as gauges under their prefix."""

    def __init__(self, namespace: str = "akka"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._device: Dict[str, DeviceHistogram] = {}
        self._collectors: Dict[str, Callable[[], Dict[str, Any]]] = {}
        self._step = 0  # newest device step seen by any stamp
        self._http_server = None
        self._http_thread = None
        self._jsonl_fh = None
        self._jsonl_thread = None
        self._jsonl_stop = threading.Event()

    # ------------------------------------------------------------- series
    def counter(self, name: str, help: str = "") -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge(name, help))

    def histogram(self, name: str, help: str = "") -> Histogram:
        with self._lock:
            return self._histograms.setdefault(name, Histogram(name, help))

    def register_collector(self, prefix: str,
                           fn: Callable[[], Dict[str, Any]]) -> None:
        """Absorb an existing `*_stats()`-style dict source: at pull time
        (expose / JSONL emit) its numeric fields become gauges named
        `<prefix>_<field>`; non-numeric fields are skipped."""
        with self._lock:
            self._collectors[prefix] = fn

    def set_step(self, step: int) -> None:
        """Advance the correlation axis: the device step counter current
        for subsequently stamped samples."""
        self._step = max(self._step, int(step))

    @property
    def step(self) -> int:
        return self._step

    # ------------------------------------------------------- device slab
    def ingest_device_slab(self, lanes: Dict[str, np.ndarray],
                           step: int) -> None:
        """One drain of the device metric slab (metrics_slab.slab_dict
        output): cumulative bucket counts replace the previous drain's,
        every lane stamped with the draining step."""
        self.set_step(step)
        with self._lock:
            for name, buckets in lanes.items():
                key = f"device_{name}"
                h = self._device.get(key)
                if h is None:
                    h = self._device[key] = DeviceHistogram(key)
                h.buckets = np.asarray(buckets, np.int64)
                h.step = int(step)

    def device_histogram(self, lane: str) -> Optional[DeviceHistogram]:
        return self._device.get(f"device_{lane}")

    # ------------------------------------------------------------- pulls
    def _pull_collectors(self) -> List[Tuple[str, float]]:
        out: List[Tuple[str, float]] = []
        with self._lock:
            items = list(self._collectors.items())
        for prefix, fn in items:
            try:
                d = fn()
            except Exception:  # noqa: BLE001 — a sick collector never breaks expose
                continue
            for k, v in d.items():
                if isinstance(v, bool) or not isinstance(
                        v, (int, float, np.integer, np.floating)):
                    continue
                out.append((f"{prefix}_{k}", float(v)))
        return out

    def expose(self) -> str:
        """Prometheus-style text exposition of every series."""
        from ..batched.metrics_slab import bucket_upper_bounds
        ns = self.namespace
        lines: List[str] = []
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._histograms.values())
            device = list(self._device.values())
        for c in counters:
            lines.append(f"# TYPE {ns}_{c.name} counter")
            lines.append(f"{ns}_{c.name} {c.value}")
        for g in gauges:
            lines.append(f"# TYPE {ns}_{g.name} gauge")
            lines.append(f"{ns}_{g.name} {g.value:g}")
        for name, v in self._pull_collectors():
            lines.append(f"# TYPE {ns}_{name} gauge")
            lines.append(f"{ns}_{name} {v:g}")
        for h in hists:
            s = h.snapshot()
            lines.append(f"# TYPE {ns}_{h.name} summary")
            for q in (0.50, 0.95, 0.99):
                lines.append(f'{ns}_{h.name}{{quantile="{q}"}} '
                             f"{h.percentile(q):g}")
            lines.append(f"{ns}_{h.name}_count {s['count']}")
            lines.append(f"{ns}_{h.name}_sum {s['sum']:g}")
        ubs = bucket_upper_bounds()
        for d in device:
            lines.append(f"# TYPE {ns}_{d.name} histogram")
            cum = 0
            for i, n in enumerate(d.buckets.tolist()):
                cum += int(n)
                le = "+Inf" if math.isinf(ubs[i]) else str(int(ubs[i]))
                lines.append(f'{ns}_{d.name}_bucket{{le="{le}"}} {cum}')
            lines.append(f"{ns}_{d.name}_count {cum}")
            # the step stamp rides as a companion gauge: the device step
            # of the drain that produced these counts (correlation axis)
            lines.append(f"{ns}_{d.name}_step "
                         f"{d.step if d.step is not None else 0}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """One JSON-able frame of every series (the JSONL emitter's row
        body; also handy for tests)."""
        with self._lock:
            frame: Dict[str, Any] = {
                "step": self._step,
                "counters": {c.name: c.value
                             for c in self._counters.values()},
                "gauges": {g.name: g.value for g in self._gauges.values()},
                "histograms": {h.name: h.snapshot()
                               for h in self._histograms.values()},
                "device": {d.name: {"buckets": d.buckets.tolist(),
                                    "count": d.count,
                                    "p50": d.percentile(0.50),
                                    "p95": d.percentile(0.95),
                                    "p99": d.percentile(0.99),
                                    "step": d.step}
                           for d in self._device.values()},
            }
        frame["collected"] = dict(self._pull_collectors())
        return frame

    # ---------------------------------------------------------- HTTP sink
    def serve_http(self, port: int, host: str = "127.0.0.1") -> int:
        """Start the opt-in exposition endpoint (GET /metrics). Returns
        the bound port (pass 0 to let the OS pick — tests do). Daemon
        thread; close() tears it down."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        registry = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                body = registry.expose().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        srv = ThreadingHTTPServer((host, int(port)), Handler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever,
                             name="akka-tpu-metrics-http", daemon=True)
        with self._lock:
            self._http_server, self._http_thread = srv, t
        t.start()
        return int(srv.server_address[1])

    # --------------------------------------------------------- JSONL sink
    def start_jsonl(self, path: str, interval_s: float = 1.0) -> None:
        """Periodic JSONL emitter, flight-recorder file conventions
        (JsonlFlightRecorder): makedirs, line-buffered append, one
        `{"event": "metrics", "ts": ..., ...}` object per line."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        fh = open(path, "a", buffering=1)
        with self._lock:
            self._jsonl_fh = fh
        self._jsonl_stop.clear()

        def loop():
            while not self._jsonl_stop.wait(interval_s):
                self.emit_jsonl_once()

        t = threading.Thread(target=loop, name="akka-tpu-metrics-jsonl",
                             daemon=True)
        with self._lock:
            self._jsonl_thread = t
        t.start()

    def emit_jsonl_once(self) -> None:
        fh = self._jsonl_fh
        if fh is None:
            return
        row = {"event": "metrics", "ts": time.time(), **self.snapshot()}
        try:
            fh.write(json.dumps(row) + "\n")
        except ValueError:  # closed mid-shutdown
            pass

    def close(self) -> None:
        """Final JSONL frame, then tear down both sinks."""
        self._jsonl_stop.set()
        t = self._jsonl_thread
        if t is not None:
            t.join(timeout=2.0)
        if self._jsonl_fh is not None:
            self.emit_jsonl_once()
            try:
                self._jsonl_fh.close()
            except Exception:  # noqa: BLE001
                pass
            self._jsonl_fh = None
        srv = self._http_server
        if srv is not None:
            try:
                srv.shutdown()
                srv.server_close()
            except Exception:  # noqa: BLE001
                pass
            self._http_server = None


def from_config(config) -> Optional[MetricsRegistry]:
    """`akka.metrics.enabled` gates the whole plane (default off). With it
    on: `http-port` > 0 starts the exposition endpoint, `jsonl-path`
    starts the periodic emitter at `jsonl-interval` seconds."""
    if config is None or not config.get_bool("akka.metrics.enabled", False):
        return None
    reg = MetricsRegistry(config.get_string("akka.metrics.namespace",
                                            "akka"))
    port = config.get_int("akka.metrics.http-port", 0)
    if port > 0:
        reg.serve_http(port)
    path = config.get_string("akka.metrics.jsonl-path", "")
    if path:
        reg.start_jsonl(path,
                        config.get_duration("akka.metrics.jsonl-interval",
                                            "1s"))
    return reg
