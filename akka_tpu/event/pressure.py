"""Shared mailbox-pressure signal reading for admission AND autoscaling.

The runtime's overload signals are CUMULATIVE device counters (per-shard
`mailbox_overflow` / exchange `dropped` in the packed attention word): the
meaningful quantity is their GROWTH since the previous poll — device mail
being lost right now — not the lifetime total, or a long-dead spike sheds
(or widens the mesh) forever. That delta bookkeeping used to live as a
closure inside gateway/admission.py; once the mesh autoscaler started
polling the same counters the two copies could drift (different `last`
baselines reading different deltas off one counter stream). This module is
the single owner of that bookkeeping:

  * PressureReader — one object per CONSUMER (admission controller,
    autoscaler): each holds its own last-seen baselines, so two consumers
    polling at different cadences both see correct per-interval deltas.
  * Signal names are the stable vocabulary both layers share:
    "mailbox_overflow", "exchange_dropped", "ask_pool_occupancy", and the
    optional "mailbox_occupancy_p90" histogram-lane signal.

A re-shard (failover or autoscale) RESETS the cumulative counters on the
new mesh (conserved into shard 0 by `_restore_resharded`, possibly lower
after row-0 conservation of a torn snapshot); a naive delta would then go
hugely negative and mask real pressure for one poll. `read()` clamps
deltas at 0 and re-baselines, so the first post-re-shard poll reads quiet,
not negative.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

import numpy as np

__all__ = ["PressureReader", "system_pressure_sources"]


class PressureReader:
    """Growth-delta + occupancy reader over a dict of cumulative/level
    sources. `sources` maps signal name -> zero-arg callable; names listed
    in `cumulative` report max(0, value - last) per read() and re-baseline,
    all others report the level as-is. One reader per consumer — baselines
    are consumer-local state."""

    CUMULATIVE = ("mailbox_overflow", "exchange_dropped")

    def __init__(self, sources: Dict[str, Callable[[], float]],
                 cumulative: Optional[tuple] = None):
        self.sources = dict(sources)
        self.cumulative = tuple(cumulative if cumulative is not None
                                else self.CUMULATIVE)
        self._last: Dict[str, float] = {}
        self._lock = threading.Lock()

    def read(self) -> Dict[str, float]:
        """Poll every source once; returns {name: delta-or-level}. A dead
        source is skipped (a wedged device read must not take down the
        caller's control loop)."""
        out: Dict[str, float] = {}
        with self._lock:
            for name, fn in self.sources.items():
                try:
                    v = float(fn())
                except Exception:  # noqa: BLE001 — dead signal, skip
                    continue
                if name in self.cumulative:
                    last = self._last.get(name)
                    self._last[name] = v
                    # clamp at 0: counters reset on re-shard (conserved into
                    # shard 0, or zeroed); first poll after is quiet
                    out[name] = max(0.0, v - last) if last is not None else 0.0
                else:
                    out[name] = v
        return out

    def rebaseline(self) -> None:
        """Drop the counter baselines (next read() reports 0 for every
        cumulative signal). Call after a re-shard if the consumer wants a
        guaranteed-quiet first poll regardless of counter direction."""
        with self._lock:
            self._last.clear()

    def signals(self) -> Dict[str, Callable[[], float]]:
        """Per-signal zero-arg callables over this reader's shared
        baselines — the AdmissionController `pressure_signals` shape. All
        callables poll ONLY their own signal (one device read each), not
        the whole source dict."""

        def one(name: str) -> Callable[[], float]:
            def poll() -> float:
                fn = self.sources[name]
                v = float(fn())
                if name not in self.cumulative:
                    return v
                with self._lock:
                    last = self._last.get(name)
                    self._last[name] = v
                return max(0.0, v - last) if last is not None else 0.0
            return poll

        return {name: one(name) for name in self.sources}


def system_pressure_sources(system, ask_pool_stats: Optional[Callable[[], Dict[str, Any]]] = None,
                            occupancy_quantile: float = 0.9,
                            open_wave_depth: Optional[Callable[[], float]] = None) -> Dict[str, Callable[[], float]]:
    """Standard source dict for a (Sharded)BatchedSystem:

    | signal                 | source                                      |
    |------------------------|---------------------------------------------|
    | mailbox_overflow       | attention-word mailbox_overflow (cumulative)|
    | exchange_dropped       | attention-word dropped (cumulative)         |
    | ask_pool_occupancy     | promise-slot occupancy (level, 0..1)        |
    | mailbox_occupancy_p90  | metric-slab occupancy-lane p90 (level)      |
    | open_wave_depth        | scheduler open waves / pipeline_depth       |

    `system` may be a live object whose `.system` is swapped under it by a
    re-shard (MeshSentinel, DeviceShardRegion): sources resolve attributes
    at poll time, never capture slabs. The histogram signal only appears
    when the system compiles the metric slab in (`metrics_on`).

    `open_wave_depth` (ISSUE 18 satellite) is the continuous-wave
    pipeline's fullness, a LEVEL in 0..1: 1.0 means `pipeline_depth`
    waves are already open and the next window will block on a wave
    slot, so an admission threshold below 1.0 sheds BEFORE the promise
    pool backs the whole ingest path up. Pass the scheduler's
    `open_wave_depth` bound method (AskBatcher.open_wave_depth)."""
    sys_of = (lambda: system.system) if hasattr(system, "system") \
        else (lambda: system)

    sources: Dict[str, Callable[[], float]] = {
        "mailbox_overflow": lambda: float(sys_of().mailbox_overflow),
        "exchange_dropped": lambda: float(np.sum(sys_of().dropped_per_shard)),
    }
    if ask_pool_stats is not None:
        sources["ask_pool_occupancy"] = \
            lambda: float(ask_pool_stats()["occupancy"])
    if open_wave_depth is not None:
        sources["open_wave_depth"] = \
            lambda: float(open_wave_depth())
    if getattr(sys_of(), "metrics_on", False):
        from ..batched.metrics_slab import HIST_NAMES, bucket_percentile

        def occ_p90() -> float:
            lane = sys_of().read_metrics()[HIST_NAMES[0]]
            return bucket_percentile(lane, occupancy_quantile)

        sources["mailbox_occupancy_p90"] = occ_p90
    return sources
