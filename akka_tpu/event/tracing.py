"""Causal tracing: sampled request → wave → device-step spans (ISSUE 12).

The flight recorder (flight_recorder.py) answers "what happened" as
discrete events; the metrics plane (metrics.py) answers "how is the
system doing" as aggregates on the shared `ATT_STEP` axis. Neither can
answer "what happened to THIS request" once a user-visible latency is
assembled from five asynchronous stages (decode, admission, wave
scheduling, shared step rounds, promise readback). This module is the
missing causal side: a span layer whose records carry

- identity: `trace` / `span` / `parent` ids (u64; a trace is one
  external request's journey),
- both clocks: wall `ts` at start plus monotonic `t0`/`t1` (the
  converter's alignment axis — flight-recorder rows carry the same
  `ts_mono` since ISSUE 12 satellite 2),
- the device step window: `step0`/`step1` on the `ATT_STEP` axis, so a
  span lines up with histograms and FR events without clock guessing.

Sampling is HEAD-BASED: one decision per trace, made at ingress, and the
decision is a pure function of the (deterministically generated) trace
id — same seed ⇒ same sampled set, which is what the tier-1 determinism
test pins. Unsampled requests get trace id 0 and every downstream hook
degrades to one predicate check (the FR noop contract: ≤1% quiet
overhead). `akka.tracing.force-tenants` / `force-request-ids` flip the
decision to "always" for debugging one tenant or one known-bad id.

Context propagates two ways:

- a `contextvars.ContextVar` carries the current span across call
  boundaries in one thread; `AskBatcher.submit` snapshots it into the
  `BatchAsk` so the trace survives the dispatcher thread hop,
- columnar waves (the binary window path) carry an explicit per-member
  ctx list — one window holds many traces, so a single ambient ctx
  cannot represent it.

Sinks mirror the flight recorder: a bounded in-memory ring (tests,
post-mortem) plus an optional JSONL file with the same writer
discipline (makedirs, line-buffered append, lock, close is idempotent).
Selection mirrors the FR SPI: `from_config` returns None unless
`akka.tracing.enabled` — a system without tracing holds no tracer and
pays one `is not None` per hook.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = ["SpanCtx", "Span", "Tracer", "NOOP_SPAN", "current_ctx",
           "set_ctx", "reset_ctx", "from_config"]

_M64 = (1 << 64) - 1

# the ambient span (one per thread of control): gateway roots set it,
# AskBatcher.submit snapshots it across the thread hop
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "akka_tpu_trace_ctx", default=None)


def current_ctx() -> Optional["SpanCtx"]:
    """The calling thread's current span context (None outside any
    sampled span) — the one read `AskBatcher.submit` pays per ask."""
    return _CURRENT.get()


def set_ctx(ctx) -> Any:
    """Install `ctx` as the ambient span context; returns the reset
    token. The explicit form of entering a span block, for callers that
    carry a ctx across an API boundary (columnar waves of one)."""
    return _CURRENT.set(ctx)


def reset_ctx(token) -> None:
    _CURRENT.reset(token)


def _splitmix64(x: int) -> int:
    """Deterministic id stream (the SplitMix64 finalizer): seed + ordinal
    in, well-mixed u64 out. Chosen over random.getrandbits so the same
    seed reproduces the same trace ids AND the same sampled set."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
    return (z ^ (z >> 31)) & _M64


class SpanCtx:
    """Immutable (trace, span) pair — what crosses thread/wave hops."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:
        return f"SpanCtx(trace={self.trace_id:#x}, span={self.span_id})"


class _NoopSpan:
    """The quiet-path span: every method is a no-op, `child` returns
    itself, so an unsampled request walks the whole serving path paying
    attribute reads and empty calls only."""

    __slots__ = ()
    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def begin(self, current: bool = False):
        return self

    def finish(self, **attrs) -> None: ...

    def set(self, **attrs) -> None: ...

    def child(self, name: str, **attrs):
        return self


NOOP_SPAN = _NoopSpan()


class Span:
    """One timed region of one trace. Use as a context manager (sets the
    ambient ctx for the block) or via begin()/finish() when the lifetime
    does not nest lexically (per-member engine spans, columnar roots)."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "ts", "t0", "t1", "step0", "step1", "attrs", "_token")

    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 span_id: int, parent_id: int, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.ts = 0.0
        self.t0 = 0.0
        self.t1 = 0.0
        self.step0 = 0
        self.step1 = 0
        self.attrs = attrs
        self._token = None

    @property
    def ctx(self) -> SpanCtx:
        return SpanCtx(self.trace_id, self.span_id)

    def begin(self, current: bool = False) -> "Span":
        self.ts = time.time()
        self.t0 = time.monotonic()
        self.step0 = self._tracer._step()
        if current:
            self._token = _CURRENT.set(self.ctx)
        return self

    def finish(self, **attrs) -> None:
        if attrs:
            self.attrs.update(attrs)
        self.t1 = time.monotonic()
        self.step1 = self._tracer._step()
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        self._tracer._emit(self)

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def child(self, name: str, **attrs) -> "Span":
        return self._tracer.span(name, self.ctx, **attrs)

    def __enter__(self) -> "Span":
        return self.begin(current=True)

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.finish()
        return False


class Tracer:
    """Head-sampled span recorder. Thread-safe; every public hook is
    fire-and-forget and must never raise into the serving path."""

    enabled = True

    def __init__(self, sample_rate: float = 1.0, seed: int = 0,
                 jsonl_path: Optional[str] = None, capacity: int = 8192,
                 step_fn: Optional[Callable[[], int]] = None,
                 force_tenants=(), force_request_ids=()):
        rate = min(max(float(sample_rate), 0.0), 1.0)
        self._rate_ppm = int(round(rate * 1_000_000))
        self.sample_rate = rate
        self._seed = int(seed) & _M64
        self._ordinal = 0
        self._span_seq = 0
        self.step_fn = step_fn
        self._force_tenants = frozenset(str(t) for t in force_tenants)
        self._force_ids = frozenset(int(i) for i in force_request_ids)
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)
        self._path = jsonl_path
        self._fh = None
        if jsonl_path:
            os.makedirs(os.path.dirname(os.path.abspath(jsonl_path)),
                        exist_ok=True)
            self._fh = open(jsonl_path, "a", buffering=1)

    # ------------------------------------------------------------- sampling
    def sampled(self, trace_id: int) -> bool:
        """The head decision as a pure function of the trace id (ppm
        threshold on a well-mixed u64): deterministic per seed."""
        return (trace_id % 1_000_000) < self._rate_ppm

    def start_trace(self, tenant: Optional[str] = None,
                    request_id: Optional[int] = None) -> int:
        """Mint the next trace id and decide ONCE whether this trace is
        recorded: returns the (nonzero) trace id when sampled or forced,
        else 0 — and 0 is the one value every downstream hook checks."""
        with self._lock:
            self._ordinal += 1
            tid = _splitmix64(self._seed ^ self._ordinal)
        if tid == 0:  # reserve 0 for "unsampled"
            tid = 1
        if self.sampled(tid):
            return tid
        if tenant is not None and tenant in self._force_tenants:
            return tid
        if request_id is not None and int(request_id) in self._force_ids:
            return tid
        return 0

    # ---------------------------------------------------------------- spans
    def span(self, name: str, trace, parent: Optional[int] = None,
             **attrs):
        """Make an (unstarted when used via begin(); started on __enter__)
        span. `trace` is a trace id (int) or a SpanCtx; falsy ⇒ the noop
        span. With no explicit parent, a SpanCtx parents to its span and
        an int trace id parents to the ambient ctx when the trace
        matches (lexical nesting for free)."""
        if not trace:
            return NOOP_SPAN
        if isinstance(trace, SpanCtx):
            trace_id = trace.trace_id
            if parent is None:
                parent = trace.span_id
        else:
            trace_id = int(trace)
            if parent is None:
                cur = _CURRENT.get()
                parent = cur.span_id \
                    if cur is not None and cur.trace_id == trace_id else 0
        with self._lock:
            self._span_seq += 1
            sid = self._span_seq
        return Span(self, name, trace_id, sid, int(parent), dict(attrs))

    def begin(self, name: str, trace, parent: Optional[int] = None,
              current: bool = False, **attrs):
        """span() + begin() in one call — the non-lexical entry point."""
        return self.span(name, trace, parent, **attrs).begin(current)

    def emit(self, name: str, trace, t0: float, t1: float,
             parent: Optional[int] = None, step0: int = 0,
             step1: int = 0, **attrs) -> None:
        """Retro-emit a completed span from explicit timestamps (the
        engine's per-member spans: staged at one loop turn, resolved at
        a later one — no lexical block to wrap)."""
        sp = self.span(name, trace, parent, **attrs)
        if sp is NOOP_SPAN:
            return
        sp.ts = time.time() - (time.monotonic() - t0)
        sp.t0, sp.t1 = float(t0), float(t1)
        sp.step0, sp.step1 = int(step0), int(step1)
        self._emit(sp)

    def _step(self) -> int:
        fn = self.step_fn
        if fn is None:
            return 0
        try:
            return int(fn())
        except Exception:  # noqa: BLE001 — tracing must never raise
            return 0

    def _emit(self, span: Span) -> None:
        row = {"kind": "span", "name": span.name, "trace": span.trace_id,
               "span": span.span_id, "parent": span.parent_id,
               "ts": span.ts, "t0": span.t0, "t1": span.t1,
               "step0": span.step0, "step1": span.step1}
        if span.attrs:
            row.update(span.attrs)
        with self._lock:
            self._buf.append(row)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(row, default=str) + "\n")
                except ValueError:  # closed file mid-shutdown
                    pass

    # ---------------------------------------------------------------- sinks
    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._buf)

    def of_trace(self, trace_id: int) -> List[Dict[str, Any]]:
        """Request-journey query: every span of one trace (exporter (a):
        the span JSONL is keyed by the same `trace` field)."""
        return [s for s in self.spans() if s["trace"] == trace_id]

    def of_name(self, name: str) -> List[Dict[str, Any]]:
        return [s for s in self.spans() if s["name"] == name]

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except Exception:  # noqa: BLE001
                    pass
                self._fh = None


def from_config(config) -> Optional[Tracer]:
    """`akka.tracing.enabled` gates the layer (default off ⇒ None — the
    quiet path is one `is not None`). With it on: `sample-rate` (0..1),
    `jsonl-path` for the span sink, `seed` for the deterministic id
    stream, `force-tenants` / `force-request-ids` for debugging."""
    if config is None or not config.get_bool("akka.tracing.enabled", False):
        return None
    return Tracer(
        sample_rate=config.get_float("akka.tracing.sample-rate", 1.0),
        seed=config.get_int("akka.tracing.seed", 0),
        jsonl_path=config.get_string("akka.tracing.jsonl-path", "") or None,
        capacity=config.get_int("akka.tracing.capacity", 8192),
        force_tenants=config.get_list("akka.tracing.force-tenants", []),
        force_request_ids=config.get_list(
            "akka.tracing.force-request-ids", []))
