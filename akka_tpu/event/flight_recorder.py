"""Flight recorder: structured runtime tracing behind a noop-by-default SPI.

Reference parity: the JDK Flight Recorder emitters selected at runtime —
typed actor events (akka-actor-typed/src/main/scala-jdk-9/akka/actor/typed/
internal/jfr/JFRActorFlightRecorder.scala, noop fallback
typed/internal/ActorFlightRecorder.scala) and remoting events
(akka-remote/src/main/scala-jdk-9/akka/remote/artery/jfr/Events.scala), with
hook points through ArteryTransport.start (ArteryTransport.scala:344,436-466).

The TPU translation (SURVEY.md §2.10 item 9): the host control plane emits
structured events into a pluggable recorder (noop / in-memory ring / JSONL
file), and the device hot path is annotated with jax.profiler traces —
`with trace_span("akka.step")` brackets show up in a TensorBoard/XProf trace
captured via start_trace()/stop_trace() (or bench.py --trace DIR).

Selection mirrors the reference's runtime pick: config
`akka.flight-recorder.implementation = noop|memory|jsonl` read at system
bootstrap; `noop` costs one no-inlined method call per hook, nothing else.
"""

from __future__ import annotations

import inspect
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple


class FlightRecorder:
    """SPI. Every hook is fire-and-forget and must never raise into the
    caller; implementations are thread-safe. Callers building non-trivial
    hook arguments (path strings, reprs) should gate on `enabled` so the
    noop configuration pays one attribute read, nothing else."""

    enabled = True

    # -- actor lifecycle (JFRActorFlightRecorder parity) ---------------------
    def actor_spawned(self, path: str) -> None: ...
    def actor_stopped(self, path: str) -> None: ...
    def actor_failed(self, path: str, cause: str) -> None: ...
    def actor_restarted(self, path: str, cause: str) -> None: ...

    # -- remoting (artery/jfr/Events.scala parity) ---------------------------
    def transport_started(self, address: str) -> None: ...
    def association_opened(self, peer: str) -> None: ...
    def association_quarantined(self, peer: str, reason: str) -> None: ...
    def remote_message_sent(self, peer: str, size: int) -> None: ...
    def remote_message_received(self, peer: str, size: int) -> None: ...

    # -- device runtime (no reference analogue; the TPU data plane) ----------
    def device_step(self, system: str, n_steps: int, elapsed_s: float) -> None: ...
    def device_flush(self, system: str, staged: int) -> None: ...
    def device_compile(self, system: str, elapsed_s: float) -> None: ...
    def dropped(self, system: str, count: int) -> None: ...

    # in-graph supervision counter DELTA since the previous report
    # (batched/supervision.py COUNTER_NAMES): one event per step window,
    # emitted only when something happened — the watchdog's artifact shows
    # directive traffic without per-step device syncs
    def device_supervision(self, system: str, steps: int, failed: int,
                           resumed: int, restarted: int, stopped: int,
                           escalated: int, dead_letters: int) -> None: ...

    # depth-k dispatch pipeline counter DELTA since the previous report
    # (batched/bridge.py): programs enqueued/drained in the window and how
    # many drains paid the wide promise readback (wide_resolves) vs
    # host-only deadline checks — emitted at the pump's busy->idle edge
    # and at handle shutdown
    def device_pipeline(self, system: str, depth: int, steps: int,
                        drains: int, wide_resolves: int,
                        host_checks: int) -> None: ...

    # checkpoint/recovery (batched runtime + persistence/tell_journal):
    # one device_checkpoint per snapshot taken; checkpoint_failed when
    # snapshot IO degrades (the step loop keeps running); journal_truncated
    # when a torn record-log tail is repaired on open
    def device_checkpoint(self, system: str, step: int, elapsed_s: float,
                          size_bytes: int, path: str) -> None: ...

    def checkpoint_failed(self, system: str, error: str,
                          consecutive: int) -> None: ...

    def journal_truncated(self, path: str, dropped_bytes: int) -> None: ...

    # failure detection / degraded-mesh failover (batched/sentinel.py):
    # device_suspected when a shard's heartbeat lane trips its detector
    # (phi-accrual on frozen progress, or the wall-clock drain deadline);
    # device_evicted once the sentinel quarantines it; failover_completed
    # after the surviving-mesh rebuild resumes stepping (mttr_s measures
    # suspicion -> first post-failover step); failover_halted is TERMINAL —
    # the failover breaker tripped and the runtime stopped instead of
    # flapping; shard_overflow localizes mailbox/exchange overflow to one
    # shard (the "slow, not dead" warning)
    def device_suspected(self, system: str, shard: int, phi: float,
                         detector: str) -> None: ...

    def device_evicted(self, system: str, shard: int, step: int) -> None: ...

    def failover_completed(self, system: str, lost_shards, survivors: int,
                           step: int, mttr_s: float) -> None: ...

    def failover_halted(self, system: str, failovers: int,
                        reason: str) -> None: ...

    def shard_overflow(self, system: str, shard: int, mailbox_overflow: int,
                       dropped: int) -> None: ...

    # elastic mesh (batched/sentinel.scale_to + batched/autoscale.py):
    # device_rejoined per device added back on a grow; mesh_expanded /
    # mesh_narrowed after the bounded-pause live re-shard resumes
    # (pause_s = drain -> first dispatch on the new mesh is ready);
    # autoscale_decision records WHY the policy acted (trigger signal +
    # its observed value) with the measured pause — the operator-facing
    # audit trail of every mesh-size change
    def device_rejoined(self, system: str, shard: int, step: int) -> None: ...

    def mesh_expanded(self, system: str, from_shards: int, to_shards: int,
                      step: int, pause_s: float, trigger: str) -> None: ...

    def mesh_narrowed(self, system: str, from_shards: int, to_shards: int,
                      step: int, pause_s: float, trigger: str) -> None: ...

    def autoscale_decision(self, system: str, direction: str, signal: str,
                           value: float, from_shards: int, to_shards: int,
                           pause_ms: float) -> None: ...

    # -- generic escape hatch ------------------------------------------------
    def event(self, name: str, **fields: Any) -> None: ...

    def events(self) -> List[Dict[str, Any]]:
        return []

    def close(self) -> None: ...


class NoOpFlightRecorder(FlightRecorder):
    """Default: every hook is a pass (ActorFlightRecorder noop parity)."""

    enabled = False


def _structured(method_name):
    def hook(self, *args, **kwargs):
        self._record(method_name, args, kwargs)
    return hook


# Recorder plumbing on the SPI that is NOT a structured hook: the **fields
# escape hatch and the buffer/lifecycle accessors.
_NON_HOOKS = frozenset({"event", "events", "close"})


def spi_hook_fields() -> Dict[str, Tuple[str, ...]]:
    """hook name -> positional field names, derived from the FlightRecorder
    SPI signatures themselves. Adding a hook to the SPI (or a field to an
    existing hook) updates every structured recorder automatically — the
    hand-maintained copy of this table used to drift one hook behind."""
    fields: Dict[str, Tuple[str, ...]] = {}
    for name, fn in vars(FlightRecorder).items():
        if name.startswith("_") or name in _NON_HOOKS or not callable(fn):
            continue
        params = tuple(inspect.signature(fn).parameters)
        fields[name] = params[1:]  # drop self
    return fields


class InMemoryFlightRecorder(FlightRecorder):
    """Bounded ring of structured events; the testkit/debug recorder."""

    _FIELDS = spi_hook_fields()

    def __init__(self, capacity: int = 4096):
        self._buf: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def _record(self, name: str, args, kwargs=None) -> None:
        # dual timestamps (ISSUE 12 satellite 2): wall `ts` for humans,
        # monotonic `ts_mono` so tools/trace_export.py can align FR rows
        # with tracing spans without guessing a clock offset. Rows written
        # before this change carry `ts` only and still parse everywhere.
        ev = {"event": name, "ts": time.time(), "ts_mono": time.monotonic()}
        for field, value in zip(self._FIELDS.get(name, ()), args):
            ev[field] = value
        if kwargs:
            ev.update(kwargs)
        self._append(ev)

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            self._buf.append(ev)

    def event(self, name: str, **fields: Any) -> None:
        self._append({"event": name, "ts": time.time(),
                      "ts_mono": time.monotonic(), **fields})

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._buf)

    def of_type(self, name: str) -> List[Dict[str, Any]]:
        return [e for e in self.events() if e["event"] == name]


for _m in InMemoryFlightRecorder._FIELDS:
    setattr(InMemoryFlightRecorder, _m, _structured(_m))


class JsonlFlightRecorder(InMemoryFlightRecorder):
    """Appends every event as one JSON line (the post-mortem recorder —
    a human can `jq` the flight after a crash, like opening a .jfr)."""

    def __init__(self, path: str, capacity: int = 4096):
        super().__init__(capacity)
        self._path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._fh = open(path, "a", buffering=1)
        self._flock = threading.Lock()

    def _append(self, ev: Dict[str, Any]) -> None:
        super()._append(ev)
        with self._flock:
            try:
                self._fh.write(json.dumps(ev) + "\n")
            except ValueError:  # closed file mid-shutdown
                pass

    def close(self) -> None:
        with self._flock:
            try:
                self._fh.close()
            except Exception:  # noqa: BLE001
                pass


def from_config(config) -> FlightRecorder:
    """`akka.flight-recorder.implementation`: noop (default) | memory | jsonl
    (+ `akka.flight-recorder.path` for jsonl)."""
    impl = "noop"
    path = "flight.jsonl"
    capacity = 4096
    if config is not None:
        impl = config.get_string("akka.flight-recorder.implementation", "noop")
        path = config.get_string("akka.flight-recorder.path", path)
        capacity = config.get_int("akka.flight-recorder.capacity", capacity)
    if impl == "memory":
        return InMemoryFlightRecorder(capacity)
    if impl == "jsonl":
        return JsonlFlightRecorder(path, capacity)
    return NoOpFlightRecorder()


# --------------------------------------------------------- jax.profiler side
# one import attempt per process, not one per span (the old per-__enter__
# `import jax.profiler` paid the sys.modules lookup + exception machinery
# on every bracket); absent profiler stays a harmless noop forever
_PROFILER: Any = None
_PROFILER_TRIED = False


def _profiler():
    global _PROFILER, _PROFILER_TRIED
    if not _PROFILER_TRIED:
        _PROFILER_TRIED = True
        try:
            import jax.profiler as _p
            _PROFILER = _p
        except Exception:  # noqa: BLE001
            _PROFILER = None
    return _PROFILER


class trace_span:
    """Context manager: annotate a host-side region so it shows up in a
    jax.profiler (XProf/TensorBoard) trace alongside the XLA ops it
    launches. No-ops harmlessly when the profiler isn't active."""

    __slots__ = ("_name", "_cm")

    def __init__(self, name: str):
        self._name = name
        self._cm = None

    def __enter__(self):
        prof = _profiler()
        if prof is None:
            return self
        try:
            self._cm = prof.TraceAnnotation(self._name)
            self._cm.__enter__()
        except Exception:  # noqa: BLE001 — tracing must never break the step
            self._cm = None
        return self

    def __exit__(self, *exc):
        if self._cm is not None:
            try:
                self._cm.__exit__(*exc)
            except Exception:  # noqa: BLE001
                pass
        return False


def start_trace(log_dir: str) -> bool:
    """Begin capturing a device+host profiler trace into log_dir (open with
    TensorBoard's profile plugin / xprof)."""
    try:
        import jax.profiler
        jax.profiler.start_trace(log_dir)
        return True
    except Exception:  # noqa: BLE001
        return False


def stop_trace() -> bool:
    try:
        import jax.profiler
        jax.profiler.stop_trace()
        return True
    except Exception:  # noqa: BLE001
        return False
