"""EventStream: the system-wide pub-sub bus with subchannel classification.

Reference parity: akka-actor/src/main/scala/akka/event/EventStream.scala:26-50 —
subscribe by channel *class*; publishing an event delivers it to subscribers of
the event's class and every superclass (subchannel classification via
util/Subclassification). Carries LogEvents, DeadLetters, lifecycle events.
Also EventBus variants (LookupClassification / ScanningClassification) from
akka-actor/src/main/scala/akka/event/EventBus.scala.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Any, Callable, Dict, Optional, Set


class EventBus:
    """Classifier-based bus: subclasses define classify(event) -> classifier
    and compare classifiers (reference: event/EventBus.scala)."""

    def subscribe(self, subscriber, to: Any) -> bool:
        raise NotImplementedError

    def unsubscribe(self, subscriber, from_: Any = None) -> bool:
        raise NotImplementedError

    def publish(self, event: Any) -> None:
        raise NotImplementedError


class LookupEventBus(EventBus):
    """Exact-classifier lookup (reference: LookupClassification)."""

    def __init__(self):
        self._subscribers: Dict[Any, Set] = defaultdict(set)
        self._lock = threading.RLock()

    def classify(self, event: Any) -> Any:
        raise NotImplementedError

    def publish_to(self, event: Any, subscriber: Any) -> None:
        subscriber.tell(event, None)

    def subscribe(self, subscriber, to: Any) -> bool:
        with self._lock:
            self._subscribers[to].add(subscriber)
        return True

    def unsubscribe(self, subscriber, from_: Any = None) -> bool:
        with self._lock:
            if from_ is None:
                for subs in self._subscribers.values():
                    subs.discard(subscriber)
            else:
                self._subscribers[from_].discard(subscriber)
        return True

    def publish(self, event: Any) -> None:
        for sub in list(self._subscribers.get(self.classify(event), ())):
            self.publish_to(event, sub)


class EventStream(EventBus):
    """Class-hierarchy (subchannel) classification: subscribing to a class
    receives events of that class and all its subclasses."""

    def __init__(self, debug: bool = False):
        self._subscribers: Dict[type, Set] = defaultdict(set)
        self._lock = threading.RLock()
        self.debug = debug
        self._direct: list[Callable[[Any], None]] = []  # synchronous taps (stdout logger)

    def attach_tap(self, fn: Callable[[Any], None]) -> None:
        self._direct.append(fn)

    def detach_tap(self, fn: Callable[[Any], None]) -> None:
        try:
            self._direct.remove(fn)
        except ValueError:
            pass

    def subscribe(self, subscriber, to: type) -> bool:
        if subscriber is None:
            raise ValueError("subscriber is None")
        with self._lock:
            self._subscribers[to].add(subscriber)
        return True

    def unsubscribe(self, subscriber, from_: Optional[type] = None) -> bool:
        with self._lock:
            if from_ is None:
                for subs in self._subscribers.values():
                    subs.discard(subscriber)
            else:
                self._subscribers.get(from_, set()).discard(subscriber)
        return True

    def publish(self, event: Any) -> None:
        for tap in self._direct:
            try:
                tap(event)
            except Exception:  # noqa: BLE001 — bus must not die
                pass
        event_cls = type(event)
        targets: Set = set()
        with self._lock:
            for cls, subs in self._subscribers.items():
                if isinstance(cls, type) and isinstance(event, cls):
                    targets |= subs
        for sub in targets:
            try:
                if hasattr(sub, "tell"):
                    sub.tell(event, None)
                else:
                    sub(event)
            except Exception:  # noqa: BLE001
                pass
