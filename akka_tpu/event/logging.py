"""Logging events + logger actors.

Reference parity: akka-actor/src/main/scala/akka/event/Logging.scala —
LogEvent levels (Error/Warning/Info/Debug), logger actors subscribed on the
EventStream with a dedicated mailbox (event/LoggerMailbox.scala), and the
LoggingAdapter (BusLogging) front-end.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Optional

ERROR_LEVEL = 1
WARNING_LEVEL = 2
INFO_LEVEL = 3
DEBUG_LEVEL = 4

_LEVEL_NAMES = {ERROR_LEVEL: "ERROR", WARNING_LEVEL: "WARNING",
                INFO_LEVEL: "INFO", DEBUG_LEVEL: "DEBUG"}
_NAME_LEVELS = {v: k for k, v in _LEVEL_NAMES.items()}
_NAME_LEVELS["OFF"] = 0


def level_for(name: str) -> int:
    return _NAME_LEVELS.get(name.upper(), INFO_LEVEL)


@dataclass
class LogEvent:
    log_source: str
    log_class: str
    message: Any
    level: int = INFO_LEVEL
    timestamp: float = field(default_factory=time.time)
    mdc: dict = field(default_factory=dict)
    marker: Optional[str] = None


@dataclass
class Error(LogEvent):
    cause: Optional[BaseException] = None

    def __post_init__(self):
        self.level = ERROR_LEVEL


@dataclass
class Warning(LogEvent):
    def __post_init__(self):
        self.level = WARNING_LEVEL


@dataclass
class Info(LogEvent):
    def __post_init__(self):
        self.level = INFO_LEVEL


@dataclass
class Debug(LogEvent):
    def __post_init__(self):
        self.level = DEBUG_LEVEL


_CLASS_FOR = {ERROR_LEVEL: Error, WARNING_LEVEL: Warning, INFO_LEVEL: Info, DEBUG_LEVEL: Debug}


class StdOutLogger:
    """Synchronous fallback logger used during system startup/shutdown
    (reference: Logging.StandardOutLogger)."""

    _lock = threading.Lock()

    def __init__(self, level: int = WARNING_LEVEL):
        self.level = level

    def __call__(self, event: LogEvent) -> None:
        if event.level > self.level:
            return
        ts = time.strftime("%H:%M:%S", time.localtime(event.timestamp))
        line = f"[{_LEVEL_NAMES.get(event.level, '?')}] [{ts}] [{event.log_source}] {event.message}"
        with self._lock:
            print(line, file=sys.stderr)
            cause = getattr(event, "cause", None)
            if cause is not None:
                traceback.print_exception(type(cause), cause, cause.__traceback__, file=sys.stderr)


class LoggingAdapter:
    """Per-source front-end publishing onto the event stream
    (reference: event/Logging.scala BusLogging)."""

    __slots__ = ("bus", "log_source", "log_class", "level")

    def __init__(self, bus, log_source: str, log_class: str = "", level: int = DEBUG_LEVEL):
        self.bus = bus
        self.log_source = log_source
        self.log_class = log_class
        self.level = level

    def _log(self, level: int, message: str, cause: Optional[BaseException] = None) -> None:
        if level > self.level:
            return
        cls = _CLASS_FOR[level]
        if cls is Error:
            self.bus.publish(Error(self.log_source, self.log_class, message, cause=cause))
        else:
            self.bus.publish(cls(self.log_source, self.log_class, message))

    def error(self, message: str, cause: Optional[BaseException] = None) -> None:
        self._log(ERROR_LEVEL, message, cause)

    def warning(self, message: str) -> None:
        self._log(WARNING_LEVEL, message)

    def info(self, message: str) -> None:
        self._log(INFO_LEVEL, message)

    def debug(self, message: str) -> None:
        self._log(DEBUG_LEVEL, message)

    def is_enabled(self, level: int) -> bool:
        return level <= self.level
