"""Delivery primitives: segment reductions over recipient ids.

TPU-native replacement for the reference's MPSC mailbox queues
(AbstractNodeQueue.java; dispatch/Mailbox.scala:467-497): a step's messages
are SoA columns (dst, payload, valid) and "enqueue + dequeue" becomes one
segment reduction per step — sums/maxes/counts land in per-actor slots.

Two kernel families implement the ordered paths (see
docs/DELIVERY_KERNELS.md for the measured crossover table):

- "ranked" (rank-then-scatter, the default XLA backend): ONE sort over a
  narrow int32 key operand (on CPU a single packed (key, arrival-block)
  operand — see `stable_ranks`) computes per-recipient ranks/offsets;
  every slot index, spill position and aggregation offset is then
  closed-form, and payload rows move with one scatter/gather — payload
  columns never ride the sort network.
- "wide" (the reference backend, kept for A/B and for TPU where its
  numbers were actually measured): every payload column rides a
  multi-operand sort (measured ~70x the narrow sort at 1M rows on CPU).

Kernel implementation choice is behind the `delivery_backend` seam
(set_delivery_backend / the `backend=` argument) so a Pallas backend can
drop in later without touching callers; `mode="auto"` routes through the
cost model in `choose_reduce_kernel`. Both families produce bit-identical
`Delivery`/`SlotDelivery` results (up to the sign of floating-point zero
— the wide kernels' marker rows interleave +0.0 additions), enforced by
tests/test_delivery_parity.py.

All functions are jit-safe, static-shape, and XLA-fusable. The drop bucket
(index n_actors) absorbs invalid/out-of-range messages so no dynamic filtering
is needed.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


def _resolve_platform(x: jax.Array) -> str:
    """Platform the computation will actually RUN on, for auto-mode kernel
    choice: prefer the operand's committed device (arrays placed on TPU
    while the process default is cpu — the repo's own cpu-first forcing
    workflow — must still pick the TPU kernel); tracers carry no devices,
    so fall back to the default backend that jit will target."""
    try:
        devs = x.devices()
        if devs:
            return next(iter(devs)).platform
    except Exception:  # noqa: BLE001 — tracers/abstract values
        pass
    return jax.default_backend()


class Delivery(NamedTuple):
    sum: jax.Array     # [N, P]
    max: jax.Array     # [N, P]
    count: jax.Array   # [N] int32


# ---------------------------------------------------------------------------
# delivery_backend seam
#
# A backend names the IMPLEMENTATION of the ordered kernels (merge/sort/
# slots); the mode names the SEMANTIC variant callers ask for. Keeping the
# two orthogonal is what lets a Pallas backend drop in later without
# touching callers (VERDICT next-round #3).
#
#   "auto"      — cost-model choice per platform (ranked on CPU, wide on
#                 TPU until the attribution bench runs on-chip)
#   "xla"       — the rank-then-scatter kernels (narrow key rank + one
#                 payload gather/scatter)
#   "reference" — the original wide multi-operand-sort kernels, kept
#                 bit-for-bit for parity tests and on-chip A/B
#   "pallas"    — the ring-mailbox prototype kernel
#                 (akka_tpu/ops/pallas_mailbox.py): per-recipient cursor
#                 bump in arrival order, no rank pass at all. Falls back
#                 to the ranked kernels per call when Pallas is
#                 unimportable or the call shape/options are outside the
#                 prototype's support matrix (see `pallas_mailbox.supported`).
# ---------------------------------------------------------------------------

DELIVERY_BACKENDS = ("auto", "xla", "reference", "pallas")
_delivery_backend = "auto"


def set_delivery_backend(name: str) -> str:
    """Set the process-default delivery backend; returns the previous one.
    Per-call `backend=` arguments override this."""
    global _delivery_backend
    if name not in DELIVERY_BACKENDS:
        raise ValueError(f"unknown delivery backend {name!r}; "
                         f"expected one of {DELIVERY_BACKENDS}")
    prev = _delivery_backend
    _delivery_backend = name
    return prev


def get_delivery_backend() -> str:
    return _delivery_backend


def _backend_impl(backend: str | None, platform: str) -> str:
    """Resolve a backend name to a kernel family: 'ranked', 'wide' or
    'pallas'."""
    backend = backend or _delivery_backend
    if backend == "reference":
        return "wide"
    if backend == "xla":
        return "ranked"
    if backend == "pallas":
        return "pallas"
    # auto: ranked is measured faster on CPU (docs/DELIVERY_KERNELS.md
    # crossover table); the wide kernels' TPU numbers are the only ones
    # actually measured on-chip (r4), so TPU keeps them until
    # delivery_attribution runs in a TPU window.
    return "ranked" if platform == "cpu" else "wide"


# Below this message count the reduce kernels are N-shaped (markers /
# boundary reads dominate) while scatter is M-shaped; measured r4.
SCATTER_MAX_M = 1024


def choose_reduce_kernel(m: int, n_actors: int, p: int,
                         platform: str = "cpu") -> str:
    """Cost model for mode="auto": pick the reduce-delivery mode from
    (M, N, P, platform). Crossover points are measured by the bench
    artifact (bench.py modes config + delivery_attribution), recorded in
    docs/DELIVERY_KERNELS.md:

    - cpu: XLA scatter-add beats every sort at every measured shape (64k
      actors, P=4, bench modes config: scatter 7.6 ms/step vs ranked
      merge 11.2 vs wide merge ~123). Always scatter.
    - M <= SCATTER_MAX_M: scatter — a few host rows into a large actor
      space would pay an N-shaped sort for an M-shaped problem.
    - tpu/gpu: merge (the wide merge kernel is the one with on-chip
      measurements: sorts vectorize, 1M-row gathers and unsorted scatters
      run 10-40x slower). The ranked kernel's single [M, P] gather is
      unmeasured on-chip; the per-phase attribution exists so the next
      TPU window can move this crossover from assertion to measurement.
    """
    del n_actors, p  # present in the signature for future crossovers
    if platform == "cpu" or m <= SCATTER_MAX_M:
        return "scatter"
    return "merge"


def deliver(dst: jax.Array, payload: jax.Array, valid: jax.Array,
            n_actors: int, need_max: bool = False,
            mode: str = "auto", backend: str | None = None) -> Delivery:
    """Reduce messages into per-actor inbox slots.

    dst: [M] int32 recipient ids; payload: [M, P]; valid: [M] bool.
    Invalid or out-of-range messages fall into a drop bucket.

    Modes:
    - "scatter": XLA scatter-add (segment_sum). Wins for small M and on
      CPU, where scatter-add lowers to a serial O(M) loop.
    - "merge" / "sort": the ordered sort-based kernels. Which
      IMPLEMENTATION runs is the backend's choice: under the default
      "xla" (rank-then-scatter) backend both lower to `_deliver_ranked`
      — a narrow (key, arrival) sort plus one payload gather — because
      once payload stops riding the sort network the historical
      merge/sort distinction collapses. Under backend="reference" the
      original wide kernels run (`_deliver_merge_wide`,
      `_deliver_sorted_wide`).
    - "auto": `choose_reduce_kernel` cost model over (M, N, P, platform),
      decided at trace time so it is free at runtime.

    All choices return bit-identical results (up to the sign of float
    zero); tests/test_delivery_parity.py enforces it.
    """
    if mode == "auto":
        mode = choose_reduce_kernel(dst.shape[0], n_actors,
                                    payload.shape[1],
                                    _resolve_platform(dst))
    impl = _backend_impl(backend, _resolve_platform(dst))
    if mode == "pallas" or (impl == "pallas" and mode != "scatter"):
        from akka_tpu.ops import pallas_mailbox  # deferred: optional dep
        if pallas_mailbox.supported(n_actors, payload.shape[1]):
            return pallas_mailbox.deliver_reduce(dst, payload, valid,
                                                 n_actors, need_max)
        # fallback matrix (docs/DELIVERY_KERNELS.md): unsupported shape
        # or no Pallas -> the ranked kernels, merge semantics
        mode = "merge" if mode == "pallas" else mode
        impl = "ranked"
    if mode == "scatter":
        return _deliver_scatter(dst, payload, valid, n_actors, need_max)
    if impl == "wide":
        if mode == "merge":
            return _deliver_merge_wide(dst, payload, valid, n_actors,
                                       need_max)
        return _deliver_sorted_wide(dst, payload, valid, n_actors, need_max)
    return _deliver_ranked(dst, payload, valid, n_actors, need_max,
                           style=mode)


# Within-block triangle size for the packed-sort rank strategy: the
# [M/B, B, B] equality triangle costs M*B vectorized ops, the int32
# packing needs (n_actors + 2) * ceil(M/B) < 2^31. B=32 keeps both sides
# comfortable up to ~1M actors at the bench's CPU auto scale.
_RANK_BLOCK = 32


RANK_STRATEGIES = ("auto", "counting", "packed", "sort2")

# Key domains this small rank in ONE counting pass (radix covers the
# whole alphabet), where counting beats the packed sort outright on the
# CPU grid bench — this is the sharded exchange's shard-id case.
_COUNT_SMALL_DOMAIN = 64


def _auto_rank_strategy(m: int, n_keys: int, platform: str) -> str:
    """The measured strategy crossover (docs/DELIVERY_KERNELS.md grid):
    counting wins wherever the packed strategy's int32 packing overflows
    (1.5-3x over the sort2 fallback at 1M x 64k and 1M x 1M) and for tiny
    key domains where it needs a single compare-reduce pass; the packed
    sort keeps a modest edge on mid-scale legal shapes; accelerators
    keep the vectorizing two-operand sort."""
    if platform != "cpu":
        return "sort2"
    nb = -(-m // _RANK_BLOCK)
    if (n_keys + 2) * nb >= 2 ** 31:
        return "counting"
    if n_keys + 2 <= _COUNT_SMALL_DOMAIN:
        return "counting"
    return "packed"


def stable_ranks(key: jax.Array, n_keys: int,
                 platform: str | None = None,
                 strategy: str = "auto") -> Tuple[jax.Array, jax.Array]:
    """The 'rank' phase of rank-then-scatter: for each row, the number of
    EARLIER rows with the same key (its stable arrival rank within the
    recipient), plus per-key counts. Returns (rank [M] int32,
    counts [n_keys + 1] int32); keys must lie in [0, n_keys].

    Everything downstream — slot indices, spill positions, the inverse
    sort permutation inv = offsets[key] + rank — is closed-form from
    these two arrays, so no payload column ever rides a sort network.

    Three strategies, chosen at trace time (`strategy="auto"` follows
    the measured crossover in `_auto_rank_strategy`; the explicit names
    exist for A/B benches and parity tests):

    - counting: no sort network AT ALL — `counting_ranks` buckets rows
      by (key-digit, arrival-block), ONE exclusive cumsum over the
      compare-reduce histogram gives every row its cross-block offset,
      and a [B, B] equality triangle gives the within-block stable
      rank. O(M * radix) compare/cumsum work per radix pass; large key
      domains decompose into LSD passes so there is no int32 packing
      limit. The CPU pick for tiny key domains (sharded exchange) and
      for every shape where packing would overflow — including the
      1M x 1M bench shape, where it measures 1.5-2.7x the sort2
      fallback (docs/DELIVERY_KERNELS.md has the grid).
    - packed (CPU pick for mid-scale key domains): pack
      (key, block-of-B arrival index) into ONE int32 and single-operand
      lax.sort it — measured 5.3x faster than the generic-comparator
      two-operand sort. Cross-block ranks come back via vectorized
      binary search on the sorted packs; within-block ranks via the
      same [B, B] equality triangle. Requires
      (n_keys + 2) * ceil(M/B) < 2^31; falls back to counting beyond.
    - sort2 (TPU/GPU): the two-operand (key, iota) sort +
      head-flag/cummax ranks (sorts vectorize on accelerators; the
      counting strategy's data-dependent scatters and the packed
      strategy's searchsorted binary search both serialize into
      dependent gathers).
    """
    m = key.shape[0]
    nb = -(-m // _RANK_BLOCK)
    if platform is None:
        platform = _resolve_platform(key)
    if strategy not in RANK_STRATEGIES:
        raise ValueError(f"unknown rank strategy {strategy!r}; "
                         f"expected one of {RANK_STRATEGIES}")
    if strategy == "auto":
        strategy = _auto_rank_strategy(m, n_keys, platform)
    if strategy == "packed" and (n_keys + 2) * nb >= 2 ** 31:
        strategy = "counting"  # int32 packing would overflow; counting
        #                        has no such precondition and measures
        #                        1.5-3x faster than the sort2 fallback here
    if strategy == "counting":
        return counting_ranks(key, n_keys)
    if strategy == "packed":
        kp, packed = _pack_keys(key, n_keys)
        psorted = jax.lax.sort(packed)
        rank, counts = _ranks_from_packed(psorted, packed, kp, n_keys)
        return rank[:m], counts
    iota = jnp.arange(m, dtype=jnp.int32)
    skey, sidx = jax.lax.sort((key, iota), num_keys=1)
    head = jnp.concatenate([jnp.ones((1,), jnp.bool_), skey[1:] != skey[:-1]])
    start = jax.lax.cummax(jnp.where(head, iota, -1))
    rank = jnp.zeros((m,), jnp.int32).at[sidx].set(iota - start)
    bounds = jnp.searchsorted(
        skey, jnp.arange(n_keys + 2, dtype=jnp.int32)).astype(jnp.int32)
    return rank, bounds[1:] - bounds[:-1]


def _pack_keys(key: jax.Array, n_keys: int):
    """Pack (key, arrival-block) into a single int32 sort operand; rows
    past M pad with key n_keys + 1 so they sort last and never perturb
    counts. Returns (padded keys [nb*B], packed operand [nb*B])."""
    m = key.shape[0]
    b = _RANK_BLOCK
    nb = -(-m // b)
    pad = nb * b - m
    kp = (key if pad == 0 else
          jnp.concatenate([key, jnp.full((pad,), n_keys + 1, jnp.int32)]))
    blk = jnp.arange(nb * b, dtype=jnp.int32) // b
    return kp, kp * nb + blk


def _ranks_from_packed(psorted, packed, kp, n_keys: int):
    """The rank phase proper: cross-block same-key counts via vectorized
    binary search on the sorted packs, within-block counts via a [B, B]
    equality triangle. Returns (rank [nb*B], counts [n_keys + 1])."""
    b = _RANK_BLOCK
    nb = packed.shape[0] // b
    kb = jnp.searchsorted(
        psorted,
        jnp.arange(n_keys + 2, dtype=jnp.int32) * nb).astype(jnp.int32)
    counts = kb[1:] - kb[:-1]                              # [n_keys + 1]
    before = (jnp.searchsorted(psorted, packed).astype(jnp.int32)
              - kb[kp])                # same-key rows in earlier blocks
    k2 = kp.reshape(nb, b)
    tri = jnp.tril(jnp.ones((b, b), jnp.bool_), k=-1)      # tri[i, j] = j < i
    within = jnp.sum((k2[:, :, None] == k2[:, None, :]) & tri[None],
                     axis=2, dtype=jnp.int32)
    return before + within.reshape(-1), counts


# Counting-pass tuning, from the measured per-op constants on XLA CPU
# (docs/DELIVERY_KERNELS.md): a fused broadcast-compare-reduce runs at
# ~0.2 ns/element while scatter costs ~85 ns/row and cumsum ~10 ns/bin
# (log-depth passes). So a pass NEVER scatters — the histogram is a
# compare-reduce against the digit alphabet — and the radix stays small
# (<= 2^_COUNT_MAX_RADIX_BITS) so both the [nb, radix] compare and the
# flat histogram cumsum stay cheap; what large radixes would save —
# passes — costs less than the giant histograms they need.
_COUNT_MAX_RADIX_BITS = 8
_COUNT_MAX_BINS = 1 << 22


def _counting_pass(digit: jax.Array, n_digits: int, nb: int,
                   b: int) -> jax.Array:
    """One stable counting pass: the destination position of every padded
    row when rows are ordered by `digit` (values in [0, n_digits)) with
    arrival order as the tiebreak. For a row in block `blk` with digit
    `d` the destination is

        (# rows with a smaller digit)             flat-cumsum, digit-major
      + (# same-digit rows in earlier blocks)     ... same cumsum
      + (# same-digit rows earlier in this block) [B, B] equality triangle

    — the "histogram -> exclusive cumsum -> arrival-block cumsum"
    decomposition with no sort network and no scatter: the [nb, n_digits]
    per-block histogram is a broadcast compare against the digit alphabet
    reduced over the block axis (XLA fuses it; ~0.2 ns/element vs ~85
    ns/row for a scatter-add histogram), and ONE flat exclusive cumsum
    over its digit-major transpose yields the first two terms in a
    single gather."""
    d2 = digit.reshape(nb, b)
    alphabet = jnp.arange(n_digits, dtype=jnp.int32)
    hist = jnp.sum(alphabet[None, :, None] == d2[:, None, :],
                   axis=2, dtype=jnp.int32)                # [nb, n_digits]
    flat = jnp.cumsum(hist.T.reshape(-1))                  # digit-major
    excl = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            flat[:-1].astype(jnp.int32)])
    blk = jnp.arange(nb * b, dtype=jnp.int32) // b
    base = excl[digit * nb + blk]
    tri = jnp.tril(jnp.ones((b, b), jnp.bool_), k=-1)      # tri[i, j] = j < i
    within = jnp.sum((d2[:, :, None] == d2[:, None, :]) & tri[None],
                     axis=2, dtype=jnp.int32)
    return base + within.reshape(-1)


def counting_ranks(key: jax.Array, n_keys: int,
                   max_bins: int = _COUNT_MAX_BINS
                   ) -> Tuple[jax.Array, jax.Array]:
    """`stable_ranks` by bucketed counting sort — the rank phase with NO
    sort network: O(M * radix) compare/cumsum work per pass instead of
    an O(M log M) sort. Returns (rank [M] int32, counts [n_keys + 1]
    int32); keys must lie in [0, n_keys].

    One `_counting_pass` orders rows stably by one base-`radix` digit of
    the key; LSD composition of `passes = ceil(log_radix(domain))`
    passes orders them by the full key. Small key domains (the sharded
    exchange's shard ids, small-N tests) take exactly one pass with the
    alphabet trimmed to the domain. Between passes the permutation is
    applied to the keys by one narrow int32 scatter (positions are a
    bijection) and pass permutations compose by gather
    (pos = step[pos]); those scatters are the dominant cost, so the
    radix is chosen as the SMALLEST power of two that still achieves
    the minimum pass count reachable under _COUNT_MAX_RADIX_BITS. Rows
    past M pad with key n_keys + 1 so they order strictly last and
    never perturb ranks or counts.

    Unlike the packed strategy there is no int32 packing precondition:
    every intermediate is a position (< padded M) or a histogram count
    (<= M), so any (M, n_keys) that fits in memory is exact.
    """
    m = key.shape[0]
    b = _RANK_BLOCK
    nb = -(-m // b)
    pad = nb * b - m
    kp = (key if pad == 0 else
          jnp.concatenate([key, jnp.full((pad,), n_keys + 1, jnp.int32)]))
    n_vals = n_keys + 2              # real keys + drop bucket + pad key
    bitlen = max((n_vals - 1).bit_length(), 1)
    passes = -(-bitlen // _COUNT_MAX_RADIX_BITS)
    r_bits = -(-bitlen // passes)    # smallest radix with that pass count
    while nb * (1 << r_bits) > max_bins and r_bits > 1:
        passes += 1
        r_bits = -(-bitlen // passes)
    radix = 1 << r_bits
    pos = None                       # pos[i]: destination of original row i
    kcur = kp                        # keys arranged in the current order
    for p in range(passes):
        if p + 1 < passes:
            digit = (kcur >> (p * r_bits)) & (radix - 1)
            nd = radix
        else:
            digit = kcur >> (p * r_bits)
            nd = -(-n_vals // (radix ** p))  # top-digit alphabet only
        step = _counting_pass(digit, nd, nb, b)
        pos = step if pos is None else step[pos]
        if p + 1 < passes:
            kcur = jnp.zeros_like(kcur).at[step].set(
                kcur, unique_indices=True, mode="promise_in_bounds")
    counts = jnp.zeros((n_vals,), jnp.int32).at[kp].add(
        1, mode="promise_in_bounds")[:n_keys + 1]
    excl = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(counts)[:-1]])
    return pos[:m] - excl[key], counts


def _merged_layout_sums(inv, key, incl, masked, n_actors: int) -> jax.Array:
    """Per-segment sums with the EXACT float association of the wide merge
    kernel: messages and the n+1 zero marker rows share one cumsum of
    length M + N + 1, and XLA's scan-tree association depends on that
    length. The interleaved layout is closed-form — row i lands at
    inv[i] + key[i] (key[i] markers precede it), marker k at
    k + incl[k] — so ONE narrow int32 scatter of row indices rebuilds it
    (the [., P] payload rows follow by gather, ~60x cheaper than
    scattering them) without any wide sort."""
    m, p = masked.shape
    n1 = n_actors + 1
    g = jnp.full((m + n1,), -1, jnp.int32).at[inv + key].set(
        jnp.arange(m, dtype=jnp.int32))
    merged = jnp.where((g >= 0)[:, None], masked[jnp.maximum(g, 0)], 0)
    csum = jnp.cumsum(merged, axis=0)
    mk = csum[jnp.arange(n1, dtype=jnp.int32) + incl]
    return jnp.concatenate([mk[:1], mk[1:] - mk[:-1]],
                           axis=0)[:n_actors].astype(masked.dtype)


def _deliver_ranked(dst, payload, valid, n_actors: int, need_max: bool,
                    style: str = "merge") -> Delivery:
    """Rank-then-scatter segment reduction.

    Phases (the names match bench.py's attribution breakdown):

    - key-sort + rank: `stable_ranks` — only narrow int32 keys are ever
      sorted.
    - place: ONE [M, P] scatter at the closed-form inverse permutation
      lines payload rows up in (recipient, arrival) order.
    - reduce: per-column cumsum + boundary reads. The partial-sum
      sequence replicates the wide kernel of the same `style`
      bit-for-bit ("merge" interleaves the n+1 zero marker rows into the
      cumsum, "sort" runs it over the M message rows), because XLA's
      scan-tree association depends on layout and length.

    `style` also preserves each wide kernel's empty-segment max
    convention ("merge" zeroes max <= -inf sentinels, "sort" zeroes
    count == 0 segments) so parity holds against either reference.
    """
    m, p = payload.shape
    ok = valid & (dst >= 0) & (dst < n_actors)
    key = jnp.where(ok, dst, n_actors).astype(jnp.int32)
    rank, counts_full = stable_ranks(key, n_actors, _resolve_platform(dst))
    incl = jnp.cumsum(counts_full)                          # [n+1]
    excl = jnp.concatenate([jnp.zeros((1,), jnp.int32), incl[:-1]])
    inv = excl[key] + rank
    counts = counts_full[:n_actors]
    masked = jnp.where(ok[:, None], payload, 0)
    if style == "merge":
        sums = _merged_layout_sums(inv, key, incl, masked, n_actors)
    else:
        # inv is a bijection on [0, M), so inverting it is one narrow
        # int32 scatter; the payload rows follow by gather
        g = jnp.zeros((m,), jnp.int32).at[inv].set(
            jnp.arange(m, dtype=jnp.int32))
        csum = jnp.concatenate([jnp.zeros((1, p), payload.dtype),
                                jnp.cumsum(masked[g], axis=0)], axis=0)
        sums = (csum[incl[:n_actors]]
                - csum[excl[:n_actors]]).astype(payload.dtype)
    if need_max:
        neg_inf = _neg_inf(payload.dtype)
        maxs = jax.ops.segment_max(jnp.where(ok[:, None], payload, neg_inf),
                                   key, num_segments=n_actors + 1)[:n_actors]
        if style == "merge":
            maxs = jnp.where(maxs <= neg_inf, jnp.zeros_like(maxs), maxs)
        else:
            maxs = jnp.where((counts > 0)[:, None], maxs, 0)
        maxs = maxs.astype(payload.dtype)
    else:
        maxs = jnp.zeros((n_actors, p), payload.dtype)
    return Delivery(sum=sums, max=maxs, count=counts)


def _deliver_merge_wide(dst, payload, valid, n_actors: int,
                        need_max: bool) -> Delivery:
    """Gather/scatter-free segment reduction via a merged marker sort
    (the "reference" backend; payload columns ride both sorts).

    Sort #1: messages and n+1 boundary markers together, on the packed key
    ``key*2 + tag`` (tag: 0 = message, 1 = marker) so marker i lands
    immediately after every message addressed to actor i. An inclusive
    cumsum over the sorted payload (markers contribute 0) then carries, at
    marker i's position, the total of all messages with key <= i.

    Sort #2: on ``tag*(n+2) + key`` — moves the n+1 marker rows (with their
    cumsum columns) contiguously to the tail, in actor order; messages sort
    among themselves by key, which is irrelevant. Slicing the tail is
    static; per-actor sums/counts are first-order diffs. No index math ever
    touches a gather.
    """
    m, p = payload.shape
    n1 = n_actors + 1
    ok = valid & (dst >= 0) & (dst < n_actors)
    key = jnp.where(ok, dst, n_actors).astype(jnp.int32)

    key2 = jnp.concatenate([key * 2, jnp.arange(n1, dtype=jnp.int32) * 2 + 1])
    zcols = jnp.zeros((n1,), payload.dtype)
    cols = tuple(jnp.concatenate([jnp.where(ok, payload[:, i], 0), zcols])
                 for i in range(p))
    cnt = jnp.concatenate([ok.astype(jnp.int32), jnp.zeros((n1,), jnp.int32)])
    s1 = jax.lax.sort((key2,) + cols + (cnt,), num_keys=1)
    skey2, scols, scnt = s1[0], s1[1:-1], s1[-1]

    csums = tuple(jnp.cumsum(c) for c in scols)
    ccnt = jnp.cumsum(scnt)

    tag = skey2 & 1
    key_c = skey2 >> 1
    key3 = tag * (n_actors + 2) + key_c
    s2 = jax.lax.sort((key3,) + csums + (ccnt,), num_keys=1)
    mk = tuple(c[m:] for c in s2[1:-1])          # [n1] inclusive prefix, per col
    mc = s2[-1][m:]                               # [n1] inclusive count prefix

    def diffs(c):
        return jnp.concatenate([c[:1], c[1:] - c[:-1]])[:n_actors]

    sums = jnp.stack([diffs(c) for c in mk], axis=1).astype(payload.dtype)
    counts = diffs(mc).astype(jnp.int32)
    if need_max:
        maxs = _segmented_max_sorted(key_c[:],
                                     jnp.stack(scols, axis=1), tag, n_actors,
                                     payload.dtype, m)
    else:
        maxs = jnp.zeros((n_actors, p), payload.dtype)
    return Delivery(sum=sums, max=maxs, count=counts)


def _segmented_max_sorted(key_c, svals, tag, n_actors, dtype, m):
    """Per-segment max on the merged-sorted array via a log-step segmented
    max-scan (shift + select passes — contiguous moves, no gathers), read
    out at the marker rows by the same tag-compaction sort."""
    total = key_c.shape[0]
    neg_inf = _neg_inf(dtype)
    vals = jnp.where((tag == 0)[:, None], svals, neg_inf)
    seg = key_c
    acc = vals
    shift = 1
    while shift < total:
        shifted = jnp.concatenate([jnp.full((shift, acc.shape[1]), neg_inf,
                                            acc.dtype), acc[:-shift]])
        sseg = jnp.concatenate([jnp.full((shift,), -1, seg.dtype), seg[:-shift]])
        take = (sseg == seg)[:, None]
        acc = jnp.maximum(acc, jnp.where(take, shifted, neg_inf))
        shift *= 2
    key3 = tag * (n_actors + 2) + key_c
    cols = tuple(acc[:, i] for i in range(acc.shape[1]))
    s = jax.lax.sort((key3,) + cols, num_keys=1)
    mk = jnp.stack([c[m:] for c in s[1:]], axis=1)[:n_actors]
    return jnp.where(mk <= neg_inf, jnp.zeros_like(mk), mk).astype(dtype)


def _deliver_scatter(dst, payload, valid, n_actors: int, need_max: bool) -> Delivery:
    ok = valid & (dst >= 0) & (dst < n_actors)
    safe_dst = jnp.where(ok, dst, n_actors)
    okf = ok[:, None]
    sums = jax.ops.segment_sum(
        jnp.where(okf, payload, 0), safe_dst, num_segments=n_actors + 1)
    counts = jax.ops.segment_sum(
        ok.astype(jnp.int32), safe_dst, num_segments=n_actors + 1)
    counts = counts[:n_actors]
    if need_max:
        neg_inf = jnp.asarray(-jnp.inf if jnp.issubdtype(payload.dtype, jnp.floating)
                              else jnp.iinfo(payload.dtype).min, payload.dtype)
        maxs = jax.ops.segment_max(
            jnp.where(okf, payload, neg_inf), safe_dst, num_segments=n_actors + 1)
        maxs = jnp.where((counts > 0)[:, None], maxs[:n_actors], 0)
    else:
        maxs = jnp.zeros((n_actors, payload.shape[1]), payload.dtype)
    return Delivery(sum=sums[:n_actors], max=maxs, count=counts)


def _deliver_sorted_wide(dst, payload, valid, n_actors: int,
                         need_max: bool) -> Delivery:
    """Sort-by-recipient + cumsum-difference segment reduction, with every
    payload column riding the sort ("reference" backend)."""
    p = payload.shape[1]
    ok = valid & (dst >= 0) & (dst < n_actors)
    key = jnp.where(ok, dst, n_actors).astype(jnp.int32)
    cols = tuple(jnp.where(ok, payload[:, i], 0) for i in range(p))
    sorted_vals = jax.lax.sort((key,) + cols, num_keys=1)
    skey, scols = sorted_vals[0], sorted_vals[1:]
    spayload = jnp.stack(scols, axis=1)                    # [M, P] sorted by dst
    bounds = jnp.searchsorted(skey, jnp.arange(n_actors + 1, dtype=jnp.int32))
    start, end = bounds[:-1], bounds[1:]
    counts = (end - start).astype(jnp.int32)
    csum = jnp.cumsum(spayload.astype(jnp.float32 if spayload.dtype == jnp.float32
                                      else spayload.dtype), axis=0)
    csum = jnp.concatenate([jnp.zeros((1, p), csum.dtype), csum], axis=0)  # prefix w/ 0
    sums = (csum[end] - csum[start]).astype(payload.dtype)
    if need_max:
        neg_inf = jnp.asarray(-jnp.inf if jnp.issubdtype(payload.dtype, jnp.floating)
                              else jnp.iinfo(payload.dtype).min, payload.dtype)
        cmax = jax.lax.associative_scan(jnp.maximum,
                                        jnp.where((skey < n_actors)[:, None],
                                                  spayload, neg_inf), axis=0)
        # per-segment max needs a segmented scan; fall back to scatter for max
        maxs = jax.ops.segment_max(
            jnp.where((skey < n_actors)[:, None], spayload, neg_inf), skey,
            num_segments=n_actors + 1)[:n_actors]
        maxs = jnp.where((counts > 0)[:, None], maxs, 0)
    else:
        maxs = jnp.zeros((n_actors, p), payload.dtype)
    return Delivery(sum=sums, max=maxs, count=counts)


class SlotDelivery(NamedTuple):
    """Per-message mailbox delivery: each actor's first `slots` messages this
    step, in arrival order (per-sender FIFO), plus the EXACT commutative
    aggregation over all messages CONSUMED this step so reduce-kind behaviors
    coexisting in a slots-mode system lose nothing. With a spill region
    (spill_cap > 0), messages past the slot cap — and all mail addressed to
    suspended rows — are NOT consumed: they come back compacted in the spill_*
    outputs for redelivery next step (unbounded-mailbox semantics,
    dispatch/Mailbox.scala:647 UnboundedMailbox; suspension retention,
    actor/dungeon/FaultHandling.scala)."""

    types: jax.Array    # [N, S] int32 message-type tags (slot invalid -> 0)
    payload: jax.Array  # [N, S, P]
    valid: jax.Array    # [N, S] bool
    count: jax.Array    # [N] int32 messages consumed this step
    sum: jax.Array      # [N, P] segment-sum over consumed messages (exact)
    max: jax.Array      # [N, P] segment-max over consumed (zeros unless
                        #        need_max)
    dropped: jax.Array  # [] int32 REAL losses this step (spill overflow, or
                        #    all overflow when spill_cap == 0)
    spill_dst: jax.Array      # [spill_cap] int32 LOCAL rows (-1 = empty)
    spill_type: jax.Array     # [spill_cap]
    spill_payload: jax.Array  # [spill_cap, P]
    spill_valid: jax.Array    # [spill_cap] bool


def deliver_slots(dst: jax.Array, mtype: jax.Array, payload: jax.Array,
                  valid: jax.Array, n_actors: int, slots: int,
                  need_max: bool = False, spill_cap: int = 0,
                  slots_kind=None, suspended=None,
                  backend: str | None = None) -> SlotDelivery:
    """Ordered per-message delivery into per-actor mailbox slots.

    The TPU-native form of the reference's discrete-envelope mailbox
    (dispatch/Mailbox.scala:260-277 processMailbox dequeues one Envelope at a
    time in FIFO order): a stable sort on recipient id — with arrival index as
    the implicit tiebreak — lines messages up in (recipient, seq) order, and a
    rank-in-segment scatter places each actor's first `slots` messages into its
    mailbox rows. Per-sender FIFO holds because a sender's emissions occupy
    increasing flat inbox indices and the sort is stable (SURVEY.md §7 hard
    parts: ordering under scatter delivery).

    dst: [M] int32; mtype: [M] int32; payload: [M, P]; valid: [M] bool.
    Arrival order IS the index order of the inputs.

    spill_cap == 0 (bounded mailbox): messages beyond `slots` for one actor
    are dropped and counted (dispatch/Mailbox.scala:415-443 — surface via
    dead letters host-side); slots_kind/suspended are ignored.

    spill_cap > 0 (unbounded semantics): overflow for slots-kind recipients
    (slots_kind: [N] bool — reduce-kind recipients always consume everything
    via the aggregation) and ALL mail to suspended rows (suspended: [N] bool)
    is excluded from slots AND from the aggregation, and returned compacted
    in (recipient, seq) order in the spill_* outputs; the caller writes it at
    the FRONT of the next step's inbox, so redelivered mail sorts before any
    fresh emission and per-sender FIFO is preserved across spill generations.
    Only spill-region overflow is a real (counted) drop.

    `backend` picks the kernel implementation (see module docstring):
    rank-then-scatter ("xla"), the original wide-sort kernel
    ("reference"), the ring-mailbox prototype where its support matrix
    allows ("pallas", integer fields bit-identical / sums arrival-order),
    or the platform cost model (None/"auto"). Results are bit-identical
    either way.
    """
    impl = _backend_impl(backend, _resolve_platform(dst))
    if impl == "pallas":
        from akka_tpu.ops import pallas_mailbox  # deferred: optional dep
        if pallas_mailbox.supported(n_actors, payload.shape[1], slots=slots,
                                    spill_cap=spill_cap,
                                    slots_kind=slots_kind,
                                    suspended=suspended):
            return pallas_mailbox.deliver_slots_ring(
                dst, mtype, payload, valid, n_actors, slots, need_max)
        impl = "ranked"  # fallback matrix: docs/DELIVERY_KERNELS.md
    fn = _deliver_slots_ranked if impl == "ranked" else _deliver_slots_wide
    return fn(dst, mtype, payload, valid, n_actors, slots, need_max,
              spill_cap, slots_kind, suspended)


def _deliver_slots_ranked(dst, mtype, payload, valid, n_actors: int,
                          slots: int, need_max: bool, spill_cap: int,
                          slots_kind, suspended) -> SlotDelivery:
    """Rank-then-scatter slots delivery, entirely in the ORIGINAL row
    order: `stable_ranks` sorts narrow int32 keys only, and every slot
    index, spill position and aggregation offset is then closed-form
    from (rank, counts). One int32 scatter inverts the sort permutation;
    mailbox and spill rows are pure gathers off it, and the consumed
    aggregation pays one more narrow scatter — payload columns never
    ride a sort network. Phases mirror bench.py's attribution breakdown
    (key-sort / rank / place / reduce)."""
    m, p = payload.shape
    ok = valid & (dst >= 0) & (dst < n_actors)
    key = jnp.where(ok, dst, n_actors).astype(jnp.int32)
    cdst = jnp.clip(dst, 0, n_actors - 1)

    # --- key-sort + rank: arrival rank within recipient, per-key counts
    rank, counts_full = stable_ranks(key, n_actors, _resolve_platform(dst))
    counts = counts_full[:n_actors]

    incl = jnp.cumsum(counts_full)                          # [n+1]
    excl = jnp.concatenate([jnp.zeros((1,), jnp.int32), incl[:-1]])
    inv = excl[key] + rank

    if spill_cap > 0:
        susp_n = (suspended if suspended is not None
                  else jnp.zeros((n_actors,), jnp.bool_))
        kind_n = (slots_kind if slots_kind is not None
                  else jnp.ones((n_actors,), jnp.bool_))
        kind_m = (slots_kind[cdst] if slots_kind is not None
                  else jnp.ones((m,), jnp.bool_))
        susp_m = (suspended[cdst] if suspended is not None
                  else jnp.zeros((m,), jnp.bool_))
        spill = ok & (susp_m | (kind_m & (rank >= slots)))
        consumed = ok & ~spill
    else:
        spill = jnp.zeros((m,), jnp.bool_)
        consumed = ok

    # --- place: ONE narrow int32 scatter inverts the sort permutation
    # (inv is a bijection on [0, M)); every mailbox row and spill row is
    # then a pure gather at a closed-form sorted position, so payload
    # columns are touched exactly once
    s2o = jnp.zeros((m,), jnp.int32).at[inv].set(
        jnp.arange(m, dtype=jnp.int32), unique_indices=True,
        mode="promise_in_bounds")
    kk = jnp.arange(n_actors * slots, dtype=jnp.int32) // slots
    jj = jnp.arange(n_actors * slots, dtype=jnp.int32) % slots
    buf_v = jj < counts[kk]
    if spill_cap > 0:
        buf_v &= ~susp_n[kk]
    row = s2o[jnp.minimum(excl[kk] + jj, m - 1)]
    buf_t = jnp.where(buf_v, mtype[row], 0)
    buf_p = jnp.where(buf_v[:, None], payload[row], 0)

    # spill compaction: the wide kernel assigns spill positions by a
    # cumsum over the (recipient, seq)-sorted spill flags; that same
    # position is closed-form here — per-key spill counts (suspended
    # rows spill everything, slots-kind rows spill past `slots`) prefix-
    # summed across keys invert back to (key, within-rank) per spill
    # slot with one [spill_cap] binary search, no second scatter
    if spill_cap > 0:
        spc = jnp.where(susp_n, counts,
                        jnp.where(kind_n,
                                  jnp.maximum(counts - slots, 0), 0))
        sp_excl = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                   jnp.cumsum(spc)])          # [n+1]
        ss = jnp.arange(spill_cap, dtype=jnp.int32)
        k_s = (jnp.searchsorted(sp_excl, ss, side="right").astype(jnp.int32)
               - 1)
        k_c = jnp.minimum(k_s, n_actors - 1)
        r_s = (ss - sp_excl[k_c]
               + jnp.where(susp_n[k_c], 0, slots))
        srow = s2o[jnp.minimum(excl[k_c] + r_s, m - 1)]
        sp_v = ss < jnp.minimum(sp_excl[n_actors], spill_cap)
        sp_dst = jnp.where(sp_v, k_c, -1)
        sp_type = jnp.where(sp_v, mtype[srow], 0)
        sp_pl = jnp.where(sp_v[:, None], payload[srow], 0)
        dropped = jnp.maximum(sp_excl[n_actors] - spill_cap, 0)
        spill_out = (sp_dst, sp_type, sp_pl, sp_v)
    else:
        spc = None
        in_cap = ok & (rank < slots)
        dropped = jnp.sum((ok & ~in_cap).astype(jnp.int32))
        spill_out = (jnp.full((0,), -1, jnp.int32),
                     jnp.zeros((0,), jnp.int32),
                     jnp.zeros((0, p), payload.dtype),
                     jnp.zeros((0,), jnp.bool_))

    # --- reduce: exact consumed aggregation. _merged_layout_sums
    # reproduces the wide kernel's marker-interleaved cumsum bit-for-bit
    # (one scatter instead of two wide sorts); consumed counts are
    # integer-exact differences
    sums = _merged_layout_sums(inv, key,
                               incl, jnp.where(consumed[:, None], payload, 0),
                               n_actors)
    a_counts = counts - spc if spill_cap > 0 else counts
    if need_max:
        # non-consumed live rows contribute 0 exactly like the wide
        # kernel's masked columns; the -inf sentinel only marks segments
        # with no rows at all
        neg_inf = _neg_inf(payload.dtype)
        vals = jnp.where(consumed[:, None], payload,
                         jnp.zeros((), payload.dtype))
        vals = jnp.where(ok[:, None], vals, neg_inf)
        maxs = jax.ops.segment_max(vals, key,
                                   num_segments=n_actors + 1)[:n_actors]
        maxs = jnp.where(maxs <= neg_inf, jnp.zeros_like(maxs),
                         maxs).astype(payload.dtype)
    else:
        maxs = jnp.zeros((n_actors, p), payload.dtype)

    return SlotDelivery(
        types=buf_t.reshape(n_actors, slots),
        payload=buf_p.reshape(n_actors, slots, p),
        valid=buf_v.reshape(n_actors, slots),
        count=a_counts,
        sum=sums,
        max=maxs,
        dropped=dropped,
        spill_dst=spill_out[0],
        spill_type=spill_out[1],
        spill_payload=spill_out[2],
        spill_valid=spill_out[3],
    )


def _deliver_slots_wide(dst, mtype, payload, valid, n_actors: int,
                        slots: int, need_max: bool, spill_cap: int,
                        slots_kind, suspended) -> SlotDelivery:
    """The original wide-sort slots kernel ("reference" backend): every
    payload column rides the (P+4)-operand sort, and the aggregation pays
    two more wide marker sorts. Kept bit-for-bit for parity testing and
    for TPU, where its numbers were actually measured."""
    m, p = payload.shape
    ok = valid & (dst >= 0) & (dst < n_actors)
    key = jnp.where(ok, dst, n_actors).astype(jnp.int32)
    cdst = jnp.clip(dst, 0, n_actors - 1)
    if spill_cap > 0:
        kind_m = (slots_kind[cdst] if slots_kind is not None
                  else jnp.ones((m,), jnp.bool_))
        susp_m = (suspended[cdst] if suspended is not None
                  else jnp.zeros((m,), jnp.bool_))
        flags = susp_m.astype(jnp.int32) * 2 + kind_m.astype(jnp.int32)
    else:
        flags = jnp.zeros((m,), jnp.int32)

    # ONE keyed sort carries every column: (recipient, arrival-index) as a
    # two-key sort IS the stable (recipient, seq) order, and payload/type
    # ride the sort network instead of being gathered afterwards (argsort +
    # x[order] is ~8x slower on TPU — gathers serialize, sorts vectorize)
    iota = jnp.arange(m, dtype=jnp.int32)
    fcols = tuple(payload[:, i] for i in range(p))
    s = jax.lax.sort((key, iota, mtype, flags) + fcols, num_keys=2)
    skey, stype, sflags, sp = s[0], s[2], s[3], jnp.stack(s[4:], axis=1)

    # rank within segment, gather-free: head flags on the sorted keys, then
    # a log-depth cummax of (head ? position : -1) gives each message its
    # segment-start position (keys are monotone, so the equality check with
    # the 2^k-shifted position is exact)
    head = jnp.concatenate([jnp.ones((1,), jnp.bool_), skey[1:] != skey[:-1]])
    start = jax.lax.cummax(jnp.where(head, iota, -1))
    rank = iota - start
    live = skey < n_actors
    if spill_cap > 0:
        susp_s = sflags >= 2
        kind_s = (sflags & 1).astype(jnp.bool_)
        spill_m = live & (susp_s | (kind_s & (rank >= slots)))
        in_cap = live & ~susp_s & (rank < slots)
        consumed = live & ~spill_m
    else:
        spill_m = jnp.zeros((m,), jnp.bool_)
        in_cap = live & (rank < slots)
        consumed = live
    slot = jnp.where(in_cap, skey * slots + rank, n_actors * slots)

    buf_t = jnp.zeros((n_actors * slots + 1,), jnp.int32)
    buf_p = jnp.zeros((n_actors * slots + 1, p), payload.dtype)
    buf_v = jnp.zeros((n_actors * slots + 1,), jnp.bool_)
    buf_t = buf_t.at[slot].set(jnp.where(in_cap, stype, 0))
    buf_p = buf_p.at[slot].set(jnp.where(in_cap[:, None], sp, 0))
    buf_v = buf_v.at[slot].set(in_cap)

    # spill compaction: cumsum positions preserve the (recipient, seq) sort
    # order, so a spilled burst re-enters next step still in FIFO order
    if spill_cap > 0:
        pos = jnp.cumsum(spill_m.astype(jnp.int32)) - 1
        placed = spill_m & (pos < spill_cap)
        sslot = jnp.where(placed, pos, spill_cap)
        sp_dst = jnp.full((spill_cap + 1,), -1, jnp.int32
                          ).at[sslot].set(jnp.where(placed, skey, -1))
        sp_type = jnp.zeros((spill_cap + 1,), jnp.int32
                            ).at[sslot].set(jnp.where(placed, stype, 0))
        sp_pl = jnp.zeros((spill_cap + 1, p), payload.dtype
                          ).at[sslot].set(jnp.where(placed[:, None], sp, 0))
        sp_v = jnp.zeros((spill_cap + 1,), jnp.bool_).at[sslot].set(placed)
        dropped = jnp.sum((spill_m & ~placed).astype(jnp.int32))
        spill_out = (sp_dst[:-1], sp_type[:-1], sp_pl[:-1], sp_v[:-1])
    else:
        dropped = jnp.sum((live & ~in_cap).astype(jnp.int32))
        spill_out = (jnp.full((0,), -1, jnp.int32), jnp.zeros((0,), jnp.int32),
                     jnp.zeros((0, p), payload.dtype), jnp.zeros((0,), jnp.bool_))

    # exact consumed-message aggregation alongside the slots, via the same
    # merged-marker compaction as _deliver_merge (gather-free): markers
    # sort after their segment, cumsums are read back actor-ordered
    key2 = jnp.concatenate([skey * 2,
                            jnp.arange(n_actors + 1, dtype=jnp.int32) * 2 + 1])
    zc = jnp.zeros((n_actors + 1,), payload.dtype)
    sp_masked = jnp.where(consumed[:, None], sp, 0)
    mcols = tuple(jnp.concatenate([sp_masked[:, i], zc]) for i in range(p))
    mcnt = jnp.concatenate([consumed.astype(jnp.int32),
                            jnp.zeros((n_actors + 1,), jnp.int32)])
    s1 = jax.lax.sort((key2,) + mcols + (mcnt,), num_keys=1)
    csums = tuple(jnp.cumsum(c) for c in s1[1:-1])
    ccnt = jnp.cumsum(s1[-1])
    tag = s1[0] & 1
    key3 = tag * (n_actors + 2) + (s1[0] >> 1)
    s2 = jax.lax.sort((key3,) + csums + (ccnt,), num_keys=1)

    def diffs(c):
        t = c[m:]
        return jnp.concatenate([t[:1], t[1:] - t[:-1]])[:n_actors]

    sums = jnp.stack([diffs(c) for c in s2[1:-1]], axis=1).astype(payload.dtype)
    counts = diffs(s2[-1]).astype(jnp.int32)
    if need_max:
        maxs = _segmented_max_sorted(key3 % (n_actors + 2),
                                     jnp.stack(s1[1:-1], axis=1), tag,
                                     n_actors, payload.dtype, m)
    else:
        maxs = jnp.zeros((n_actors, p), payload.dtype)

    return SlotDelivery(
        types=buf_t[:-1].reshape(n_actors, slots),
        payload=buf_p[:-1].reshape(n_actors, slots, p),
        valid=buf_v[:-1].reshape(n_actors, slots),
        count=counts,
        sum=sums,
        max=maxs,
        dropped=dropped,
        spill_dst=spill_out[0],
        spill_type=spill_out[1],
        spill_payload=spill_out[2],
        spill_valid=spill_out[3],
    )


class StaticTopology:
    """Precompiled communication graph: delivery with NO runtime sort/scatter.

    When the actor graph is fixed (ring, trees, fan-in, router pools — the
    common case, and exactly what maps well to TPUs), the routing can be
    compiled at build time. `from_dst_table` pattern-matches the graph the way
    a communication compiler pattern-matches collectives:

    - "shift": dst[i] = (i+c) mod N  ->  delivery is jnp.roll (the on-chip
      analogue of lax.ppermute; ~memory-copy speed)
    - "mod":   dst[i] = i mod C      ->  reshape [G, C] + sum over G (the
      reduction-tree shape of a fan-in; full-bandwidth reduce)
    - "block": dst[i] = i // G       ->  reshape [C, G] + sum over G
    - "dense": uniform small fan-in  ->  gather inverse_edges [N, F], sum F
    - "csr":   anything else         ->  static sort permutation + cumsum
      differences at static segment boundaries

    Message VALUES and validity stay fully dynamic — only the wiring is static.
    Kind and scalar params are trace-time constants; only dense/csr carry
    device arrays (passed as runtime args so the HLO stays small).
    """

    def __init__(self, kind: str, n: int, k: int, shift: int = 0,
                 mod: int = 0, block: int = 0, inverse_edges=None,
                 perm=None, bounds=None):
        self.kind = kind
        self.n = n
        self.k = k
        self.shift = shift
        self.mod = mod
        self.block = block
        self.inverse_edges = inverse_edges
        self.perm = perm
        self.bounds = bounds

    def runtime_arrays(self) -> tuple:
        """Device arrays to pass through jit as arguments (pytree)."""
        if self.kind == "dense":
            return (self.inverse_edges,)
        if self.kind == "csr":
            return (self.perm, self.bounds)
        return ()

    @staticmethod
    def from_dst_table(dst_table, dense_max_fan_in: int = 4) -> "StaticTopology":
        """dst_table: [N, K] int — static destination of each actor's k-th
        out-slot; -1 = unused slot (runtime valid flags gate anyway).
        Host-side build (numpy)."""
        import numpy as np
        dt = np.asarray(dst_table, dtype=np.int64)
        n, k = dt.shape
        flat_dst = dt.reshape(-1)
        m = n * k
        slots = np.arange(m, dtype=np.int64)
        okm = flat_dst >= 0

        if k == 1 and okm.any():
            i_ok = slots[okm]
            d_ok = flat_dst[okm]
            # shift: dst = (i + c) mod n, all slots emitting
            if okm.all():
                c = int((d_ok[0] - i_ok[0]) % n)
                if ((i_ok + c) % n == d_ok).all():
                    return StaticTopology("shift", n, k, shift=c)
            # mod: dst = i mod C (C = number of distinct targets span)
            cands = np.unique(d_ok)
            c_mod = int(cands.max()) + 1
            if c_mod >= 1 and m % c_mod == 0 and (i_ok % c_mod == d_ok).all():
                return StaticTopology("mod", n, k, mod=c_mod)
            # block: dst = i // G
            if len(cands) > 0:
                g = m // (int(cands.max()) + 1)
                if g > 0 and m % g == 0 and (i_ok // g == d_ok).all():
                    return StaticTopology("block", n, k, block=g)

        order = np.argsort(flat_dst[okm], kind="stable")
        tgt = flat_dst[okm][order]
        src = slots[okm][order]
        counts = np.bincount(tgt, minlength=n) if tgt.size else np.zeros(n, np.int64)
        f = max(int(counts.max()) if counts.size else 1, 1)
        if f <= dense_max_fan_in:
            inv = np.full((n, f), -1, dtype=np.int32)
            starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
            pos = np.arange(tgt.shape[0]) - starts[tgt]
            inv[tgt, pos] = src.astype(np.int32)
            return StaticTopology("dense", n, k, inverse_edges=jnp.asarray(inv))
        perm = np.concatenate([src, slots[~okm]]).astype(np.int32)
        bounds = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
        return StaticTopology("csr", n, k, perm=jnp.asarray(perm),
                              bounds=jnp.asarray(bounds))


def deliver_static(topo: StaticTopology, arrays: tuple, payload: jax.Array,
                   valid: jax.Array, need_max: bool = False) -> Delivery:
    """Delivery over a static topology; `arrays` = topo.runtime_arrays()
    passed through jit (payload: [N*K, P] slot-indexed emissions)."""
    p = payload.shape[1]
    n = topo.n

    if topo.kind == "shift":
        in_pl = jnp.roll(payload, topo.shift, axis=0)
        in_ok = jnp.roll(valid, topo.shift, axis=0)
        sums = jnp.where(in_ok[:, None], in_pl, 0)
        counts = in_ok.astype(jnp.int32)
        maxs = sums if need_max else jnp.zeros_like(sums)
        return Delivery(sum=sums, max=maxs, count=counts)

    if topo.kind in ("mod", "block"):
        if topo.kind == "mod":
            c = topo.mod
            g = payload.shape[0] // c
            pl3 = payload.reshape(g, c, p)          # sum over leading groups
            ok2 = valid.reshape(g, c)
            axis = 0
        else:
            g = topo.block
            c = payload.shape[0] // g
            pl3 = payload.reshape(c, g, p)
            ok2 = valid.reshape(c, g)
            axis = 1
        okf = jnp.expand_dims(ok2, -1)
        sums_c = jnp.sum(jnp.where(okf, pl3, 0), axis=axis)      # [C, P]
        counts_c = jnp.sum(ok2.astype(jnp.int32), axis=axis)     # [C]
        # targets are ids [0, C): place into the first C rows
        c_eff = min(c, n)
        sums = jnp.zeros((n, p), payload.dtype).at[:c_eff].set(sums_c[:c_eff])
        counts = jnp.zeros((n,), jnp.int32).at[:c_eff].set(counts_c[:c_eff])
        if need_max:
            neg_inf = _neg_inf(payload.dtype)
            maxs_c = jnp.max(jnp.where(okf, pl3, neg_inf), axis=axis)
            maxs = jnp.zeros((n, p), payload.dtype).at[:c_eff].set(
                jnp.where((counts_c > 0)[:, None], maxs_c, 0)[:c_eff])
        else:
            maxs = jnp.zeros((n, p), payload.dtype)
        return Delivery(sum=sums, max=maxs, count=counts)

    if topo.kind == "dense":
        (inv,) = arrays                          # [N, F] small F
        safe = jnp.maximum(inv, 0)
        ok = (inv >= 0) & valid[safe]            # [N, F]
        gathered = payload[safe]                 # [N, F, P]
        okf = ok[..., None]
        sums = jnp.sum(jnp.where(okf, gathered, 0), axis=1)
        counts = jnp.sum(ok.astype(jnp.int32), axis=1)
        if need_max:
            neg_inf = _neg_inf(payload.dtype)
            maxs = jnp.max(jnp.where(okf, gathered, neg_inf), axis=1)
            maxs = jnp.where((counts > 0)[:, None], maxs, 0)
        else:
            maxs = jnp.zeros(sums.shape, payload.dtype)
        return Delivery(sum=sums, max=maxs, count=counts)

    # csr: static permutation + cumsum differences
    perm, bounds = arrays
    sp = payload[perm]                           # [M, P] dest-sorted (static)
    sv = valid[perm]
    sp = jnp.where(sv[:, None], sp, 0)
    csum = jnp.concatenate([jnp.zeros((1, p), sp.dtype),
                            jnp.cumsum(sp, axis=0)], axis=0)
    sums = csum[bounds[1:]] - csum[bounds[:-1]]
    cvalid = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                              jnp.cumsum(sv.astype(jnp.int32))])
    counts = cvalid[bounds[1:]] - cvalid[bounds[:-1]]
    if need_max:
        seg_ids = jnp.zeros((sp.shape[0],), jnp.int32).at[bounds[1:-1]].add(1)
        seg_ids = jnp.cumsum(seg_ids)
        neg_inf = _neg_inf(payload.dtype)
        maxs = jax.ops.segment_max(jnp.where(sv[:, None], sp, neg_inf), seg_ids,
                                   num_segments=n)
        maxs = jnp.where((counts > 0)[:, None], maxs, 0)
    else:
        maxs = jnp.zeros(sums.shape, payload.dtype)
    return Delivery(sum=sums, max=maxs, count=counts)


def _neg_inf(dtype):
    return jnp.asarray(-jnp.inf if jnp.issubdtype(dtype, jnp.floating)
                       else jnp.iinfo(dtype).min, dtype)


def exchange_uses_ranked(platform: str, backend: str | None = None) -> bool:
    """Kernel choice for sharded.py's exchange bucketing (rank-in-group +
    scatter into the [D, C] all_to_all buffer): same seam and the same
    measured tradeoff as the slots kernel — ranked on CPU, wide on TPU
    until on-chip attribution lands. The exchange's shard-id domain is
    tiny, so the ranked path's `stable_ranks` resolves to a single
    counting pass there (no sort at all); the pallas backend has no
    exchange kernel and rides the ranked one."""
    return _backend_impl(backend, platform) in ("ranked", "pallas")


def delivery_attribution(m: int, n_actors: int, p: int = 4, slots: int = 2,
                         repeats: int = 3, seed: int = 0) -> dict:
    """Measure the per-phase cost of the rank-then-scatter slots kernel at
    one shape on the current default backend; the numbers feed bench.py's
    modes config and docs/DELIVERY_KERNELS.md so kernel choices are
    attributed, not asserted.

    Phases (exactly the blocks of `_deliver_slots_ranked`):
      key_sort_ms — the ONE single-operand lax.sort over packed
                    (key, arrival-block) int32 keys
      rank_ms     — binary-search cross-block offsets + within-block
                    equality triangle + per-key counts
      place_ms    — one inverse-permutation scatter + mailbox gathers
                    at closed-form slot positions
      reduce_ms   — marker-interleaved layout scatter + cumsum +
                    boundary reads (the bit-exact consumed aggregation)
    plus wide_sort_ms, the reference kernel's (P+4)-operand sort at the
    same shape — the single number that motivates the whole scheme.

    The counting-sort family adds:
      count_rank_ms — the full `counting_ranks` pass (rank + counts,
                      no sort network) at this shape
      auto_rank_ms  — whatever `stable_ranks` auto-picks here (the
                      strategy name lands in rank_strategy)
      slots_phases  — the slots-path breakdown the ISSUE-6 satellite
                      asks for: rank vs per-slot scatter (place) vs
                      spill/redeliver compaction vs exact reduce, plus
                      the end-to-end bounded step (step_ms) and the
                      end-to-end spill-generation step (spill_step_ms).

    Each phase is jitted standalone and timed best-of-`repeats` with
    block_until_ready; dict values are milliseconds.
    """
    import time as _time

    import numpy as np

    rng = np.random.default_rng(seed)
    dst = jnp.asarray(rng.integers(0, n_actors, size=m), jnp.int32)
    mtype = jnp.asarray(rng.integers(0, 4, size=m), jnp.int32)
    payload = jnp.asarray(rng.standard_normal((m, p)), jnp.float32)
    key = dst
    iota = jnp.arange(m, dtype=jnp.int32)

    def key_sort(key):
        _, packed = _pack_keys(key, n_actors)
        return jax.lax.sort(packed)

    def rank_phase(psorted, key):
        kp, packed = _pack_keys(key, n_actors)
        rank, counts = _ranks_from_packed(psorted, packed, kp, n_actors)
        return rank[:m], counts

    def place_phase(rank, counts_full, key, mtype, payload):
        incl = jnp.cumsum(counts_full)
        excl = jnp.concatenate([jnp.zeros((1,), jnp.int32), incl[:-1]])
        inv = excl[key] + rank
        s2o = jnp.zeros((m,), jnp.int32).at[inv].set(
            jnp.arange(m, dtype=jnp.int32), unique_indices=True,
            mode="promise_in_bounds")
        kk = jnp.arange(n_actors * slots, dtype=jnp.int32) // slots
        jj = jnp.arange(n_actors * slots, dtype=jnp.int32) % slots
        buf_v = jj < counts_full[kk]
        row = s2o[jnp.minimum(excl[kk] + jj, m - 1)]
        return (jnp.where(buf_v, mtype[row], 0),
                jnp.where(buf_v[:, None], payload[row], 0), buf_v)

    def reduce_phase(rank, counts_full, key, payload):
        incl = jnp.cumsum(counts_full)
        excl = jnp.concatenate([jnp.zeros((1,), jnp.int32), incl[:-1]])
        inv = excl[key] + rank
        consumed = key < n_actors
        sums = _merged_layout_sums(
            inv, key, incl, jnp.where(consumed[:, None], payload, 0),
            n_actors)
        return sums, counts_full[:n_actors]

    def wide_sort(key, iota, mtype, payload):
        fcols = tuple(payload[:, i] for i in range(payload.shape[1]))
        flags = jnp.zeros_like(key)
        return jax.lax.sort((key, iota, mtype, flags) + fcols, num_keys=2)

    def count_rank(key):
        return counting_ranks(key, n_actors)

    def auto_rank(key):
        return stable_ranks(key, n_actors)

    spill_cap = max(m // 4, 8)

    def spill_phase(rank, counts_full, key, mtype, payload):
        # the spill/redeliver compaction block of _deliver_slots_ranked
        # (includes the shared inverse-permutation scatter it hangs off)
        incl = jnp.cumsum(counts_full)
        excl = jnp.concatenate([jnp.zeros((1,), jnp.int32), incl[:-1]])
        inv = excl[key] + rank
        s2o = jnp.zeros((m,), jnp.int32).at[inv].set(
            jnp.arange(m, dtype=jnp.int32), unique_indices=True,
            mode="promise_in_bounds")
        counts = counts_full[:n_actors]
        spc = jnp.maximum(counts - slots, 0)
        sp_excl = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                   jnp.cumsum(spc)])
        ss = jnp.arange(spill_cap, dtype=jnp.int32)
        k_s = (jnp.searchsorted(sp_excl, ss, side="right").astype(jnp.int32)
               - 1)
        k_c = jnp.minimum(k_s, n_actors - 1)
        r_s = ss - sp_excl[k_c] + slots
        srow = s2o[jnp.minimum(excl[k_c] + r_s, m - 1)]
        sp_v = ss < jnp.minimum(sp_excl[n_actors], spill_cap)
        return (jnp.where(sp_v, k_c, -1), jnp.where(sp_v, mtype[srow], 0),
                jnp.where(sp_v[:, None], payload[srow], 0))

    ones_v = jnp.ones((m,), jnp.bool_)

    def slots_step(dst, mtype, payload):
        return deliver_slots(dst, mtype, payload, ones_v, n_actors, slots)

    def spill_step(dst, mtype, payload):
        return deliver_slots(dst, mtype, payload, ones_v, n_actors, slots,
                             spill_cap=spill_cap)

    def _best_ms(fn, *args):
        jfn = jax.jit(fn)
        jax.block_until_ready(jfn(*args))  # compile outside the clock
        best = float("inf")
        for _ in range(max(repeats, 1)):
            t0 = _time.perf_counter()
            jax.block_until_ready(jfn(*args))
            best = min(best, _time.perf_counter() - t0)
        return best * 1e3

    psorted = jax.jit(key_sort)(key)
    rank, counts_full = jax.jit(rank_phase)(psorted, key)
    out = {
        "platform": jax.default_backend(),
        "m": int(m), "n": int(n_actors), "p": int(p), "slots": int(slots),
        "key_sort_ms": _best_ms(key_sort, key),
        "rank_ms": _best_ms(rank_phase, psorted, key),
        "place_ms": _best_ms(place_phase, rank, counts_full, key, mtype,
                             payload),
        "reduce_ms": _best_ms(reduce_phase, rank, counts_full, key, payload),
        "wide_sort_ms": _best_ms(wide_sort, key, iota, mtype, payload),
        "count_rank_ms": _best_ms(count_rank, key),
        "auto_rank_ms": _best_ms(auto_rank, key),
        "rank_strategy": _auto_rank_strategy(m, n_actors,
                                             jax.default_backend()),
    }
    out["total_ms"] = round(out["key_sort_ms"] + out["rank_ms"]
                            + out["place_ms"] + out["reduce_ms"], 4)
    out["slots_phases"] = {
        "strategy": out["rank_strategy"],
        "spill_cap": int(spill_cap),
        "rank_ms": round(out["auto_rank_ms"], 4),
        "place_ms": round(out["place_ms"], 4),
        "spill_ms": round(_best_ms(spill_phase, rank, counts_full, key,
                                   mtype, payload), 4),
        "reduce_ms": round(out["reduce_ms"], 4),
        "step_ms": round(_best_ms(slots_step, dst, mtype, payload), 4),
        "spill_step_ms": round(_best_ms(spill_step, dst, mtype, payload), 4),
    }
    for k in ("key_sort_ms", "rank_ms", "place_ms", "reduce_ms",
              "wide_sort_ms", "count_rank_ms", "auto_rank_ms"):
        out[k] = round(out[k], 4)
    return out


def route_one_hop(dst: jax.Array, perm_table: jax.Array) -> jax.Array:
    """Rewrite destinations through a routing table (router logics as index
    maps — SURVEY.md §2.11: RoundRobin = iota mod n, ConsistentHash = hash
    tensor)."""
    return perm_table[dst]


def compact_messages(dst: jax.Array, payload: jax.Array, valid: jax.Array,
                     capacity: int) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Stable-compact valid messages to the front of a fixed-size buffer.

    Returns (dst, payload, valid, dropped_count). Stable order preserves
    per-sender FIFO (SURVEY.md §7 hard parts: ordering under scatter delivery).
    """
    m = dst.shape[0]
    # positions of valid messages in stable order
    order = jnp.argsort(jnp.where(valid, 0, 1), stable=True)
    dst_s = dst[order]
    payload_s = payload[order]
    valid_s = valid[order]
    n_valid = jnp.sum(valid.astype(jnp.int32))
    if capacity >= m:
        pad = capacity - m
        return (jnp.pad(dst_s, (0, pad), constant_values=-1),
                jnp.pad(payload_s, ((0, pad), (0, 0))),
                jnp.pad(valid_s, (0, pad)),
                jnp.asarray(0, jnp.int32))
    dropped = jnp.maximum(n_valid - capacity, 0)
    return dst_s[:capacity], payload_s[:capacity], valid_s[:capacity], dropped
