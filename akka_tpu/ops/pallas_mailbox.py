"""Pallas ring-mailbox delivery prototype (SURVEY §2.10 native component).

The reference runtime gets per-sender FIFO for free: an MPSC linked queue
(AbstractNodeQueue.java) makes enqueue order THE mailbox order. Every XLA
kernel family in `segment.py` re-derives that order per step with a rank
pass (sort or counting) because XLA has no per-recipient mutable cursor.
Pallas does: a TPU grid executes sequentially, so a kernel that walks the
message stream in arrival-block order and bumps a per-recipient cursor in
on-chip memory IS the MPSC enqueue loop — recipient-id -> inbox-ring slot,
cursor bump, no global sort and no rank pass at all.

Two entry points, both registered behind the `delivery_backend` seam in
`segment.py` (backend="pallas" / deliver(mode="pallas")):

- `deliver_slots_ring`: the bounded mailbox (spill_cap == 0) semantics of
  `deliver_slots` — each recipient's first `slots` messages in arrival
  order land in its ring, later ones are counted as dropped, and the
  consumed aggregation accumulates in strict arrival order.
- `deliver_reduce`: the `Delivery` (sums/counts) reduction of `deliver`.

Validation and fallback matrix (docs/DELIVERY_KERNELS.md): the kernel runs
in interpret mode everywhere except a real TPU backend with
AKKA_TPU_PALLAS_COMPILE=1 (it is a prototype: the inner loop is scalar, so
compiled-TPU performance work — vectorized two-phase enqueue, SMEM
cursors — is deliberately out of scope). `supported()` gates every call:
unsupported options (spill generations, slots_kind/suspended masks) or a
missing Pallas import fall back to the ranked XLA kernels in the caller.
Integer outputs (slots, types, valid, counts, dropped) are bit-identical
to the ranked/wide kernels; float sums accumulate in arrival order, which
the modes-agree oracle checks with allclose (association differs from the
cumsum-based kernels).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from akka_tpu.ops.segment import Delivery, SlotDelivery, _neg_inf

try:  # Pallas ships with jax, but keep the runtime importable without it
    from jax.experimental import pallas as pl
    HAVE_PALLAS = True
except Exception:  # noqa: BLE001 — any import failure means "no pallas"
    pl = None
    HAVE_PALLAS = False

# Arrival-block size: messages per grid step. The grid dimension is the
# arrival axis, and TPU grids execute sequentially, so cursor state in the
# revisited output blocks carries FIFO order across steps for free.
_BLOCK_M = 256

# Accumulator state (rings + cursors + sums) must fit on-chip when
# compiled for a real TPU core; interpret mode has no such limit but the
# same cap keeps pathological shapes off the scalar loop.
_STATE_BUDGET_BYTES = 1 << 23


def _interpret() -> bool:
    """Interpret everywhere except a real TPU with the opt-in flag — the
    scalar inner loop is prototype-grade, not production TPU code."""
    return not (jax.default_backend() == "tpu"
                and os.environ.get("AKKA_TPU_PALLAS_COMPILE") == "1")


def supported(n_actors: int, p: int, slots: int = 1, spill_cap: int = 0,
              slots_kind=None, suspended=None) -> bool:
    """Static support matrix for the prototype; callers fall back to the
    ranked kernels when False. Spill generations and per-recipient
    kind/suspension masks are redelivery machinery the ring kernel does
    not model (yet)."""
    if not HAVE_PALLAS:
        return False
    if spill_cap > 0 or slots_kind is not None or suspended is not None:
        return False
    if n_actors < 1 or slots < 1 or p < 1:
        return False
    state = 4 * (n_actors * slots * (p + 2) + n_actors * (p + 1) + 1)
    return state <= _STATE_BUDGET_BYTES


def _ring_kernel(n_actors: int, slots: int, bm: int, with_slots: bool):
    """Kernel body: one arrival block per grid step, scalar enqueue loop.
    Output refs double as state — counts IS the per-recipient ring
    cursor, initialised on the first grid step and carried across steps
    because every step maps the same (whole-array) output block."""

    def kernel(dst_ref, t_ref, p_ref, v_ref, *out_refs):
        if with_slots:
            (buf_t_ref, buf_p_ref, buf_v_ref, counts_ref, sums_ref,
             drop_ref) = out_refs
        else:
            counts_ref, sums_ref, drop_ref = out_refs

        @pl.when(pl.program_id(0) == 0)
        def _init():  # first arrival block: empty mailboxes
            counts_ref[...] = jnp.zeros_like(counts_ref)
            sums_ref[...] = jnp.zeros_like(sums_ref)
            drop_ref[...] = jnp.zeros_like(drop_ref)
            if with_slots:
                buf_t_ref[...] = jnp.zeros_like(buf_t_ref)
                buf_p_ref[...] = jnp.zeros_like(buf_p_ref)
                buf_v_ref[...] = jnp.zeros_like(buf_v_ref)

        def enqueue(j, carry):
            d = dst_ref[pl.ds(j, 1)]                            # (1,)
            ok = (v_ref[pl.ds(j, 1)] != 0) & (d >= 0) & (d < n_actors)
            dc = jnp.clip(d[0], 0, n_actors - 1)
            cur = counts_ref[pl.ds(dc, 1)]                      # ring cursor
            counts_ref[pl.ds(dc, 1)] = cur + ok.astype(jnp.int32)
            pay = p_ref[pl.ds(j, 1), :]                         # (1, P)
            acc = sums_ref[pl.ds(dc, 1), :]
            sums_ref[pl.ds(dc, 1), :] = acc + jnp.where(ok[:, None], pay, 0)
            if with_slots:
                in_ring = ok & (cur < slots)
                slot = dc * slots + jnp.minimum(cur[0], slots - 1)
                buf_t_ref[pl.ds(slot, 1)] = jnp.where(
                    in_ring, t_ref[pl.ds(j, 1)], buf_t_ref[pl.ds(slot, 1)])
                buf_p_ref[pl.ds(slot, 1), :] = jnp.where(
                    in_ring[:, None], pay, buf_p_ref[pl.ds(slot, 1), :])
                buf_v_ref[pl.ds(slot, 1)] = jnp.where(
                    in_ring, 1, buf_v_ref[pl.ds(slot, 1)])
                drop_ref[...] = drop_ref[...] + jnp.sum(
                    (ok & (cur >= slots)).astype(jnp.int32))
            return carry

        jax.lax.fori_loop(0, bm, enqueue, 0)

    return kernel


@functools.partial(jax.jit, static_argnames=("n_actors", "slots",
                                             "with_slots"))
def _run(dst, mtype, payload, valid, n_actors: int, slots: int,
         with_slots: bool):
    m, p = payload.shape
    bm = min(_BLOCK_M, max(m, 1))
    mp = -(-max(m, 1) // bm) * bm
    pad = mp - m
    if pad:
        dst = jnp.concatenate([dst, jnp.full((pad,), -1, jnp.int32)])
        mtype = jnp.concatenate([mtype, jnp.zeros((pad,), jnp.int32)])
        payload = jnp.concatenate(
            [payload, jnp.zeros((pad, p), payload.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), jnp.bool_)])
    row_spec = pl.BlockSpec((bm,), lambda i: (i,))
    pay_spec = pl.BlockSpec((bm, p), lambda i: (i, 0))
    out_shape = [
        jax.ShapeDtypeStruct((n_actors,), jnp.int32),           # counts
        jax.ShapeDtypeStruct((n_actors, p), payload.dtype),     # sums
        jax.ShapeDtypeStruct((1,), jnp.int32),                  # dropped
    ]
    out_specs = [
        pl.BlockSpec((n_actors,), lambda i: (0,)),
        pl.BlockSpec((n_actors, p), lambda i: (0, 0)),
        pl.BlockSpec((1,), lambda i: (0,)),
    ]
    if with_slots:
        out_shape = [
            jax.ShapeDtypeStruct((n_actors * slots,), jnp.int32),
            jax.ShapeDtypeStruct((n_actors * slots, p), payload.dtype),
            jax.ShapeDtypeStruct((n_actors * slots,), jnp.int32),
        ] + out_shape
        out_specs = [
            pl.BlockSpec((n_actors * slots,), lambda i: (0,)),
            pl.BlockSpec((n_actors * slots, p), lambda i: (0, 0)),
            pl.BlockSpec((n_actors * slots,), lambda i: (0,)),
        ] + out_specs
    return pl.pallas_call(
        _ring_kernel(n_actors, slots, bm, with_slots),
        grid=(mp // bm,),
        in_specs=[row_spec, row_spec, pay_spec, row_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(dst, mtype, payload, valid.astype(jnp.int32))


def _merge_style_max(dst, payload, ok, n_actors: int, p: int,
                     need_max: bool):
    """The wide merge kernel's max convention (exact, shared with the
    ranked family): invalid rows contribute -inf, recipients with no
    rows at all read back 0."""
    if not need_max:
        return jnp.zeros((n_actors, p), payload.dtype)
    neg_inf = _neg_inf(payload.dtype)
    key = jnp.where(ok, dst, n_actors).astype(jnp.int32)
    maxs = jax.ops.segment_max(jnp.where(ok[:, None], payload, neg_inf),
                               key, num_segments=n_actors + 1)[:n_actors]
    return jnp.where(maxs <= neg_inf, jnp.zeros_like(maxs),
                     maxs).astype(payload.dtype)


def deliver_reduce(dst, payload, valid, n_actors: int,
                   need_max: bool) -> Delivery:
    """`deliver` semantics via the ring kernel: sums/counts accumulate
    per recipient in strict arrival order (no sort, no rank pass)."""
    m, p = payload.shape
    mtype = jnp.zeros((m,), jnp.int32)
    counts, sums, _ = _run(dst, mtype, payload, valid, n_actors, 1, False)
    ok = valid & (dst >= 0) & (dst < n_actors)
    return Delivery(sum=sums,
                    max=_merge_style_max(dst, payload, ok, n_actors, p,
                                         need_max),
                    count=counts)


def deliver_slots_ring(dst, mtype, payload, valid, n_actors: int,
                       slots: int, need_max: bool) -> SlotDelivery:
    """Bounded-mailbox `deliver_slots` semantics (spill_cap == 0) via the
    ring kernel: first `slots` messages per recipient land in arrival
    order, the rest are counted as dropped, and the aggregation consumes
    every valid row — bit-identical integer fields vs the ranked/wide
    kernels, arrival-order float sums."""
    m, p = payload.shape
    buf_t, buf_p, buf_v, counts, sums, dropped = _run(
        dst, mtype, payload, valid, n_actors, slots, True)
    ok = valid & (dst >= 0) & (dst < n_actors)
    return SlotDelivery(
        types=buf_t.reshape(n_actors, slots),
        payload=buf_p.reshape(n_actors, slots, p),
        valid=buf_v.reshape(n_actors, slots).astype(jnp.bool_),
        count=counts,
        sum=sums,
        max=_merge_style_max(dst, payload, ok, n_actors, p, need_max),
        dropped=dropped[0],
        spill_dst=jnp.full((0,), -1, jnp.int32),
        spill_type=jnp.zeros((0,), jnp.int32),
        spill_payload=jnp.zeros((0, p), payload.dtype),
        spill_valid=jnp.zeros((0,), jnp.bool_),
    )
